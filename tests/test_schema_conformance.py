"""The docs/metrics_schema.md contract is machine-enforced: every
record kind and field the obs / serve / agg layers can emit must be
documented, so the schema can't silently drift again (the check
drives the real emission paths — see scripts/check_metrics_schema.py).
"""

import os
import sys

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _import_checker():
    sys.path.insert(0, SCRIPTS)
    try:
        return __import__("check_metrics_schema")
    finally:
        sys.path.pop(0)


def test_every_emitted_kind_and_field_is_documented(capsys):
    checker = _import_checker()
    rc = checker.main()
    out = capsys.readouterr()
    assert rc == 0, f"schema drift:\n{out.err}"
    # The harness actually exercised every layer.
    assert "obs_epoch" in out.out and "obs_serve" in out.out \
        and "obs_fleet" in out.out and "obs_alert" in out.out \
        and "obs_crash" in out.out and "obs_elastic" in out.out \
        and "obs_router" in out.out


def test_thread_stalled_and_crash_reasons_emitted(tmp_path):
    """The new emission paths actually fire in the harness: a
    thread_stalled obs_alert from the watchdog, and an obs_crash from
    the prior-crash detection path."""
    checker = _import_checker()
    records = checker.collect_obs_records(str(tmp_path / "obs"))
    reasons = {r.get("reason") for r in records
               if r.get("kind") == "obs_alert"}
    assert "thread_stalled" in reasons
    crash = checker.collect_crash_records(str(tmp_path / "crash"))
    assert [r["kind"] for r in crash] == ["obs_crash"]
    assert crash[0]["report_path"].endswith(".json")
    # The fleet side pages on the ingested obs_crash.
    agg_records = checker.collect_agg_records()
    fleet_reasons = {r.get("reason") for r in agg_records
                     if r.get("kind") == "obs_alert"}
    assert "crash" in fleet_reasons
    rollups = [r for r in agg_records if r.get("kind") == "obs_fleet"]
    assert any(r.get("crashes_total") for r in rollups)


def test_elastic_and_ckpt_io_paths_emitted(tmp_path):
    """obs_elastic flows through both real emitters (agent jsonl
    append + trainer registry emit) and the ckpt_io_retry alert
    fires; the fleet side rolls elastic events up."""
    checker = _import_checker()
    records = checker.collect_elastic_records(str(tmp_path))
    events = {r.get("event") for r in records
              if r.get("kind") == "obs_elastic"}
    assert {"shrink", "quorum_failed", "recovered",
            "evict_requested"} <= events
    reasons = {r.get("reason") for r in records
               if r.get("kind") == "obs_alert"}
    assert "ckpt_io_retry" in reasons
    # Every record carries the run identity (the original run_id).
    for r in records:
        assert r.get("run_id") == "elastic-check"
    rollups = [r for r in checker.collect_agg_records()
               if r.get("kind") == "obs_fleet"]
    assert any(r.get("elastic_events_total") for r in rollups)
    assert any(r.get("elastic_last_event") == "shrink"
               for r in rollups)


def test_router_records_emitted_and_rolled_up():
    """obs_router flows through the real builders (window + every
    event flavor) and the fleet aggregator rolls routers up."""
    checker = _import_checker()
    records = checker.collect_router_records()
    kinds = [r["kind"] for r in records]
    assert kinds == ["obs_router"] * 6
    window = records[0]
    assert window["final"] and window["replicas"] == 2
    assert window["per_replica"][0]["state"] == "healthy"
    assert window["scale_decision"] == "scale_up"
    assert window["failovers_total"] == 2
    events = {r.get("event") for r in records[1:]}
    assert events == {"evict", "respawn", "scale_up", "scale_down",
                      "failover"}
    # Identity stamps every record.
    assert all(r["run_id"] == "router-check" for r in records)
    rollups = [r for r in checker.collect_agg_records()
               if r.get("kind") == "obs_fleet"]
    assert any(r.get("routers") for r in rollups)
    assert any(r.get("router_last_event") == "evict" for r in rollups)
    assert any(r.get("router_replicas") == 2 for r in rollups)


def test_trace_records_emitted_and_rolled_up():
    """obs_trace flows through the real builder (router + replica
    roles) with the trace_* instruments observed, and the fleet
    aggregator decomposes the phases and keeps slow exemplars."""
    checker = _import_checker()
    records = checker.collect_trace_records()
    assert [r["kind"] for r in records] == ["obs_trace"] * 2
    router_rec, replica_rec = records
    assert router_rec["role"] == "router" and router_rec["hop"] == 0
    assert router_rec["failover_count"] == 1
    assert router_rec["tokens_relayed"] == 12
    assert replica_rec["role"] == "replica" and replica_rec["hop"] == 2
    assert replica_rec["prefill_bucket"] == 64
    assert replica_rec["resume_offset"] == 12
    # One request, one id, across both roles.
    assert router_rec["trace_id"] == replica_rec["trace_id"]
    assert all(r["run_id"] == "trace-check" for r in records)
    rollups = [r for r in checker.collect_agg_records()
               if r.get("kind") == "obs_fleet"]
    assert any(r.get("trace_records_total") for r in rollups)
    assert any(r.get("trace_queue_p99_s") is not None for r in rollups)
    slow = next(r["trace_slow"] for r in rollups
                if r.get("trace_slow"))
    # Top-of-list exemplar is the slowest span; its trace_id is the
    # obs_timeline lookup key.
    assert slow[0]["e2e_s"] >= slow[-1]["e2e_s"]
    assert slow[0]["trace_id"] == "0123456789abcdef"


def test_checker_catches_drift():
    """The check is only worth its CI minutes if it actually fails on
    an undocumented emission."""
    checker = _import_checker()
    kinds, fields, global_fields = checker.parse_schema()
    bad = checker.undocumented(
        [{"kind": "obs_epoch", "brand_new_field": 1},
         {"kind": "obs_never_documented"}],
        kinds, fields, global_fields)
    assert ("obs_epoch", "brand_new_field") in bad
    assert ("obs_never_documented", "<kind undocumented>") in bad


def test_doc_parser_expands_brace_families():
    checker = _import_checker()
    kinds, fields, _ = checker.parse_schema()
    # `ttft_{p50,p90,p99,mean}_s` in the obs_serve table must expand.
    assert "ttft_p99_s" in fields["obs_serve"]
    assert "token_latency_mean_s" in fields["obs_serve"]
    assert "step_time_sample" in fields["obs_epoch"]
    assert "straggler_factor" in fields["obs_fleet"]
