"""The docs/metrics_schema.md contract is machine-enforced: every
record kind and field the obs / serve / agg layers can emit must be
documented, so the schema can't silently drift again (the check
drives the real emission paths — see scripts/check_metrics_schema.py).
"""

import os
import sys

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _import_checker():
    sys.path.insert(0, SCRIPTS)
    try:
        return __import__("check_metrics_schema")
    finally:
        sys.path.pop(0)


def test_every_emitted_kind_and_field_is_documented(capsys):
    checker = _import_checker()
    rc = checker.main()
    out = capsys.readouterr()
    assert rc == 0, f"schema drift:\n{out.err}"
    # The harness actually exercised every layer.
    assert "obs_epoch" in out.out and "obs_serve" in out.out \
        and "obs_fleet" in out.out and "obs_alert" in out.out


def test_checker_catches_drift():
    """The check is only worth its CI minutes if it actually fails on
    an undocumented emission."""
    checker = _import_checker()
    kinds, fields, global_fields = checker.parse_schema()
    bad = checker.undocumented(
        [{"kind": "obs_epoch", "brand_new_field": 1},
         {"kind": "obs_never_documented"}],
        kinds, fields, global_fields)
    assert ("obs_epoch", "brand_new_field") in bad
    assert ("obs_never_documented", "<kind undocumented>") in bad


def test_doc_parser_expands_brace_families():
    checker = _import_checker()
    kinds, fields, _ = checker.parse_schema()
    # `ttft_{p50,p90,p99,mean}_s` in the obs_serve table must expand.
    assert "ttft_p99_s" in fields["obs_serve"]
    assert "token_latency_mean_s" in fields["obs_serve"]
    assert "step_time_sample" in fields["obs_epoch"]
    assert "straggler_factor" in fields["obs_fleet"]
