"""Serving engine tests (tpunet/serve/): continuous batching over the
KV-slot pool on a tiny CPU LM — slot reuse, mid-flight admission token
parity with solo greedy decode, backpressure, deadlines, cancellation,
drain, and the host-side sampler's parity with filter_logits."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import ModelConfig, ServeConfig
from tpunet.models import create_model, init_variables
from tpunet.models.lm import generate
from tpunet.serve import (Engine, GenerateRequest, PromptTooLongError,
                          QueueFullError, RequestQueue, sample_token)
from tpunet.serve.scheduler import DrainingError

TINY = ModelConfig(name="lm", vit_hidden=32, vit_depth=2, vit_heads=2,
                   dropout_rate=0.0, dtype="float32", vocab_size=31,
                   max_seq_len=48)


@pytest.fixture(scope="module")
def tiny_lm():
    model = create_model(TINY)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    return model, variables


def make_engine(tiny_lm, **cfg_kw):
    model, variables = tiny_lm
    cfg_kw.setdefault("slots", 4)
    cfg_kw.setdefault("queue_max", 8)
    cfg_kw.setdefault("prefill_buckets", (8, 16))
    cfg_kw.setdefault("default_max_new_tokens", 6)
    cfg_kw.setdefault("emit_every_s", 0.0)
    return Engine(model, variables, ServeConfig(**cfg_kw))


def prompts(n, rng_seed=0, lo=2, hi=9):
    rng = np.random.default_rng(rng_seed)
    return [rng.integers(0, TINY.vocab_size,
                         size=int(rng.integers(lo, hi))).astype(np.int32)
            for _ in range(n)]


def solo_greedy(tiny_lm, prompt, n_new):
    model, variables = tiny_lm
    out = generate(model, variables, np.asarray(prompt)[None],
                   n_new=n_new)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# continuous batching correctness
# ---------------------------------------------------------------------------

def test_mid_flight_admission_matches_solo_greedy(tiny_lm):
    """The acceptance bar: 8 concurrent requests, admitted in waves so
    later ones join while earlier ones are mid-decode (2 slots force
    both queueing and slot REUSE), each return exactly the tokens solo
    greedy decode produces — per-slot masking means co-residents never
    contaminate each other."""
    eng = make_engine(tiny_lm, slots=2).start()
    try:
        ps = prompts(8)
        reqs = []
        for i, p in enumerate(ps):
            reqs.append(eng.submit(p, max_new_tokens=5))
            if i % 3 == 2:
                time.sleep(0.02)   # stagger admission mid-flight
        outs = [r.result(timeout=120) for r in reqs]
        for p, out, req in zip(ps, outs, reqs):
            assert out == solo_greedy(tiny_lm, p, 5), \
                f"request {req.id} diverged from solo decode"
            assert req.finish_reason == "length"
        # 8 requests through 2 slots: slots were reused.
        snap = eng.registry.snapshot()
        assert snap["serve_requests_completed"] == 8
        assert snap["serve_ttft_s_count"] == 8
        assert eng.active_slots() == 0
    finally:
        eng.stop()


def test_slot_reuse_across_staggered_requests(tiny_lm):
    """One slot, requests submitted strictly after the previous
    finished: every request runs in the SAME cache row and must not see
    the previous occupant's K/V (active-mask freeze + prefill
    overwrite)."""
    eng = make_engine(tiny_lm, slots=1).start()
    try:
        for seed in range(3):
            p = prompts(1, rng_seed=seed)[0]
            out = eng.submit(p, max_new_tokens=4).result(timeout=60)
            assert out == solo_greedy(tiny_lm, p, 4)
    finally:
        eng.stop()


def test_streamed_events_arrive_in_order(tiny_lm):
    eng = make_engine(tiny_lm).start()
    try:
        p = prompts(1)[0]
        req = eng.submit(p, max_new_tokens=4)
        events = list(req.events(timeout=60))
        kinds = [k for k, _ in events]
        assert kinds == ["token"] * 4 + ["done"]
        assert [v for k, v in events if k == "token"] == \
            solo_greedy(tiny_lm, p, 4)
        assert events[-1][1] == "length"
    finally:
        eng.stop()


def test_sampled_generation_deterministic_per_seed(tiny_lm):
    """Sampling is host-side with a per-request seeded generator: the
    same seed reproduces the same tokens, a different seed (almost
    surely) differs, and all tokens stay in-vocab."""
    eng = make_engine(tiny_lm).start()
    try:
        p = prompts(1)[0]
        kw = dict(max_new_tokens=8, temperature=1.0, top_k=10,
                  top_p=0.9)
        a = eng.submit(p, seed=7, **kw).result(timeout=60)
        b = eng.submit(p, seed=7, **kw).result(timeout=60)
        c = eng.submit(p, seed=8, **kw).result(timeout=60)
        assert a == b
        assert all(0 <= t < TINY.vocab_size for t in a)
        assert a != c or len(a) == 0  # vanishing collision odds
    finally:
        eng.stop()


def test_stop_token_finishes_early(tiny_lm):
    """A request whose stop_token is the model's first greedy token
    finishes with reason 'stop' after exactly one token."""
    p = prompts(1)[0]
    first = solo_greedy(tiny_lm, p, 1)[0]
    eng = make_engine(tiny_lm).start()
    try:
        req = eng.submit(p, max_new_tokens=6, stop_token=int(first))
        out = req.result(timeout=60)
        assert out == [first]
        assert req.finish_reason == "stop"
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# admission control / backpressure
# ---------------------------------------------------------------------------

def test_queue_full_rejection():
    q = RequestQueue(queue_max=2)
    q.submit(GenerateRequest([1], max_new_tokens=1))
    q.submit(GenerateRequest([1], max_new_tokens=1))
    with pytest.raises(QueueFullError):
        q.submit(GenerateRequest([1], max_new_tokens=1))
    assert q.depth() == 2


def test_engine_rejects_when_queue_bound_hit(tiny_lm):
    """Backpressure end-to-end: a stopped engine never drains its
    queue, so submits beyond queue_max must raise QueueFullError
    (frontend: 429) instead of growing the queue."""
    eng = make_engine(tiny_lm, slots=1, queue_max=2)  # NOT started
    eng.submit([1, 2], max_new_tokens=2)
    eng.submit([1, 2], max_new_tokens=2)
    with pytest.raises(QueueFullError):
        eng.submit([1, 2], max_new_tokens=2)
    snap = eng.registry.snapshot()
    assert snap["serve_requests_rejected"] == 1
    assert snap["serve_requests_total"] == 2


def test_prompt_too_long_rejected(tiny_lm):
    eng = make_engine(tiny_lm)   # buckets (8, 16), max_seq_len 48
    with pytest.raises(PromptTooLongError):
        eng.submit(np.zeros(17, np.int32))
    # fits the bucket but leaves no room to generate
    eng2 = make_engine(tiny_lm, prefill_buckets=(48,))
    with pytest.raises(PromptTooLongError):
        eng2.submit(np.zeros(48, np.int32))


def test_max_new_tokens_clamped_to_kv_length(tiny_lm):
    """A budget that would overflow the KV length is clamped, not
    rejected: prompt 40 + budget 100 against max_seq_len 48 yields
    exactly 8 tokens."""
    eng = make_engine(tiny_lm, prefill_buckets=(48,)).start()
    try:
        req = eng.submit(np.ones(40, np.int32), max_new_tokens=100)
        out = req.result(timeout=60)
        assert len(out) == 8
        assert req.finish_reason == "length"
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# deadlines / cancellation / drain / failure
# ---------------------------------------------------------------------------

def test_deadline_cancellation_frees_the_slot(tiny_lm):
    """A request with an already-tiny deadline is cancelled at an
    iteration boundary with reason 'deadline', its slot frees, and the
    NEXT request still decodes correctly in the freed slot."""
    eng = make_engine(tiny_lm, slots=1,
                      default_max_new_tokens=40).start()
    try:
        p = prompts(1)[0]
        doomed = eng.submit(p, max_new_tokens=40, deadline_s=0.001)
        doomed.result(timeout=60)
        assert doomed.finish_reason == "deadline"
        assert len(doomed.tokens) < 40
        # slot is reusable and clean
        out = eng.submit(p, max_new_tokens=4).result(timeout=60)
        assert out == solo_greedy(tiny_lm, p, 4)
        assert eng.registry.snapshot()["serve_finished_deadline"] == 1
    finally:
        eng.stop()


def test_client_cancel_frees_the_slot(tiny_lm):
    eng = make_engine(tiny_lm, slots=1,
                      default_max_new_tokens=40).start()
    try:
        p = prompts(1)[0]
        req = eng.submit(p, max_new_tokens=40)
        # wait for the first token so it is mid-decode, then cancel
        next(iter(req.events(timeout=60)))
        req.cancel()
        req.result(timeout=60)
        assert req.finish_reason == "cancelled"
        assert eng.active_slots() == 0
    finally:
        eng.stop()


def test_graceful_drain_finishes_in_flight(tiny_lm):
    """drain(): already-admitted AND already-queued requests finish
    with their exact tokens; submits during/after the drain are
    rejected."""
    eng = make_engine(tiny_lm, slots=1).start()
    try:
        ps = prompts(3)
        reqs = [eng.submit(p, max_new_tokens=4) for p in ps]
        assert eng.drain(timeout=120.0)
        for p, req in zip(ps, reqs):
            assert req.finish_reason == "length"
            assert list(req.tokens) == solo_greedy(tiny_lm, p, 4)
        with pytest.raises(DrainingError):
            eng.submit(ps[0])
    finally:
        eng.stop()


def test_drain_timeout_finishes_survivors_with_drain_reason(tiny_lm):
    """When the drain budget expires, BOTH the in-flight request and
    the still-queued one finish with reason 'drain' (not 'cancelled' —
    the shutdown took them, not a client) and the counter ticks for
    each."""
    eng = make_engine(tiny_lm, slots=1, default_max_new_tokens=500,
                      max_new_tokens_cap=2048)
    real_step = eng._step

    def slow_step(*a, **k):
        time.sleep(0.05)
        return real_step(*a, **k)

    eng._step = slow_step
    eng.start()
    inflight = eng.submit(prompts(1)[0], max_new_tokens=40)
    queued = eng.submit(prompts(1, rng_seed=1)[0], max_new_tokens=40)
    next(iter(inflight.events(timeout=60)))   # mid-decode for sure
    assert not eng.drain(timeout=0.05)        # budget too small
    inflight.result(timeout=30)
    queued.result(timeout=30)
    assert inflight.finish_reason == "drain"
    assert queued.finish_reason == "drain"
    assert eng.registry.snapshot()["serve_finished_drain"] == 2
    assert eng.active_slots() == 0


def test_stop_unblocks_waiting_clients(tiny_lm):
    """stop() must FINISH in-flight requests, not just cancel them — a
    client blocked in result() unblocks immediately instead of at its
    own timeout."""
    eng = make_engine(tiny_lm, slots=1, default_max_new_tokens=500,
                      max_new_tokens_cap=2048)
    real_step = eng._step

    def slow_step(*a, **k):
        time.sleep(0.05)
        return real_step(*a, **k)

    eng._step = slow_step
    eng.start()
    req = eng.submit(prompts(1)[0], max_new_tokens=40)
    next(iter(req.events(timeout=60)))        # mid-decode
    t0 = time.perf_counter()
    eng.stop()
    req.result(timeout=5)                     # must not need 5s
    assert time.perf_counter() - t0 < 15
    assert req.done and req.finish_reason == "cancelled"


def test_drain_never_started_engine_returns_fast(tiny_lm):
    """drain() on an engine whose thread never ran must not sit out
    the whole budget — there is no loop to finish the work."""
    eng = make_engine(tiny_lm, slots=1)       # NOT started
    queued = eng.submit(prompts(1)[0], max_new_tokens=4)
    t0 = time.perf_counter()
    assert not eng.drain(timeout=30.0)        # work was left behind
    assert time.perf_counter() - t0 < 5
    assert queued.done and queued.finish_reason == "drain"
    assert eng.registry.snapshot()["serve_finished_drain"] == 1
    # and an idle never-started engine drains clean
    eng2 = make_engine(tiny_lm, slots=1)
    assert eng2.drain(timeout=30.0)


def test_queued_cancel_and_deadline_are_accounted(tiny_lm):
    """Requests finished while still QUEUED (cancelled / expired
    before reaching a slot) must tick the same serve_finished_*
    counters as slot-finishes: requests_total reconciles with
    rejected + finished."""
    eng = make_engine(tiny_lm, slots=1, default_max_new_tokens=500,
                      max_new_tokens_cap=2048)
    real_step = eng._step

    def slow_step(*a, **k):
        time.sleep(0.05)
        return real_step(*a, **k)

    eng._step = slow_step
    eng.start()
    try:
        hog = eng.submit(prompts(1)[0], max_new_tokens=40)
        victim = eng.submit(prompts(1, rng_seed=1)[0],
                            max_new_tokens=4)
        expired = eng.submit(prompts(1, rng_seed=2)[0],
                             max_new_tokens=4, deadline_s=0.01)
        victim.cancel()
        # queued finishes are detected when the hog frees the slot
        victim.result(timeout=60)
        expired.result(timeout=60)
        hog.result(timeout=60)
        assert victim.finish_reason == "cancelled"
        assert expired.finish_reason == "deadline"
        assert hog.finish_reason == "length"
        snap = eng.registry.snapshot()
        assert snap["serve_finished_cancelled"] == 1
        assert snap["serve_finished_deadline"] == 1
        assert snap["serve_finished_length"] == 1
        assert snap["serve_requests_total"] == 3
        # reconciliation: total == rejected + sum(finished_*)
        finished = sum(v for k, v in snap.items()
                       if k.startswith("serve_finished_"))
        assert finished + snap.get("serve_requests_rejected", 0) == 3
    finally:
        eng.stop()


def test_engine_failure_fails_requests_and_health(tiny_lm):
    """An engine-thread crash must fail in-flight and queued requests
    fast (finish_reason 'error') and flip healthy False — the /healthz
    503 path — instead of hanging clients."""
    eng = make_engine(tiny_lm, slots=1, default_max_new_tokens=40)

    def boom(*a, **k):
        raise RuntimeError("device fell over")

    eng._step = boom
    eng.start()
    try:
        # the submit may lose the race with the engine dying
        req = eng.submit(prompts(1)[0])
    except DrainingError:
        req = None
    if req is not None:
        req.result(timeout=60)
        assert req.finish_reason == "error"
        assert "device fell over" in (req.error or "")
    deadline = time.perf_counter() + 30
    while eng.healthy and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert not eng.healthy
    assert "device fell over" in (eng.error or "")
    with pytest.raises(DrainingError):
        eng.submit(prompts(1)[0])


# ---------------------------------------------------------------------------
# host-side sampler parity
# ---------------------------------------------------------------------------

def test_sample_token_greedy_is_argmax():
    req = GenerateRequest([1], max_new_tokens=1, temperature=0.0)
    logits = np.asarray([0.1, 3.0, -1.0, 2.9])
    assert sample_token(logits, req) == 1


def test_sample_token_filters_match_filter_logits():
    """The host sampler's support (post top-k/top-p) must equal
    filter_logits' support — the serving path may not admit tokens the
    training-side sampler would have filtered out."""
    from tpunet.models.lm import filter_logits
    rng = np.random.default_rng(3)
    for _ in range(10):
        logits = rng.normal(size=16).astype(np.float32) * 2
        for top_k, top_p in ((3, 0.0), (0, 0.7), (5, 0.8)):
            ref = np.asarray(filter_logits(
                jnp.asarray(logits)[None] / 0.8, top_k=top_k,
                top_p=top_p))[0]
            allowed = set(np.nonzero(np.isfinite(ref))[0].tolist())
            seen = set()
            for seed in range(40):
                req = GenerateRequest([1], max_new_tokens=1,
                                      temperature=0.8, top_k=top_k,
                                      top_p=top_p, seed=seed)
                seen.add(sample_token(logits, req))
            assert seen <= allowed, (top_k, top_p, seen - allowed)
            # the argmax survives every filter and must be reachable
            assert int(np.argmax(logits)) in allowed
