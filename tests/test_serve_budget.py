"""Absolute tokens/s/slot serving floor (scripts/check_serve_budget.py
+ docs/serve_budget.json + bench_serve.py --enforce-budget) — the
bytes-budget mechanism pointed at serving capacity. The >=2x relative
regression test lives in tests/test_serve_http.py; this floor catches
the sequential baseline and the engine slowing down TOGETHER."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from check_serve_budget import (check_record, load_budget,  # noqa: E402
                                tokens_per_s_per_slot)


def _record(tpss=None, device="cpu", slots=8, levels=None):
    rec = {"device": device, "slots": slots, "levels": levels or []}
    if tpss is not None:
        rec["tokens_per_s_per_slot"] = tpss
    return rec


def _budget(floor, tol=50):
    return {"tolerance_pct": tol,
            "budgets": {"cpu": {"tokens_per_s_per_slot": floor}}}


def test_throughput_above_floor_passes():
    ok, msgs = check_record(_record(tpss=80.0), _budget(100.0))
    assert ok and any("OK" in m for m in msgs)


def test_throughput_below_floor_fails():
    ok, msgs = check_record(_record(tpss=49.0), _budget(100.0))
    assert not ok and any("REGRESSION" in m for m in msgs)


def test_unknown_device_passes_with_note():
    ok, msgs = check_record(_record(tpss=1.0, device="TPU v5 lite"),
                            _budget(100.0))
    assert ok and any("no serve budget" in m for m in msgs)


def test_missing_measurement_skips_with_note():
    ok, msgs = check_record(_record(), _budget(100.0))
    assert ok and any("skipping" in m for m in msgs)


def test_tokens_per_s_per_slot_derived_from_levels():
    """Older artifacts without the field still gate: peak level over
    slots. An errored level still counts when tokens flowed (the rate
    is a lower bound on capacity); a level that served nothing is no
    measurement."""
    rec = _record(slots=4, levels=[
        {"concurrency": 1, "tokens_per_s": 100.0, "errors": []},
        {"concurrency": 4, "tokens_per_s": 400.0, "errors": []},
        {"concurrency": 8, "tokens_per_s": 900.0, "errors": ["boom"]}])
    assert tokens_per_s_per_slot(rec) == 225.0
    rec["levels"][2]["tokens_per_s"] = 0.0      # errored, served nothing
    assert tokens_per_s_per_slot(rec) == 100.0
    rec["tokens_per_s_per_slot"] = 55.5  # explicit field wins
    assert tokens_per_s_per_slot(rec) == 55.5


def test_all_levels_errored_fails_the_gate():
    """A completely broken engine (every level errored -> no usable
    rate) must FAIL, not pass as 'no data' — it is the worst
    regression the floor exists to catch."""
    rec = _record(slots=8, levels=[
        {"concurrency": 1, "tokens_per_s": 0.0, "total_tokens": 0,
         "errors": ["Timeout"]},
        {"concurrency": 4, "tokens_per_s": 0.0, "total_tokens": 0,
         "errors": ["Timeout"]}])
    ok, msgs = check_record(rec, _budget(100.0))
    assert not ok and any("REGRESSION" in m for m in msgs)


def test_flaky_errors_with_tokens_flowing_is_not_broken():
    """One flaky client error per level while tokens still flow is NOT
    'serving is broken' — the served rates are real measurements and
    gate normally (the contended CI host produces exactly this
    shape)."""
    rec = _record(slots=8, levels=[
        {"concurrency": 1, "tokens_per_s": 500.0, "total_tokens": 960,
         "errors": ["client 0: Timeout"]},
        {"concurrency": 4, "tokens_per_s": 900.0, "total_tokens": 1800,
         "errors": ["client 2: Timeout"]}])
    assert tokens_per_s_per_slot(rec) == 112.5
    ok, msgs = check_record(rec, _budget(100.0))
    assert ok and any("OK" in m for m in msgs)


def test_error_at_peak_level_does_not_bias_the_floor():
    """A flaky error at the highest offered load must not drop that
    level's rate from the measurement: the lower level's rate over the
    FULL slot count would read as a false regression on a healthy
    engine."""
    rec = _record(slots=8, levels=[
        {"concurrency": 4, "tokens_per_s": 500.0, "total_tokens": 960,
         "errors": []},
        {"concurrency": 8, "tokens_per_s": 900.0, "total_tokens": 1800,
         "errors": ["client 2: Timeout"]}])
    assert tokens_per_s_per_slot(rec) == 112.5
    ok, msgs = check_record(rec, _budget(200.0))     # limit = 100.0
    assert ok, msgs    # 500/8 = 62.5 alone would have failed


def test_checked_in_serve_budget_file_is_valid():
    budget = load_budget()
    assert budget["tolerance_pct"] > 0
    cpu = budget["budgets"]["cpu"]
    assert cpu["tokens_per_s_per_slot"] > 0
    assert cpu["kv_bytes_per_token"] > 0
    # The floor must be enforceable against a record shaped like
    # bench_serve's output.
    ok, msgs = check_record(
        _record(tpss=cpu["tokens_per_s_per_slot"]), budget)
    assert ok, msgs


def _kv_budget(floor=100.0, ceiling=1024.0, tol=50):
    return {"tolerance_pct": tol,
            "budgets": {"cpu": {"tokens_per_s_per_slot": floor,
                                "kv_bytes_per_token": ceiling}}}


def test_kv_bytes_within_ceiling_passes():
    rec = _record(tpss=200.0)
    rec["kv_bytes_per_token"] = 1024.0
    ok, msgs = check_record(rec, _kv_budget())
    assert ok and any("kv_bytes_per_token" in m and "OK" in m
                      for m in msgs)


def test_kv_bytes_over_ceiling_fails_even_with_fast_tokens():
    """The capacity ceiling is independent of the throughput floor: a
    pool that silently doubled its per-token bytes fails the gate even
    while tokens/s still clears the floor (on a tiny CPU model the
    bloat costs no wall clock — that is exactly why it needs its own
    gate)."""
    rec = _record(tpss=1e6)
    rec["kv_bytes_per_token"] = 1024.0 * 1.6   # past +50% tolerance
    ok, msgs = check_record(rec, _kv_budget())
    assert not ok
    assert any("kv_bytes_per_token" in m and "REGRESSION" in m
               for m in msgs)
    assert any("tokens_per_s_per_slot" in m and "OK" in m
               for m in msgs)


def test_kv_bytes_missing_from_old_record_skips_with_note():
    ok, msgs = check_record(_record(tpss=200.0), _kv_budget())
    assert ok and any("no kv_bytes_per_token" in m for m in msgs)


def test_kv_ceiling_absent_from_budget_is_silent():
    rec = _record(tpss=200.0)
    rec["kv_bytes_per_token"] = 9e9
    ok, msgs = check_record(rec, _budget(100.0))
    assert ok and not any("kv_bytes" in m for m in msgs)


def test_budget_cli_parses_artifact(tmp_path, capsys):
    from check_serve_budget import main as serve_budget_main
    art = tmp_path / "serve.json"
    art.write_text(json.dumps(_record(tpss=1e9)))
    assert serve_budget_main([str(art)]) == 0
    art.write_text(json.dumps(_record(tpss=0.001)))
    assert serve_budget_main([str(art)]) == 1


def test_budget_cli_flag_order_and_missing_value(tmp_path, capsys):
    """--budget may precede or follow the record path; a trailing
    --budget with no value is a usage error, not a crash."""
    from check_serve_budget import main as serve_budget_main
    art = tmp_path / "serve.json"
    art.write_text(json.dumps(_record(tpss=1e9)))
    bud = tmp_path / "budget.json"
    bud.write_text(json.dumps(_budget(100.0)))
    assert serve_budget_main(["--budget", str(bud), str(art)]) == 0
    assert serve_budget_main([str(art), "--budget", str(bud)]) == 0
    assert serve_budget_main([str(art), "--budget"]) == 2
    assert serve_budget_main(["--budget", str(bud)]) == 2  # no record


def test_budget_cli_rejects_unknown_flags(tmp_path, capsys):
    """A typo'd flag must be a loud usage error (exit 2): silently
    treating its value as the record path would gate the wrong file
    and exit 0 — a false pass in CI."""
    from check_serve_budget import main as serve_budget_main
    bud = tmp_path / "budget.json"
    bud.write_text(json.dumps(_budget(100.0)))
    art = tmp_path / "serve.json"
    art.write_text(json.dumps(_record(tpss=0.001)))   # would gate FAIL
    assert serve_budget_main(["--bugdet", str(bud), str(art)]) == 2
    # Same posture for extra positionals (a shell glob would gate only
    # the first file and let a regression in the others pass).
    art2 = tmp_path / "serve2.json"
    art2.write_text(json.dumps(_record(tpss=1e9)))
    assert serve_budget_main([str(art2), str(art)]) == 2


def test_budget_cli_parses_piped_pretty_stream(tmp_path, capsys,
                                               monkeypatch):
    """`bench_serve | check_serve_budget.py -`: bench_serve emits
    indent=1 pretty JSON, and a note/warning line may precede it — the
    stream fallback must find the record, not an inner nested brace."""
    import io
    from check_serve_budget import main as serve_budget_main
    raw = ("# warming up\n" +
           json.dumps(_record(tpss=1e9, levels=[
               {"concurrency": 1, "tokens_per_s": 8e9, "errors": []}]),
               indent=1) + "\n")
    monkeypatch.setattr("sys.stdin", io.StringIO(raw))
    assert serve_budget_main(["-"]) == 0
    # Trailing non-JSON output after the record (2>&1 pipes interleave
    # the gate's own verdict lines) must not make an inner nested dict
    # win: a REGRESSING record must still fail, not skip with
    # 'no serve budget'.
    raw = ("# warming up\n" +
           json.dumps(_record(tpss=0.001, levels=[
               {"concurrency": 1, "tokens_per_s": 5.0, "errors": []}]),
               indent=1) + "\ndone\n")
    monkeypatch.setattr("sys.stdin", io.StringIO(raw))
    assert serve_budget_main(["-"]) == 1


@pytest.mark.slow
def test_bench_serve_enforce_budget_end_to_end():
    """bench_serve.py --enforce-budget on this host: record carries
    tokens_per_s_per_slot and the gate passes against the checked-in
    floor (a >50% drop on an idle host is a real regression)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_serve.py"),
         "--enforce-budget"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=800)
    assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    rec = json.loads(out.stdout)
    assert rec["tokens_per_s_per_slot"] > 0
    assert "tokens_per_s_per_slot" in out.stderr  # the gate's verdict line


# ---------------------------------------------------------------------------
# cold-start gate (bench_serve.py --cold-start records)
# ---------------------------------------------------------------------------


def _cold_record(cold, aot, device="cpu"):
    return {"mode": "cold_start", "device": device,
            "cold_start_to_first_token_s": {
                "cold": cold, "persistent": cold, "aot": aot}}


def _cold_budget(ceiling, tol=50):
    return {"tolerance_pct": tol,
            "budgets": {"cpu": {
                "tokens_per_s_per_slot": 100.0,
                "cold_start_to_first_token_s_aot": ceiling}}}


def test_cold_start_under_ceiling_and_beating_cold_passes():
    ok, msgs = check_record(_cold_record(cold=5.0, aot=0.5),
                            _cold_budget(1.0))
    assert ok and any("OK" in m for m in msgs)


def test_cold_start_aot_not_beating_cold_fails():
    """The unconditional invariant: an AOT boot slower than a cold
    boot means the store is dead weight — fail even under the
    ceiling."""
    ok, msgs = check_record(_cold_record(cold=0.4, aot=0.5),
                            _cold_budget(1.0))
    assert not ok
    assert any("did not beat cold" in m for m in msgs)


def test_cold_start_over_ceiling_fails():
    ok, msgs = check_record(_cold_record(cold=60.0, aot=2.0),
                            _cold_budget(1.0))
    assert not ok and any("REGRESSION" in m for m in msgs)


def test_cold_start_no_ceiling_still_checks_aot_beats_cold():
    budget = {"tolerance_pct": 50,
              "budgets": {"cpu": {"tokens_per_s_per_slot": 100.0}}}
    ok, msgs = check_record(_cold_record(cold=5.0, aot=0.5), budget)
    assert ok and any("aot-beats-cold only" in m for m in msgs)
    ok, _ = check_record(_cold_record(cold=0.3, aot=0.5), budget)
    assert not ok


def test_cold_start_missing_measurement_skips():
    ok, msgs = check_record(
        {"mode": "cold_start", "device": "cpu",
         "cold_start_to_first_token_s": {}}, _cold_budget(1.0))
    assert ok and any("skipping" in m for m in msgs)


def test_checked_in_budget_has_cold_start_ceiling():
    """docs/serve_budget.json carries the PR-11 cold-start ceiling the
    --cold-start bench is gated on."""
    budget = load_budget()
    entry = budget["budgets"]["cpu"]
    assert entry["cold_start_to_first_token_s_aot"] > 0


@pytest.mark.slow
def test_bench_serve_cold_start_end_to_end():
    """bench_serve.py --cold-start --enforce-budget on this host: the
    AOT boot beats the cold boot and stays under the ceiling."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_serve.py"),
         "--cold-start", "--enforce-budget"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=800)
    assert out.returncode == 0, (out.returncode, out.stderr[-2000:])
    rec = json.loads(out.stdout)
    times = rec["cold_start_to_first_token_s"]
    assert times["aot"] < times["cold"]
