"""HTTP frontend tests: end-to-end smoke against an ephemeral port
(generate, streaming ndjson, classify micro-batching, healthz/metrics,
backpressure status codes, obs_serve records in metrics.jsonl) and the
slow-marked continuous-vs-sequential throughput regression."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from tpunet.config import DataConfig, ModelConfig, ServeConfig
from tpunet.models import create_model, init_variables
from tpunet.serve import ClassifyBatcher, Engine, ServeServer

TINY = ModelConfig(name="lm", vit_hidden=32, vit_depth=2, vit_heads=2,
                   dropout_rate=0.0, dtype="float32", vocab_size=256,
                   max_seq_len=64)


def make_server(tmp_path=None, *, with_classifier=False, **cfg_kw):
    cfg_kw.setdefault("slots", 2)
    cfg_kw.setdefault("queue_max", 4)
    cfg_kw.setdefault("prefill_buckets", (16,))
    cfg_kw.setdefault("default_max_new_tokens", 8)
    cfg_kw.setdefault("emit_every_s", 0.0)
    cfg = ServeConfig(**cfg_kw)
    model = create_model(TINY)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    engine = Engine(model, variables, cfg)
    metrics_logger = None
    if tmp_path is not None:
        from tpunet.obs.registry import JsonlSink
        from tpunet.utils.logging import MetricsLogger
        metrics_logger = MetricsLogger(str(tmp_path))
        engine.registry.add_sink(JsonlSink(metrics_logger))
    batcher = None
    if with_classifier:
        from tpunet.infer.predict import Predictor
        pred = Predictor(
            model_cfg=ModelConfig(dtype="float32", width_mult=0.5,
                                  dropout_rate=0.0),
            data_cfg=DataConfig(image_size=32))
        batcher = ClassifyBatcher(pred, batch_max=4, window_ms=5.0,
                                  registry=engine.registry)
    return ServeServer(engine, classify_batcher=batcher, port=0,
                       metrics_logger=metrics_logger).start()


def post(base, path, obj, timeout=120):
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get(base, path, timeout=30):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_end_to_end(tmp_path):
    """One server, the whole surface: healthz, token + text generate,
    parity with solo decode, streaming, classify 503 (none configured),
    bad-request 400s, metrics, drain -> healthz 503 + obs_serve record
    in metrics.jsonl."""
    srv = make_server(tmp_path)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, health = get(base, "/healthz")
        assert code == 200 and health["status"] == "ok"
        assert health["slots"] == 2

        code, out = post(base, "/v1/generate",
                         {"prompt": "hello", "max_new_tokens": 5})
        assert code == 200
        assert len(out["tokens"]) == 5
        assert out["finish_reason"] == "length"
        assert isinstance(out["text"], str)
        assert out["ttft_ms"] > 0 and out["e2e_ms"] >= out["ttft_ms"]

        # token-id prompts hit the same engine path
        code, out2 = post(base, "/v1/generate",
                          {"tokens": [104, 101, 108, 108, 111],
                           "max_new_tokens": 5})
        assert code == 200 and out2["tokens"] == out["tokens"]

        # streaming: ndjson token events, then the done frame
        req = urllib.request.Request(
            base + "/v1/generate",
            json.dumps({"prompt": "hi", "max_new_tokens": 4,
                        "stream": True}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
            assert "ndjson" in r.headers["Content-Type"]
            lines = [json.loads(line) for line in
                     r.read().decode().strip().splitlines()]
        assert len(lines) == 5
        assert all("token" in ev for ev in lines[:4])
        assert lines[-1] == {**lines[-1], "done": True,
                             "finish_reason": "length", "n_tokens": 4}

        # error surface
        assert post(base, "/v1/generate", {})[0] == 400
        assert post(base, "/v1/generate", {"tokens": []})[0] == 400
        assert post(base, "/v1/generate",
                    {"tokens": [999]})[0] == 400     # out of vocab
        assert post(base, "/v1/generate",
                    {"tokens": [1] * 40})[0] == 413  # > largest bucket
        assert post(base, "/v1/classify", {"image": [[0]]})[0] == 503
        assert get(base, "/nope")[0] == 404

        code, snap = get(base, "/metrics")
        assert code == 200
        assert snap["serve_requests_total"] >= 3
        assert snap["serve_tokens_total"] >= 14
        assert "serve_ttft_s_p50" in snap

    finally:
        srv.drain(timeout=30.0)
    # after drain the listener is down; the obs_serve record flushed
    recs = [json.loads(line) for line in
            (tmp_path / "metrics.jsonl").read_text().splitlines()]
    serve_recs = [r for r in recs if r.get("kind") == "obs_serve"]
    assert serve_recs, "drain must flush a final obs_serve record"
    final = serve_recs[-1]
    assert final["final"] and final["requests_total"] >= 3
    assert final["queue_depth"] == 0 and final["active_slots"] == 0


def _eight_way_outputs(srv):
    """8 concurrent POSTs through 2 slots; returns the token lists."""
    base = f"http://127.0.0.1:{srv.port}"
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, 256, size=int(n)).astype(int).tolist()
               for n in rng.integers(2, 10, size=8)]
    results = [None] * 8

    def worker(i):
        results[i] = post(base, "/v1/generate",
                          {"tokens": prompts[i], "max_new_tokens": 6})

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    outs = []
    for res in results:
        assert res is not None, "worker timed out"
        code, out = res
        assert code == 200, out
        outs.append(out["tokens"])
    return prompts, outs


def test_http_concurrent_parity_eight_requests():
    """The ISSUE acceptance check: 8 concurrent POSTs through 2 slots
    (paged KV + device sampling, the default path) return
    token-identical output to solo greedy decode."""
    from tpunet.models.lm import generate

    srv = make_server(queue_max=8)
    model = srv.engine.model
    variables = srv.engine.variables
    try:
        assert srv.engine._paged_kv is not None  # default = paged
        prompts, outs = _eight_way_outputs(srv)
        for p, out in zip(prompts, outs):
            solo = np.asarray(generate(
                model, variables,
                np.asarray(p, np.int32)[None], n_new=6))[0, len(p):]
            assert out == solo.tolist()
    finally:
        srv.drain(timeout=10.0)


def test_http_paged_vs_dense_parity_eight_requests():
    """Paged-vs-dense parity through HTTP at 8-way concurrency: the
    dense fallback server (--no-paged-kv --no-device-sampling, the
    PR-11 path) answers the same 8 concurrent requests with the same
    tokens the paged+device-sampled default produces."""
    srv_paged = make_server(queue_max=8)
    srv_dense = make_server(queue_max=8, paged_kv=False,
                            device_sampling=False)
    try:
        _, outs_paged = _eight_way_outputs(srv_paged)
        _, outs_dense = _eight_way_outputs(srv_dense)
        assert outs_paged == outs_dense
    finally:
        srv_paged.drain(timeout=10.0)
        srv_dense.drain(timeout=10.0)


def test_http_response_reports_effective_budget():
    """The clamp satellite over the wire: a budget clamped at
    admission (operator cap / KV length) surfaces as max_new_tokens +
    requested_max_new_tokens in the response metadata instead of a
    silently short token list."""
    srv = make_server(max_new_tokens_cap=4)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, out = post(base, "/v1/generate",
                         {"prompt": "hi", "max_new_tokens": 50})
        assert code == 200
        assert len(out["tokens"]) == 4
        assert out["max_new_tokens"] == 4
        assert out["requested_max_new_tokens"] == 50
        # an unclamped request reports its effective budget only
        code, out2 = post(base, "/v1/generate",
                          {"prompt": "hi", "max_new_tokens": 3})
        assert code == 200
        assert out2["max_new_tokens"] == 3
        assert "requested_max_new_tokens" not in out2
    finally:
        srv.drain(timeout=10.0)


def test_http_queue_full_returns_429():
    """Backpressure over the wire: slots busy + queue at bound -> 429
    queue_full, and the rejected counter ticks."""
    srv = make_server(slots=1, queue_max=1,
                      default_max_new_tokens=60)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        slow = []

        def bg():
            slow.append(post(base, "/v1/generate",
                             {"prompt": "a", "max_new_tokens": 60}))

        threads = [threading.Thread(target=bg) for _ in range(3)]
        for t in threads:
            t.start()
            time.sleep(0.15)   # let each land: slot, queue, reject
        got_429 = None
        deadline = time.perf_counter() + 30
        while got_429 is None and time.perf_counter() < deadline:
            code, out = post(base, "/v1/generate",
                             {"prompt": "b", "max_new_tokens": 60})
            if code == 429:
                got_429 = out
            else:
                time.sleep(0.05)
        assert got_429 is not None, "never saw a 429 under overload"
        assert got_429["error"] == "queue_full"
        for t in threads:
            t.join(timeout=300)
        code, snap = get(base, "/metrics")
        assert snap["serve_requests_rejected"] >= 1
    finally:
        srv.drain(timeout=10.0)


def test_http_classify_micro_batched():
    """Concurrent /v1/classify requests coalesce into shared batched
    forwards and return the Predictor's exact probabilities."""
    srv = make_server(with_classifier=True)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        rng = np.random.default_rng(0)
        imgs = [rng.integers(0, 256, (32, 32, 3)).astype(int).tolist()
                for _ in range(6)]
        results = [None] * 6

        def worker(i):
            results[i] = post(base, "/v1/classify",
                              {"image": imgs[i], "topk": 3})

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        pred = srv.classify.predictor
        for img, res in zip(imgs, results):
            assert res is not None
            code, out = res
            assert code == 200, out
            assert len(out["topk"]) == 3
            ref = pred.predict_probs(np.asarray(img, np.uint8))
            got = np.asarray([out["probs"][n]
                              for n in pred.class_names])
            np.testing.assert_allclose(got, ref, atol=2e-5)
        code, snap = get(base, "/metrics")
        assert snap["serve_classify_requests_total"] == 6
        # coalescing happened: fewer batches than requests
        assert snap["serve_classify_batches_total"] < 6
    finally:
        srv.drain(timeout=10.0)


def test_healthz_unhealthy_after_engine_crash():
    srv = make_server()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        def boom(*a, **k):
            raise RuntimeError("step exploded")

        srv.engine._step = boom
        try:
            post(base, "/v1/generate", {"prompt": "x"}, timeout=60)
        except Exception:
            pass
        deadline = time.perf_counter() + 30
        code = 200
        while code == 200 and time.perf_counter() < deadline:
            code, health = get(base, "/healthz")
            time.sleep(0.05)
        assert code == 503
        assert health["status"] == "unhealthy"
        assert "step exploded" in health["error"]
    finally:
        srv.drain(timeout=10.0)


def test_drain_under_load_finishes_stream_and_503s_new_requests():
    """Satellite: an in-flight ndjson stream COMPLETES (finish_reason
    length, not drain) while drain() runs, and requests arriving
    during the drain get 503 (Retry-After semantics pinned
    deterministically in the sibling test below)."""
    big = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                      vit_heads=2, dropout_rate=0.0, dtype="float32",
                      vocab_size=256, max_seq_len=512)
    cfg = ServeConfig(slots=1, queue_max=4, prefill_buckets=(16,),
                      default_max_new_tokens=300, emit_every_s=0.0,
                      drain_timeout_s=60.0)
    model = create_model(big)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    srv = ServeServer(Engine(model, variables, cfg), port=0).start()
    base = f"http://127.0.0.1:{srv.port}"
    req = urllib.request.Request(
        base + "/v1/generate",
        json.dumps({"prompt": "hi", "max_new_tokens": 300,
                    "stream": True}).encode(),
        {"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req, timeout=120)
    first = json.loads(resp.readline())
    assert "token" in first

    drained = []
    t = threading.Thread(target=lambda: drained.append(
        srv.drain(timeout=60.0)))
    t.start()
    # While draining: new admissions are rejected 503, never queued.
    saw_503 = False
    deadline = time.perf_counter() + 30
    while not saw_503 and time.perf_counter() < deadline:
        try:
            code, out = post(base, "/v1/generate",
                             {"prompt": "x", "max_new_tokens": 2},
                             timeout=30)
        except (urllib.error.URLError, OSError):
            break              # listener already closed: drain done
        if code == 503:
            saw_503 = True
            assert out["error"] == "draining"
        else:
            time.sleep(0.005)
    # The in-flight stream ran to completion through the drain.
    lines = [json.loads(line) for line in resp]
    resp.close()
    done = ([first] + lines)[-1]
    assert done.get("done") and done["finish_reason"] == "length", done
    assert done["n_tokens"] == 300
    t.join(timeout=90)
    assert drained and drained[0], "drain did not finish clean"
    assert saw_503, "never observed a mid-drain 503 rejection"


def test_draining_503_carries_retry_after_header():
    """The Retry-After contract, deterministically: queue closed =>
    both /healthz and /v1/generate answer 503 with Retry-After."""
    srv = make_server(drain_timeout_s=45.0)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        srv.engine._draining.set()
        srv.engine.queue.close()
        try:
            urllib.request.urlopen(base + "/healthz", timeout=10)
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "draining"
            assert int(e.headers["Retry-After"]) == 45
        try:
            req = urllib.request.Request(
                base + "/v1/generate",
                json.dumps({"prompt": "x"}).encode(),
                {"Content-Type": "application/json"})
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected 503"
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert int(e.headers["Retry-After"]) == 45
    finally:
        srv.drain(timeout=10.0)


def test_healthz_carries_run_id():
    """The router matches webhook pages to replicas by the run_id the
    health probe returns."""
    srv = make_server()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, health = get(base, "/healthz")
        assert code == 200
        assert health["run_id"].startswith("serve-")
    finally:
        srv.drain(timeout=10.0)


def test_engine_aot_store_roundtrip(tmp_path):
    """AOT warm-start parity: a second engine boot deserializes every
    program ('loaded') and produces token-identical greedy output."""
    from tpunet.serve.engine import build_aot_store

    cfg = ServeConfig(slots=2, queue_max=4, prefill_buckets=(16,),
                      default_max_new_tokens=8, emit_every_s=0.0)
    model = create_model(TINY)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    store = build_aot_store(str(tmp_path), TINY, cfg)
    prompt = np.arange(5, dtype=np.int32)

    eng = Engine(model, variables, cfg, aot_store=store).start()
    try:
        toks1 = eng.submit(prompt, max_new_tokens=5).result(timeout=120)
    finally:
        eng.stop()
    assert all(v.startswith("compiled")
               for v in eng.aot_status.values())
    assert any(p.name.endswith(".aotx") for p in tmp_path.iterdir())

    eng2 = Engine(model, variables, cfg, aot_store=store).start()
    try:
        toks2 = eng2.submit(prompt, max_new_tokens=5).result(timeout=120)
    finally:
        eng2.stop()
    assert eng2.aot_status == {"w1": "loaded", "w16": "loaded"}
    assert toks2 == toks1

    # jit fallback (no store) agrees too.
    eng3 = Engine(model, variables, cfg).start()
    try:
        toks3 = eng3.submit(prompt, max_new_tokens=5).result(timeout=120)
    finally:
        eng3.stop()
    assert toks3 == toks1
    # A different pool shape is a clean store MISS, never a wrong
    # program.
    cfg4 = ServeConfig(slots=3, queue_max=4, prefill_buckets=(16,),
                       default_max_new_tokens=8, emit_every_s=0.0)
    store4 = build_aot_store(str(tmp_path), TINY, cfg4)
    eng4 = Engine(model, variables, cfg4, aot_store=store4).start()
    try:
        eng4.submit(prompt, max_new_tokens=2).result(timeout=120)
    finally:
        eng4.stop()
    assert all(v.startswith("compiled")
               for v in eng4.aot_status.values())


def test_serve_cli_argparser_roundtrip():
    """The module entry point's arg surface builds a coherent config
    (no server start — just the parse + bucket plumbing)."""
    from tpunet.serve.__main__ import build_argparser

    args = build_argparser().parse_args(
        ["--checkpoint-dir", "ck", "--slots", "3", "--queue-max", "5",
         "--prefill-buckets", "8,32", "--port", "0",
         "--vit-hidden", "32", "--vit-depth", "2", "--vit-heads", "2",
         "--max-seq-len", "64"])
    assert args.slots == 3 and args.queue_max == 5
    assert args.prefill_buckets == "8,32"
    assert args.vit_hidden == 32


@pytest.mark.slow
def test_continuous_batching_beats_sequential():
    """The regression the subsystem exists for: at concurrency >= 4,
    continuous batching through the slot pool must deliver >= 2x the
    total tokens/s of one-request-at-a-time generation of the same
    work (ISSUE acceptance bar; scripts/bench_serve.py measures the
    same thing off-CI)."""
    from tpunet.models.lm import generate

    model = create_model(TINY)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    n_req, n_new = 6, 24
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, 256, size=6).astype(np.int32)
               for _ in range(n_req)]

    # sequential: one compiled single-token program, one request at a
    # time (the tpunet/infer/generate.py serving shape) — warm up the
    # compile first so both sides race steady-state.
    generate(model, variables, prompts[0][None], n_new=2)
    t0 = time.perf_counter()
    for p in prompts:
        generate(model, variables, p[None], n_new=n_new)
    seq_s = time.perf_counter() - t0

    cfg = ServeConfig(slots=n_req, queue_max=n_req,
                      prefill_buckets=(8,), emit_every_s=0.0)
    eng = Engine(model, variables, cfg).start()
    try:
        # warm both engine programs (prefill bucket + decode step)
        eng.submit(prompts[0], max_new_tokens=2).result(timeout=120)
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        for r in reqs:
            r.result(timeout=300)
        batched_s = time.perf_counter() - t0
    finally:
        eng.stop()
    speedup = seq_s / batched_s
    assert speedup >= 2.0, (
        f"continuous batching {n_req * n_new / batched_s:.0f} tok/s vs "
        f"sequential {n_req * n_new / seq_s:.0f} tok/s "
        f"(speedup {speedup:.2f}x < 2x)")
