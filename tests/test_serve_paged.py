"""Paged KV cache + device-side batched sampling + int8 KV (PR 12).

Covers the serve engine's rebuilt memory and sampling hot paths on a
tiny CPU LM: device-vs-host sampler parity (greedy bit-identical;
seeded stochastic draws stay inside filter_logits' support and are
deterministic per (seed, step)), page-recycling/fragmentation stress
(churn until every page has been reused; no stale-KV bleed across slot
reuse), pool-exhaustion preemption resuming token-identically, the
int8 eval-parity gate, the effective-budget satellite, and AOT
cold-start of the paged+fused program set. PR 18 extends the stress
and parity coverage to the prefix KV cache: refcounted/COW page
semantics, suffix-only prefill on hits, eviction under pool pressure,
cache-on/off/dense greedy parity, and the shared-filesystem
spill/warm-start round trip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpunet.config import ModelConfig, ServeConfig
from tpunet.models import create_model, init_variables
from tpunet.models.lm import filter_logits, generate
from tpunet.serve import Engine, GenerateRequest, PromptTooLongError

TINY = ModelConfig(name="lm", vit_hidden=32, vit_depth=2, vit_heads=2,
                   dropout_rate=0.0, dtype="float32", vocab_size=31,
                   max_seq_len=48)


@pytest.fixture(scope="module")
def tiny_lm():
    model = create_model(TINY)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    return model, variables


def make_engine(tiny_lm, **cfg_kw):
    model, variables = tiny_lm
    cfg_kw.setdefault("slots", 4)
    cfg_kw.setdefault("queue_max", 16)
    cfg_kw.setdefault("prefill_buckets", (8, 16))
    cfg_kw.setdefault("default_max_new_tokens", 6)
    cfg_kw.setdefault("emit_every_s", 0.0)
    return Engine(model, variables, ServeConfig(**cfg_kw))


def prompts(n, rng_seed=0, lo=2, hi=9):
    rng = np.random.default_rng(rng_seed)
    return [rng.integers(0, TINY.vocab_size,
                         size=int(rng.integers(lo, hi))).astype(np.int32)
            for _ in range(n)]


def solo_greedy(tiny_lm, prompt, n_new):
    model, variables = tiny_lm
    out = generate(model, variables, np.asarray(prompt)[None],
                   n_new=n_new)
    return np.asarray(out)[0, len(prompt):].tolist()


# ---------------------------------------------------------------------------
# device-vs-host sampler parity
# ---------------------------------------------------------------------------

def test_batched_sample_greedy_is_bitwise_argmax():
    """Greedy rows (temperature <= 0) of the device sampler must equal
    the host sampler's np.argmax on the same float32 logits — the
    invariant that keeps greedy serve output token-identical to solo
    generate."""
    from tpunet.serve.sampling import batched_sample
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 64)).astype(np.float32) * 3
    n = logits.shape[0]
    toks = np.asarray(batched_sample(
        jnp.asarray(logits), np.zeros(n, np.float32),
        np.zeros(n, np.int32), np.zeros(n, np.float32),
        np.arange(n, dtype=np.int32), np.zeros(n, np.int32)))
    np.testing.assert_array_equal(toks, np.argmax(logits, axis=-1))


def test_batched_sample_support_matches_filter_logits():
    """Per-row stochastic draws over many steps must stay inside the
    support filter_logits admits for that row's (temperature, top_k,
    top_p) — the device path may not sample tokens the reference
    warper would have filtered out. Rows carry DIFFERENT parameters in
    one batch (the whole point of the per-row sampler)."""
    from tpunet.serve.sampling import batched_sample
    rng = np.random.default_rng(3)
    logits = rng.normal(size=(3, 24)).astype(np.float32) * 2
    params = [(0.8, 3, 0.0), (1.2, 0, 0.7), (0.6, 5, 0.8)]
    temp = np.asarray([p[0] for p in params], np.float32)
    top_k = np.asarray([p[1] for p in params], np.int32)
    top_p = np.asarray([p[2] for p in params], np.float32)
    allowed = []
    for row, (t, k, p) in zip(logits, params):
        ref = np.asarray(filter_logits(jnp.asarray(row)[None] / t,
                                       top_k=k, top_p=p))[0]
        allowed.append(set(np.nonzero(np.isfinite(ref))[0].tolist()))
    seeds = np.asarray([7, 8, 9], np.int32)
    # Rows are independent counter-based draws keyed by (seed, step)
    # alone, so ONE [60*3, V] call draws bitwise the same tokens as 60
    # separate [3, V] calls — without 60 eager dispatches of the whole
    # sort/softmax/cumsum pipeline.
    n_steps = 60
    steps = np.repeat(np.arange(n_steps, dtype=np.int32), 3)
    toks = np.asarray(batched_sample(
        jnp.asarray(np.tile(logits, (n_steps, 1))),
        np.tile(temp, n_steps), np.tile(top_k, n_steps),
        np.tile(top_p, n_steps), np.tile(seeds, n_steps), steps))
    seen = [set(), set(), set()]
    for j, t in enumerate(toks):
        seen[j % 3].add(int(t))
    for i in range(3):
        assert seen[i] <= allowed[i], (params[i], seen[i] - allowed[i])
        # every filter keeps the argmax reachable
        assert int(np.argmax(logits[i])) in allowed[i]


def test_batched_sample_deterministic_per_seed_and_step():
    """The counter-based key fold: same (seed, step) reproduces the
    same token, a different seed or step (almost surely) moves at
    least one row — and rows are independent (changing row 0's seed
    never changes row 1's draw)."""
    from tpunet.serve.sampling import batched_sample
    rng = np.random.default_rng(5)
    logits = jnp.asarray(rng.normal(size=(4, 32)).astype(np.float32))
    temp = np.full(4, 1.0, np.float32)
    zk = np.zeros(4, np.int32)
    zp = np.zeros(4, np.float32)
    seeds = np.asarray([1, 2, 3, 4], np.int32)
    step0 = np.zeros(4, np.int32)
    a = np.asarray(batched_sample(logits, temp, zk, zp, seeds, step0))
    b = np.asarray(batched_sample(logits, temp, zk, zp, seeds, step0))
    np.testing.assert_array_equal(a, b)
    seeds2 = seeds.copy()
    seeds2[0] = 99
    c = np.asarray(batched_sample(logits, temp, zk, zp, seeds2, step0))
    np.testing.assert_array_equal(a[1:], c[1:])  # row independence
    # 12 steps in one tiled call (rows independent, see the support
    # test above), regrouped per step.
    steps = np.repeat(np.arange(12, dtype=np.int32), 4)
    tiled = np.asarray(batched_sample(
        jnp.asarray(np.tile(np.asarray(logits), (12, 1))),
        np.tile(temp, 12), np.tile(zk, 12), np.tile(zp, 12),
        np.tile(seeds, 12), steps))
    draws = {tuple(tiled[s * 4:(s + 1) * 4].tolist()) for s in range(12)}
    assert len(draws) > 1  # steps actually advance the stream


def test_seed_validated_at_admission():
    """A bad seed is a client error at admission (the frontend maps
    ValueError to HTTP 400), never an engine-thread death on the host
    sampler (numpy rejects negatives) or a silent int32 stream
    collision on the device path (seeds past bit 31)."""
    with pytest.raises(ValueError, match="seed"):
        GenerateRequest(np.arange(1, 4), max_new_tokens=2, seed=-3)
    with pytest.raises(ValueError, match="seed"):
        GenerateRequest(np.arange(1, 4), max_new_tokens=2, seed=2 ** 31)
    GenerateRequest(np.arange(1, 4), max_new_tokens=2, seed=2 ** 31 - 1)


def test_engine_host_sampler_fallback_matches_device_greedy(tiny_lm):
    """--no-device-sampling keeps the host sampler as the live parity
    reference: greedy output through both engine paths is identical
    (and equals solo generate)."""
    ps = prompts(4, rng_seed=11)
    outs = {}
    for label, dev in (("device", True), ("host", False)):
        eng = make_engine(tiny_lm, device_sampling=dev).start()
        try:
            reqs = [eng.submit(p, max_new_tokens=5) for p in ps]
            outs[label] = [r.result(timeout=120) for r in reqs]
        finally:
            eng.stop()
    assert outs["device"] == outs["host"]
    for p, o in zip(ps, outs["device"]):
        assert o == solo_greedy(tiny_lm, p, 5)


# ---------------------------------------------------------------------------
# paged pool: recycling / fragmentation / preemption
# ---------------------------------------------------------------------------

def test_page_recycling_stress_no_stale_kv_bleed(tiny_lm):
    """Churn admissions through a small pool until EVERY usable page
    has been allocated at least once and the allocation count proves
    reuse; every request's greedy output must still match solo decode
    — a recycled page leaking its previous occupant's K/V would
    diverge immediately."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=8, kv_page_tokens=4,
                      prefix_cache=False).start()
    try:
        wave = 0
        # Requests of 5-8 prompt + 8 new tokens span 4 pages each, so
        # two co-residents demand the WHOLE 8-page pool; LIFO
        # recycling alone would otherwise keep cold pages cold.
        # prefix_cache=False pins the PR-12 contract: with no cache
        # retaining prompt pages, release returns every page.
        while wave < 12 and (len(eng._kv_pages_touched)
                             < eng.kv_pages_usable or wave < 4):
            ps = prompts(4, rng_seed=100 + wave, lo=5, hi=9)
            reqs = [eng.submit(p, max_new_tokens=8) for p in ps]
            for p, r in zip(ps, reqs):
                assert r.result(timeout=120) == \
                    solo_greedy(tiny_lm, p, 8), f"wave {wave} diverged"
            wave += 1
        assert eng._kv_pages_touched == set(
            range(1, eng.kv_pages_usable + 1)), "pages never all used"
        snap = eng.registry.snapshot()
        assert snap["serve_kv_page_allocs_total"] > eng.kv_pages_usable, \
            "allocation count proves no page was ever recycled"
        assert len(eng._free_pages) == eng.kv_pages_usable
        assert snap["serve_kv_pages_used"] == 0
    finally:
        eng.stop()


def test_pool_exhaustion_preempts_and_resumes_token_identically(tiny_lm):
    """5 usable pages x 4 tokens cannot hold two full-length
    co-residents: the engine must preempt the youngest blocked slot
    back to the queue and resume it by re-prefilling prompt+generated
    — every request still finishes with exactly the solo-greedy
    tokens."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=5, kv_page_tokens=4,
                      default_max_new_tokens=12).start()
    try:
        ps = prompts(4, rng_seed=1, lo=6, hi=7)
        reqs = [eng.submit(p, max_new_tokens=12) for p in ps]
        for p, r in zip(ps, reqs):
            assert r.result(timeout=120) == solo_greedy(tiny_lm, p, 12)
            assert r.finish_reason == "length"
        snap = eng.registry.snapshot()
        assert snap["serve_kv_preemptions_total"] >= 1
        assert sum(r.preemptions for r in reqs) >= 1
    finally:
        eng.stop()


def test_preempt_victim_prefers_resumable_slots(tiny_lm):
    """Victim selection under pool exhaustion: a slot whose
    prompt+generated has outgrown the largest prefill bucket cannot be
    re-prefilled, so preempting it would error a healthy in-flight
    request — the YOUNGEST RESUMABLE slot must be chosen instead, and
    an unresumable one only when there is no alternative."""
    from tpunet.serve.engine import _Slot

    eng = make_engine(tiny_lm)          # buckets (8, 16)
    old_long = _Slot(GenerateRequest(np.ones(6, np.int32),
                                     max_new_tokens=30),
                     pos=20, next_token=1, seq=1)
    old_long.req.tokens.extend([1] * 14)     # resume size 20 > 16
    young_short = _Slot(GenerateRequest(np.ones(4, np.int32),
                                        max_new_tokens=30),
                        pos=8, next_token=1, seq=2)
    young_short.req.tokens.extend([1] * 4)   # resume size 8 <= 16
    # youngest overall is resumable -> picked (slot index 1)
    assert eng._choose_preempt_victim(
        [(0, old_long), (1, young_short)]) == 1
    # youngest overall unresumable, older resumable exists -> the
    # OLDER resumable one is picked, never the unresumable youngest
    young_long = _Slot(GenerateRequest(np.ones(6, np.int32),
                                       max_new_tokens=30),
                       pos=20, next_token=1, seq=3)
    young_long.req.tokens.extend([1] * 14)
    assert eng._choose_preempt_victim(
        [(1, young_short), (2, young_long)]) == 1
    # every blocked slot unresumable -> youngest fails (unavoidable)
    assert eng._choose_preempt_victim(
        [(0, old_long), (2, young_long)]) == 2


def test_request_that_cannot_fit_pool_rejected_up_front(tiny_lm):
    """Completability guard: a request whose full length exceeds the
    whole pool would preempt itself forever — submit rejects it."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=5, kv_page_tokens=4)
    with pytest.raises(PromptTooLongError):
        eng.submit(np.ones(8, np.int32), max_new_tokens=40)
    assert eng.registry.snapshot()["serve_requests_rejected"] == 1


def test_paged_vs_dense_engine_outputs_identical(tiny_lm):
    """The dense fallback (--no-paged-kv) and the paged default are
    the same math: identical greedy tokens across a mid-flight
    admission pattern."""
    import time
    outs = {}
    for label, paged in (("paged", True), ("dense", False)):
        eng = make_engine(tiny_lm, slots=2, paged_kv=paged).start()
        try:
            ps = prompts(6, rng_seed=42)
            reqs = []
            for i, p in enumerate(ps):
                reqs.append(eng.submit(p, max_new_tokens=5))
                if i % 2 == 1:
                    time.sleep(0.01)
            outs[label] = [r.result(timeout=120) for r in reqs]
        finally:
            eng.stop()
    assert outs["paged"] == outs["dense"]


# ---------------------------------------------------------------------------
# prefix KV cache: refcounted content-addressed pages (PR 18)
# ---------------------------------------------------------------------------

def test_prefix_cache_trie_pin_release_evict_order():
    """Host-side trie semantics in isolation: lookup walks the longest
    cached chain; interleaved pin/release keeps refcounts exact (a
    double-pinned node survives one release); eviction is leaf-first
    (never orphans a cached chain) and LRU among evictable nodes."""
    from tpunet.serve.prefixcache import PrefixCache, chain_digests, \
        token_prefix_digest

    c = PrefixCache(page_tokens=4, capacity=8)
    toks = list(range(12))
    d = chain_digests(toks, 4, 3)
    n0 = c.insert(d[0], None, 0, 5)
    n1 = c.insert(d[1], n0, 1, 6)
    n2 = c.insert(d[2], n1, 2, 7)
    assert [n.page for n in c.lookup(toks, 3)] == [5, 6, 7]
    assert [n.page for n in c.lookup(toks, 2)] == [5, 6]
    assert c.lookup([9] * 12, 3) == []
    # every node pinned -> nothing evictable
    c.pin([n0, n1, n2])
    assert c.evict_one() is None
    # releasing the leaf exposes exactly the leaf; interior nodes with
    # children stay, so the surviving trie is always prefix-closed
    c.unpin([n2])
    assert c.evict_one() == 7
    assert c.lookup(toks, 3) == [n0, n1]
    c.unpin([n0, n1])
    assert c.evict_one() == 6
    assert c.evict_one() == 5
    assert c.evict_one() is None and c.pages_cached == 0
    # interleaved pin/release: two pins need two releases
    m = c.insert(token_prefix_digest([3, 3, 3, 3], 4), None, 0, 2)
    c.pin([m])
    c.pin([m])
    c.unpin([m])
    assert c.evict_one() is None
    c.unpin([m])
    assert c.evict_one() == 2
    # LRU: the older untouched root goes first
    a = c.insert(token_prefix_digest([1] * 4, 4), None, 0, 3)
    b = c.insert(token_prefix_digest([2] * 4, 4), None, 0, 4)
    c.pin([a])
    c.unpin([a])            # touches a after b's insert
    assert c.evict_one() == 4
    assert c.evict_one() == 3


def test_prefix_hit_pins_pages_and_prefills_suffix_only(tiny_lm):
    """A second request sharing the first two prompt pages must pin
    them from the cache and prefill ONLY the suffix — measured by the
    serve_prefill_tokens_total delta — while staying token-identical
    to solo decode (stale or misattributed prefix K/V would diverge
    immediately)."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=16,
                      kv_page_tokens=4).start()
    try:
        rng = np.random.default_rng(13)
        shared = rng.integers(0, TINY.vocab_size, size=8).astype(np.int32)
        p1 = np.concatenate([shared, rng.integers(
            0, TINY.vocab_size, size=3).astype(np.int32)])
        p2 = np.concatenate([shared, rng.integers(
            0, TINY.vocab_size, size=2).astype(np.int32)])
        out1 = eng.submit(p1, max_new_tokens=5).result(timeout=120)
        pre1 = eng.registry.snapshot()["serve_prefill_tokens_total"]
        assert pre1 == p1.size          # cold request: full prefill
        out2 = eng.submit(p2, max_new_tokens=5).result(timeout=120)
        snap = eng.registry.snapshot()
        assert snap["serve_prefill_tokens_total"] - pre1 == p2.size - 8
        assert snap["serve_prefix_hits_total"] >= 1
        assert snap["serve_prefix_hit_tokens_total"] >= 8
        assert snap["serve_prefix_inserts_total"] >= 2
        assert snap["serve_prefix_pages_cached"] >= 2
    finally:
        eng.stop()
    assert out1 == solo_greedy(tiny_lm, p1, 5)
    assert out2 == solo_greedy(tiny_lm, p2, 5)


def test_prefix_cow_identical_prompt_and_divergence(tiny_lm):
    """Copy-on-write at the divergence page: an identical page-aligned
    prompt re-uses the full cached chain but COPIES the last page into
    a private one (decode will write past it); a prompt diverging
    INSIDE the second page pins only the first and re-prefills from
    the divergence page without COW. Both stay solo-greedy-identical —
    a COW copy sharing mutable state with the source would corrupt the
    cached page for later hits."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=16,
                      kv_page_tokens=4).start()
    try:
        rng = np.random.default_rng(17)
        p = rng.integers(0, TINY.vocab_size, size=8).astype(np.int32)
        out1 = eng.submit(p, max_new_tokens=6).result(timeout=120)
        out2 = eng.submit(p, max_new_tokens=6).result(timeout=120)
        cow = eng.registry.snapshot()["serve_prefix_cow_total"]
        assert cow >= 1
        q = p.copy()
        q[5] = (int(q[5]) + 1) % TINY.vocab_size   # diverge in page 1
        out3 = eng.submit(q, max_new_tokens=6).result(timeout=120)
        snap = eng.registry.snapshot()
        assert snap["serve_prefix_hits_total"] >= 2
        assert snap["serve_prefix_cow_total"] == cow   # divergence != COW
        # the COW'd source page is still served intact after both
        out4 = eng.submit(p, max_new_tokens=6).result(timeout=120)
    finally:
        eng.stop()
    assert out1 == out2 == out4 == solo_greedy(tiny_lm, p, 6)
    assert out3 == solo_greedy(tiny_lm, q, 6)


def test_prefix_churn_stress_refcounted_pages_no_stale_bleed(tiny_lm):
    """The PR-12 recycling stress extended to the refcounted/COW
    regime: with the prefix cache ON over a pool two co-residents can
    exhaust, pages continuously migrate free list -> slot -> cache ->
    (eviction) -> free list, repeated prompts hit cached pages, and
    every request must STILL match solo decode — any stale K/V bleed
    through a recycled or cached page diverges greedy output. At
    quiesce every pool page is either free or cached-unpinned
    (nothing leaks)."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=8,
                      kv_page_tokens=4).start()
    try:
        for wave in range(8):
            # seeds repeat across waves -> identical prompts recur and
            # exercise hits/COW against pages that churned in between
            ps = prompts(4, rng_seed=200 + wave % 3, lo=5, hi=9)
            reqs = [eng.submit(p, max_new_tokens=8) for p in ps]
            for p, r in zip(ps, reqs):
                assert r.result(timeout=120) == \
                    solo_greedy(tiny_lm, p, 8), f"wave {wave} diverged"
        # a back-to-back repeat at quiesce must hit the cache
        fixed = prompts(1, rng_seed=999, lo=8, hi=9)[0]
        a = eng.submit(fixed, max_new_tokens=4).result(timeout=120)
        b = eng.submit(fixed, max_new_tokens=4).result(timeout=120)
        assert a == b == solo_greedy(tiny_lm, fixed, 4)
        snap = eng.registry.snapshot()
        assert snap["serve_prefix_evictions_total"] >= 1, \
            "pool pressure never evicted a cached page"
        assert snap["serve_prefix_hits_total"] >= 1
        assert eng._prefix.pinned_pages() == 0
        assert len(eng._free_pages) + eng._prefix.pages_cached \
            == eng.kv_pages_usable, "a pool page leaked"
    finally:
        eng.stop()


def test_prefix_cache_parity_on_off_dense(tiny_lm):
    """Greedy output over a shared-prefix workload is identical with
    the cache on, the cache off, and the dense (--no-paged-kv) path —
    the cache is a pure compute-elision, never a math change."""
    rng = np.random.default_rng(31)
    shared = rng.integers(0, TINY.vocab_size, size=8).astype(np.int32)
    ps = [np.concatenate([shared, rng.integers(
        0, TINY.vocab_size, size=k).astype(np.int32)])
        for k in (3, 2, 5, 1)]
    outs = {}
    for label, kw in (("cache", {}),
                      ("nocache", {"prefix_cache": False}),
                      ("dense", {"paged_kv": False})):
        eng = make_engine(tiny_lm, slots=2, **kw).start()
        try:
            outs[label] = [eng.submit(p, max_new_tokens=5)
                           .result(timeout=120) for p in ps]
        finally:
            eng.stop()
    assert outs["cache"] == outs["nocache"] == outs["dense"]
    for p, o in zip(ps, outs["cache"]):
        assert o == solo_greedy(tiny_lm, p, 5)


def test_prefix_spill_and_warm_start_roundtrip(tmp_path, tiny_lm):
    """Shared-filesystem warm start: replica 1 spills its adopted
    prefix pages write-through; a FRESH replica 2 sharing the store
    directory adopts them at boot (warm_loads), and its very first
    shared-prefix request prefills only the suffix while staying
    solo-greedy-identical — the full pickle -> fs -> pool round trip
    must reproduce the K/V rows bitwise."""
    from tpunet.serve.prefixcache import build_prefix_store

    model, variables = tiny_lm
    cfg = ServeConfig(slots=2, queue_max=8, prefill_buckets=(16,),
                      default_max_new_tokens=6, emit_every_s=0.0,
                      kv_pages=12, kv_page_tokens=4)
    store = build_prefix_store(str(tmp_path), TINY, cfg)
    rng = np.random.default_rng(21)
    shared = rng.integers(0, TINY.vocab_size, size=8).astype(np.int32)
    p1 = np.concatenate([shared, rng.integers(
        0, TINY.vocab_size, size=3).astype(np.int32)])
    eng = Engine(model, variables, cfg, prefix_store=store).start()
    try:
        out1 = eng.submit(p1, max_new_tokens=5).result(timeout=120)
    finally:
        eng.stop()
    assert out1 == solo_greedy(tiny_lm, p1, 5)
    assert eng.registry.snapshot()["serve_prefix_spills_total"] >= 2
    assert any(f.name.endswith(".pfx") for f in tmp_path.iterdir())

    eng2 = Engine(model, variables, cfg, prefix_store=store).start()
    try:
        assert eng2.registry.snapshot()[
            "serve_prefix_warm_loads_total"] >= 2
        p2 = np.concatenate([shared, rng.integers(
            0, TINY.vocab_size, size=2).astype(np.int32)])
        out2 = eng2.submit(p2, max_new_tokens=5).result(timeout=120)
    finally:
        eng2.stop()
    assert out2 == solo_greedy(tiny_lm, p2, 5)
    snap2 = eng2.registry.snapshot()
    assert snap2["serve_prefix_hits_total"] >= 1
    assert snap2["serve_prefix_hit_tokens_total"] >= 8
    # the warmed replica never prefilled the shared prefix at all
    assert snap2["serve_prefill_tokens_total"] == p2.size - 8


# ---------------------------------------------------------------------------
# int8 KV parity gate
# ---------------------------------------------------------------------------

def test_int8_kv_eval_parity_gate(tiny_lm):
    """The eval-parity gate for --kv-dtype int8: greedy decode through
    quantized pages must be token-identical to the float32 path on the
    tiny model across a prompt spread. (Quantization error exists —
    this gate is what keeps it below argmax-flipping size; a model
    where it trips must not ship int8 KV.)"""
    eng = make_engine(tiny_lm, kv_dtype="int8").start()
    try:
        for seed in range(6):
            p = prompts(1, rng_seed=seed)[0]
            out = eng.submit(p, max_new_tokens=6).result(timeout=120)
            assert out == solo_greedy(tiny_lm, p, 6), \
                f"int8 KV diverged on seed {seed}"
    finally:
        eng.stop()


def test_int8_kv_halves_bf16_page_cost(tiny_lm):
    """The capacity claim, measured: int8 pages (payload + scale
    sidecar) cost less than half the float32 pages and at most ~60%
    of bf16 pages for this head size."""
    sizes = {}
    for dtype in ("auto", "bf16", "int8"):
        eng = make_engine(tiny_lm, kv_dtype=dtype)
        sizes[dtype] = eng.kv_bytes_per_token()
    assert sizes["int8"] < sizes["auto"] / 2
    assert sizes["int8"] < sizes["bf16"] * 0.75
    assert sizes["bf16"] == pytest.approx(sizes["auto"] / 2)


def test_int8_requires_paged_kv(tiny_lm):
    with pytest.raises(ValueError):
        make_engine(tiny_lm, paged_kv=False, kv_dtype="int8")


# ---------------------------------------------------------------------------
# effective-budget satellite
# ---------------------------------------------------------------------------

def test_submit_records_requested_and_effective_budget(tiny_lm):
    """The admission clamp is explicit now: requested_max_new_tokens
    keeps the client's ask, max_new_tokens becomes the effective
    budget (operator cap, then KV-length clamp)."""
    eng = make_engine(tiny_lm, prefill_buckets=(48,),
                      max_new_tokens_cap=2048).start()
    try:
        req = eng.submit(np.ones(40, np.int32), max_new_tokens=100)
        out = req.result(timeout=60)
        assert req.requested_max_new_tokens == 100
        assert req.max_new_tokens == 8          # 48 - 40
        assert len(out) == 8
        # the cap clamp is recorded the same way
        eng2 = make_engine(tiny_lm, max_new_tokens_cap=3)
        r2 = eng2.submit(np.ones(4, np.int32), max_new_tokens=50)
        assert r2.requested_max_new_tokens == 50
        assert r2.max_new_tokens == 3
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# obs: kv gauges + record fields
# ---------------------------------------------------------------------------

def test_kv_gauges_and_serve_record_fields(tiny_lm):
    from tpunet.serve.engine import build_serve_record
    eng = make_engine(tiny_lm, kv_pages=10, kv_page_tokens=8)
    snap = eng.registry.snapshot()
    assert snap["serve_kv_pages_total"] == 10
    assert snap["serve_kv_pages_used"] == 0
    assert snap["serve_kv_bytes_per_token"] > 0
    rec = build_serve_record(eng.registry, queue_depth=0,
                             active_slots=0, slots=4, uptime_s=1.0,
                             window_s=1.0)
    assert rec["kv_pages_total"] == 10
    assert rec["kv_pages_used"] == 0
    assert rec["kv_bytes_per_token"] > 0


# ---------------------------------------------------------------------------
# AOT warm-start of the paged + device-sampled program set
# ---------------------------------------------------------------------------

def test_paged_aot_store_roundtrip(tmp_path, tiny_lm):
    """The paged decode + fused-sampling program joins the serialized
    closed set: a second boot deserializes every program ('loaded')
    and produces token-identical greedy output; flipping a paging
    lever is a clean store MISS, never a stale executable."""
    from tpunet.serve.engine import build_aot_store

    model, variables = tiny_lm
    cfg = ServeConfig(slots=2, queue_max=4, prefill_buckets=(16,),
                      default_max_new_tokens=8, emit_every_s=0.0,
                      kv_pages=12, kv_page_tokens=8)
    store = build_aot_store(str(tmp_path), TINY, cfg)
    prompt = np.arange(5, dtype=np.int32)

    eng = Engine(model, variables, cfg, aot_store=store).start()
    try:
        toks1 = eng.submit(prompt, max_new_tokens=5).result(timeout=120)
    finally:
        eng.stop()
    assert all(v.startswith("compiled") for v in eng.aot_status.values())

    eng2 = Engine(model, variables, cfg, aot_store=store).start()
    try:
        toks2 = eng2.submit(prompt, max_new_tokens=5).result(timeout=120)
    finally:
        eng2.stop()
    assert eng2.aot_status == {"w1": "loaded", "w16": "loaded"}
    assert toks2 == toks1 == solo_greedy(tiny_lm, prompt, 5)

    # A different kv_dtype selects a different program set: clean MISS.
    cfg_int8 = ServeConfig(slots=2, queue_max=4, prefill_buckets=(16,),
                           default_max_new_tokens=8, emit_every_s=0.0,
                           kv_pages=12, kv_page_tokens=8,
                           kv_dtype="int8")
    store_int8 = build_aot_store(str(tmp_path), TINY, cfg_int8)
    eng3 = Engine(model, variables, cfg_int8,
                  aot_store=store_int8).start()
    try:
        eng3.submit(prompt, max_new_tokens=2).result(timeout=120)
    finally:
        eng3.stop()
    assert all(v.startswith("compiled")
               for v in eng3.aot_status.values())


def test_aot_save_is_load_verified(tmp_path, monkeypatch):
    """save() proves the blob deserializes before committing it — an
    executable that serializes into an unloadable blob (the persistent-
    compile-cache poison mode) must yield False and write NOTHING, so
    a later boot can never trust a poisoned entry."""
    from jax.experimental import serialize_executable

    from tpunet.utils.cache import AotProgramStore

    store = AotProgramStore(str(tmp_path), "digest")
    monkeypatch.setattr(serialize_executable, "serialize",
                        lambda compiled: (b"blob", None, None))
    monkeypatch.setattr(
        serialize_executable, "deserialize_and_load",
        lambda *a: (_ for _ in ()).throw(RuntimeError("Symbols not found")))
    assert store.save("masked_step", "w16", object()) is False
    assert not list(tmp_path.iterdir())

    monkeypatch.setattr(serialize_executable, "deserialize_and_load",
                        lambda *a: object())
    assert store.save("masked_step", "w16", object()) is True
    assert any(p.name.endswith(".aotx") for p in tmp_path.iterdir())


def test_serializable_compile_restores_cache_flag():
    """AOT-destined compiles run with the persistent compilation cache
    OFF (a cache-served executable saves a poison blob) and the flag is
    restored afterwards, including on the exception path."""
    from tpunet.utils.cache import serializable_compile

    prev = jax.config.jax_enable_compilation_cache
    with serializable_compile():
        assert jax.config.jax_enable_compilation_cache is False
    assert jax.config.jax_enable_compilation_cache == prev
    with pytest.raises(ValueError):
        with serializable_compile():
            raise ValueError("boom")
    assert jax.config.jax_enable_compilation_cache == prev


# ---------------------------------------------------------------------------
# speculative decoding over the paged pool
# ---------------------------------------------------------------------------


def _pool_clean(eng):
    """Quiesce invariant: every usable page is either on the free list
    or resident in the prefix cache — a rewind or release that dropped
    a page shows up here immediately."""
    cached = eng._prefix.pages_cached if eng._prefix else 0
    return len(eng._free_pages) + cached == eng.kv_pages_usable


def test_spec_config_requires_paged_and_device_sampling(tiny_lm):
    """Drafting runs against the paged pool and samples on-device;
    both fallbacks are config errors, not silent downgrades."""
    for bad in (dict(paged_kv=False), dict(device_sampling=False),
                dict(spec_k=0), dict(spec_draft_width_mult=0.0)):
        with pytest.raises(ValueError):
            make_engine(tiny_lm, spec_decode=True, **bad)


def test_spec_greedy_bitwise_identical_both_acceptance_extremes(tiny_lm):
    """Greedy spec-on output must be BITWISE spec-off at both ends of
    the acceptance spectrum: a width_mult-1.0 drafter (the serving
    model drafting for itself — every draft accepted) and a random-
    init half-width drafter (near-total rejection — every cycle falls
    back to the one verified token). Every emitted token comes from
    the verify program, so acceptance can only change SPEED."""
    ps = prompts(5, rng_seed=7)
    solo = [solo_greedy(tiny_lm, p, 10) for p in ps]
    for wm, expect_all_accepted in ((1.0, True), (0.5, False)):
        eng = make_engine(tiny_lm, spec_decode=True, spec_k=3,
                          spec_draft_width_mult=wm).start()
        try:
            reqs = [eng.submit(p, max_new_tokens=10) for p in ps]
            outs = [r.result(timeout=120) for r in reqs]
        finally:
            eng.stop()
        assert outs == solo, f"wm={wm} diverged from solo greedy"
        snap = eng.registry.snapshot()
        drafted = snap["serve_spec_draft_tokens_total"]
        acc = snap["serve_spec_accepted_tokens_total"]
        rej = snap["serve_spec_rejected_tokens_total"]
        assert drafted > 0 and snap["serve_spec_verify_steps_total"] > 0
        assert acc + rej == drafted
        if expect_all_accepted:
            assert acc == drafted, "self-speculation must accept all"
        else:
            assert rej > 0, "random drafter should see rejections"
        assert _pool_clean(eng), "rewind/release leaked a page"


def test_spec_sampled_stream_identical_and_preempt_deterministic(tiny_lm):
    """Sampled requests: spec-on draws each position with the same
    (seed, step) counter key the sequential loop would have used, so
    the stream is bitwise spec-off — including across a pool-pressure
    preemption, where the resumed slot continues its exact sample
    sequence (steps0 = len(req.tokens) re-derives the key)."""
    kw = dict(max_new_tokens=8, temperature=0.9, top_k=5, seed=123)
    ps = prompts(4, rng_seed=11, lo=6, hi=7)

    def run(**cfg_kw):
        eng = make_engine(tiny_lm, **cfg_kw).start()
        try:
            reqs = [eng.submit(p, **kw) for p in ps]
            return eng, [r.result(timeout=120) for r in reqs]
        finally:
            eng.stop()

    _, base = run()
    eng_on, sampled = run(spec_decode=True, spec_k=3,
                          spec_draft_width_mult=0.5)
    assert sampled == base, "spec-on sampled stream diverged"
    assert _pool_clean(eng_on)
    # Tight pool: two co-residents cannot both finish without a
    # preemption; the preempted request must still produce the same
    # sampled stream after resume-prefill.
    eng_tight, tight = run(spec_decode=True, spec_k=3,
                           spec_draft_width_mult=0.5, slots=2,
                           kv_pages=5, kv_page_tokens=4)
    assert tight == base, "preempt-resume broke sample determinism"
    assert eng_tight.registry.snapshot()[
        "serve_kv_preemptions_total"] >= 1, \
        "pool never preempted; the resume path was not exercised"
    assert _pool_clean(eng_tight)


def test_spec_rejection_rewind_recycles_pages(tiny_lm):
    """The leak test for cursor rewind: a random half-width drafter
    rejects nearly everything, so every burst allocates pages through
    pos+K and rewinds most of them. Churn waves over a small pool
    until every page has been reused; greedy parity proves recycled
    pages carry no stale K/V from a rewound burst, and at quiesce
    free + prefix-cached must equal the whole pool."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=8, kv_page_tokens=4,
                      prefix_cache=False, spec_decode=True, spec_k=3,
                      spec_draft_width_mult=0.5).start()
    try:
        for wave in range(3):
            ps = prompts(4, rng_seed=300 + wave, lo=5, hi=9)
            reqs = [eng.submit(p, max_new_tokens=8) for p in ps]
            for p, r in zip(ps, reqs):
                assert r.result(timeout=120) == \
                    solo_greedy(tiny_lm, p, 8), f"wave {wave} diverged"
        snap = eng.registry.snapshot()
        assert snap["serve_spec_rejected_tokens_total"] > 0
        assert snap["serve_kv_page_allocs_total"] > eng.kv_pages_usable
        assert len(eng._free_pages) == eng.kv_pages_usable, \
            "a rewound or released page leaked"
        assert snap["serve_kv_pages_used"] == 0
    finally:
        eng.stop()


def test_spec_rewind_clamps_at_pinned_prefix_pages(tiny_lm):
    """A rejection rewind must never free or zero a page the slot
    pinned from the prefix cache: with a shared page-aligned prompt
    and a heavily-rejecting drafter, later requests keep hitting the
    SAME cached pages and must stay solo-greedy-identical — a rewind
    that clawed back (or a burst that overwrote) a shared page would
    corrupt every later hit."""
    eng = make_engine(tiny_lm, slots=2, kv_pages=16, kv_page_tokens=4,
                      spec_decode=True, spec_k=3,
                      spec_draft_width_mult=0.5).start()
    try:
        rng = np.random.default_rng(23)
        p = rng.integers(0, TINY.vocab_size, size=8).astype(np.int32)
        outs = [eng.submit(p, max_new_tokens=6).result(timeout=120)
                for _ in range(3)]
        snap = eng.registry.snapshot()
        assert snap["serve_prefix_hits_total"] >= 2
        assert snap["serve_spec_rejected_tokens_total"] > 0
        assert _pool_clean(eng)
    finally:
        eng.stop()
    want = solo_greedy(tiny_lm, p, 6)
    assert outs == [want] * 3, \
        "a spec rewind or draft write disturbed shared prefix pages"


def test_spec_serve_record_and_instruments(tiny_lm):
    """The ops contract: a spec engine's serve record carries the
    spec_* fields (docs/metrics_schema.md obs_serve) with coherent
    derived rates, and the serve_spec_* instruments exist on the
    registry."""
    from tpunet.serve.engine import build_serve_record

    eng = make_engine(tiny_lm, spec_decode=True, spec_k=3,
                      spec_draft_width_mult=1.0).start()
    try:
        eng.submit(prompts(1, rng_seed=3)[0],
                   max_new_tokens=8).result(timeout=120)
    finally:
        eng.stop()
    rec = build_serve_record(eng.registry, queue_depth=0,
                             active_slots=0, slots=4, uptime_s=1.0,
                             window_s=1.0)
    assert rec["spec_draft_tokens_total"] > 0
    assert rec["spec_accepted_tokens_total"] \
        + rec["spec_rejected_tokens_total"] \
        == rec["spec_draft_tokens_total"]
    assert rec["spec_verify_steps_total"] > 0
    assert rec["spec_acceptance_rate"] == 1.0   # self-speculation
    assert rec["spec_accepted_tokens_per_verify"] > 0
    assert eng.registry.snapshot()[
        "serve_spec_acceptance_rate"] == 1.0
