"""Byte-level text-file LM training (--dataset text_lm) and the
generation CLI (tpunet.infer.generate): corpus file -> train ->
best-checkpoint -> sampled/greedy continuation, fully hermetic."""

import dataclasses

import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.data.lm import get_lm_dataset, text_lm
from tpunet.train.loop import Trainer

LM_CFG = ModelConfig(name="lm", vit_hidden=64, vit_depth=2, vit_heads=4,
                     dropout_rate=0.0, dtype="float32", vocab_size=256,
                     max_seq_len=64)

CYCLE = b"abcdefgh"


# ------------------------------------------------------------- loader


def test_text_lm_chunks_and_tail_split(tmp_path):
    path = tmp_path / "corpus.bin"
    path.write_bytes(bytes(range(100)) * 32)  # 3200 bytes
    tx, ty, sx, sy = text_lm(str(path), seq_len=32)
    assert tx.shape[1] == sx.shape[1] == 32
    assert len(tx) + len(sx) == 100  # 3200 // 32
    assert len(sx) == 10             # tail 10%
    # tokens are the raw bytes, in order; test split is the TAIL
    flat = np.concatenate([tx.ravel(), sx.ravel()])
    np.testing.assert_array_equal(
        flat, np.frombuffer(bytes(range(100)) * 32, np.uint8))


def test_text_lm_too_small_raises(tmp_path):
    path = tmp_path / "tiny.bin"
    path.write_bytes(b"x" * 40)
    with pytest.raises(ValueError, match="at least"):
        text_lm(str(path), seq_len=32)


def test_get_lm_dataset_validation(tmp_path):
    with pytest.raises(ValueError, match="--text-file"):
        get_lm_dataset(DataConfig(dataset="text_lm"))
    path = tmp_path / "c.bin"
    path.write_bytes(CYCLE * 64)
    with pytest.raises(ValueError, match="byte-level"):
        get_lm_dataset(DataConfig(dataset="text_lm", text_path=str(path),
                                  vocab_size=32))
    tx, _, sx, _ = get_lm_dataset(DataConfig(
        dataset="text_lm", text_path=str(path), seq_len=32))
    assert tx.max() < 256 and len(sx) >= 1


# ------------------------------------------------- train + generate


def _train_on_cycle(tmp_path, epochs=8):
    path = tmp_path / "cycle.txt"
    path.write_bytes(CYCLE * 512)  # 4096 bytes; next char is deterministic
    cfg = TrainConfig(
        epochs=epochs,
        data=DataConfig(dataset="text_lm", text_path=str(path),
                        batch_size=16, seq_len=32, vocab_size=256),
        model=LM_CFG,
        optim=OptimConfig(learning_rate=1e-2, schedule="constant"),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ckpt"),
                                    save_last=False),
    )
    trainer = Trainer(cfg)
    try:
        history = trainer.train()
    finally:
        trainer.close()
    return cfg, history


@pytest.mark.slow
def test_text_lm_end_to_end_and_generation(tmp_path):
    cfg, history = _train_on_cycle(tmp_path)
    # the cycle's next byte is a function of the current byte -> a tiny
    # LM must learn it nearly perfectly
    assert history[-1]["train_accuracy"] > 0.9, history[-1]

    from tpunet.infer.generate import generate_text, load_lm
    model, variables = load_lm(LM_CFG,
                               checkpoint_dir=str(tmp_path / "ckpt"))
    out = generate_text(model, variables, "abcd", 16, temperature=0.0)
    expect = (CYCLE.decode() * 4)[4:4 + 16]
    match = np.mean([a == b for a, b in zip(out, expect)])
    assert match > 0.8, (out, expect)


@pytest.mark.slow
def test_generate_cli_main(tmp_path, capsys):
    _train_on_cycle(tmp_path, epochs=2)
    from tpunet.infer import generate as gen
    gen.main(["--checkpoint-dir", str(tmp_path / "ckpt"),
              "--prompt", "abc", "--tokens", "8",
              "--vit-hidden", "64", "--vit-depth", "2", "--vit-heads",
              "4", "--max-seq-len", "64"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert out.startswith("abc") and len(out) == 11


@pytest.mark.slow
def test_generate_cli_token_vocab_prompt(tmp_path, capsys):
    """Non-byte vocabs take the prompt as space-separated token ids —
    and reject anything else instead of silently generating from 0."""
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16,
                        seq_len=32, vocab_size=32),
        model=dataclasses.replace(LM_CFG, vocab_size=32, max_seq_len=32),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                    save_last=False),
    )
    trainer = Trainer(cfg)
    try:
        trainer.train()
    finally:
        trainer.close()
    from tpunet.infer import generate as gen
    argv = ["--checkpoint-dir", str(tmp_path / "ck"), "--tokens", "5",
            "--vit-hidden", "64", "--vit-depth", "2", "--vit-heads", "4",
            "--vocab-size", "32", "--max-seq-len", "32"]
    gen.main(argv + ["--prompt", "5 7 3"])
    out = capsys.readouterr().out.strip().splitlines()[-1].split()
    assert out[:3] == ["5", "7", "3"] and len(out) == 8
    assert all(0 <= int(t) < 32 for t in out)
    with pytest.raises(SystemExit, match="token ids"):
        gen.main(argv + ["--prompt", "The "])
    with pytest.raises(SystemExit, match="outside"):
        gen.main(argv + ["--prompt", "5 99"])


def test_cli_flags(tmp_path):
    from tpunet.config import config_from_args
    cfg = config_from_args(["--dataset", "text_lm", "--text-file",
                            "corpus.txt", "--model", "lm"])
    assert cfg.data.dataset == "text_lm"
    assert cfg.data.text_path == "corpus.txt"


@pytest.mark.slow
def test_top_k_and_top_p_sampling(tmp_path):
    """top_k=1 equals greedy regardless of temperature; top_p strictly
    inside (0,1) also constrains to high-probability tokens."""
    import jax
    from tpunet.models import create_model, init_variables
    from tpunet.models.lm import generate

    model = create_model(dataclasses.replace(LM_CFG, vocab_size=32))
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    prompt = np.asarray([[1, 2, 3]], np.int32)
    greedy = np.asarray(generate(model, variables, prompt, 8))
    k1 = np.asarray(generate(model, variables, prompt, 8,
                             temperature=5.0, top_k=1,
                             rng=jax.random.PRNGKey(7)))
    np.testing.assert_array_equal(greedy, k1)
    # tiny nucleus at low temperature behaves greedily too
    p_small = np.asarray(generate(model, variables, prompt, 8,
                                  temperature=0.01, top_p=1e-6,
                                  rng=jax.random.PRNGKey(7)))
    np.testing.assert_array_equal(greedy, p_small)
    # high temperature with a generous nucleus still yields valid tokens
    free = np.asarray(generate(model, variables, prompt, 8,
                               temperature=2.0, top_k=8, top_p=0.9,
                               rng=jax.random.PRNGKey(7)))
    assert free.shape == greedy.shape
    assert (free >= 0).all() and (free < 32).all()


def test_filter_logits_sequential_semantics():
    """Combined top-k + top-p is sequential (HF warpers): the nucleus is
    computed over the RENORMALIZED post-top-k distribution. With probs
    [0.2, 0.1, tail...] and top_k=2, top_p=0.5 the renormalized top
    token carries 0.667 >= 0.5, so ONLY it survives — the old
    intersection semantics (nucleus over the raw distribution) would
    also have kept the second token (raw cumsum 0.2 < 0.5)."""
    import jax.numpy as jnp
    from tpunet.models.lm import filter_logits

    probs = np.full(16, 0.05)
    probs[0], probs[1] = 0.2, 0.1
    probs /= probs.sum()
    lg = jnp.log(jnp.asarray(probs))
    out = np.asarray(filter_logits(lg, top_k=2, top_p=0.5))
    assert np.isfinite(out[0])
    assert not np.isfinite(out[1:]).any()
    # Each filter alone is unchanged by the refactor.
    k_only = np.asarray(filter_logits(lg, top_k=2))
    assert np.isfinite(k_only[:2]).all() and not np.isfinite(k_only[2:]).any()
    p_only = np.asarray(filter_logits(lg, top_p=0.25))
    assert np.isfinite(p_only[0]) and np.isfinite(p_only[1])


def test_prompt_format_flag(tmp_path, capsys):
    """--prompt-format overrides the vocab-size-256 heuristic in both
    directions; 'bytes' with a small vocab is rejected up front."""
    from tpunet.infer import generate as gen
    argv = ["--checkpoint-dir", str(tmp_path / "nope"), "--tokens", "4",
            "--vocab-size", "16", "--max-seq-len", "32"]
    with pytest.raises(SystemExit, match="vocab-size 256"):
        gen.main(argv + ["--prompt-format", "bytes", "--prompt", "hi"])
    with pytest.raises(SystemExit, match="vocab-size 256"):
        gen.main(["--checkpoint-dir", str(tmp_path / "nope"), "--tokens",
                  "4", "--vocab-size", "512", "--max-seq-len", "32",
                  "--prompt-format", "bytes", "--prompt", "hi"])
    # vocab 256 + explicit ids: parsed as token ids, not UTF-8 text.
    argv256 = ["--checkpoint-dir", str(tmp_path / "nope"), "--tokens", "4",
               "--vocab-size", "256", "--max-seq-len", "32",
               "--prompt-format", "ids"]
    with pytest.raises(SystemExit, match="token ids"):
        gen.main(argv256 + ["--prompt", "not numbers"])
    with pytest.raises(SystemExit, match="outside"):
        gen.main(argv256 + ["--prompt", "5 300"])
