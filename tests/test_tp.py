"""Tensor parallelism: path-rule shardings and dp x sp x tp training
parity on the 8-device CPU mesh."""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.parallel import make_mesh
from tpunet.parallel.tp import (VIT_TP_RULES, _spec_for, rules_for,
                                tree_shardings)
from tpunet.train.loop import Trainer

VIT_CFG = ModelConfig(name="vit", vit_patch=4, vit_hidden=64, vit_depth=2,
                      vit_heads=4, dropout_rate=0.0, dtype="float32")


def test_rules_registry():
    assert rules_for(VIT_CFG) == VIT_TP_RULES
    assert rules_for(ModelConfig(name="vit_tiny")) == VIT_TP_RULES
    assert rules_for(ModelConfig(name="mobilenet_v2")) == ()


def _cfg(mesh_cfg, **model_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=32,
                        synthetic_train_size=128, synthetic_test_size=32),
        model=dataclasses.replace(VIT_CFG, **model_kw),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


def test_state_shardings_follow_rules():
    mesh = make_mesh(MeshConfig(data=4, model=2))
    trainer = Trainer(_cfg(MeshConfig(data=4, model=2)), mesh=mesh)
    try:
        params = trainer.state.params
        qkv = params["block00"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.spec == P(None, "model")
        out = params["block00"]["attn"]["out"]["kernel"]
        assert out.sharding.spec == P("model", None)
        assert params["pos_embed"].sharding.spec == P()
        # Adam moments mirror the param tree -> same specs (ZeRO-style
        # optimizer sharding for free).
        mu = trainer.state.opt_state[0].mu
        assert mu["block00"]["attn"]["qkv"]["kernel"].sharding.spec \
            == P(None, "model")
        assert mu["pos_embed"].sharding.spec == P()
    finally:
        trainer.close()


def test_indivisible_rule_falls_back_to_replicated():
    import re
    mesh = make_mesh(MeshConfig(data=4, model=2))
    leaf = np.zeros((4, 7))  # 7 not divisible by model=2
    spec = _spec_for("attn/qkv/kernel",
                     leaf, mesh, [(re.compile(r"qkv/kernel$"),
                                   P(None, "model"))])
    assert spec == P()


def _one_epoch(mesh_cfg, **model_kw):
    trainer = Trainer(_cfg(mesh_cfg, **model_kw))
    try:
        train_m = trainer.train_one_epoch(1)
        eval_m = trainer.evaluate()
    finally:
        trainer.close()
    return train_m, eval_m


@pytest.mark.slow
def test_tp_training_parity():
    base_t, base_e = _one_epoch(MeshConfig(data=2))
    tp_t, tp_e = _one_epoch(MeshConfig(data=2, model=2))
    assert abs(base_t["loss"] - tp_t["loss"]) < 1e-4
    assert abs(base_e["accuracy"] - tp_e["accuracy"]) < 1e-6


@pytest.mark.slow
def test_zero1_shards_moments_and_keeps_parity():
    """ZeRO-1: Adam moments shard over 'data', params stay replicated,
    training math unchanged."""
    base_t, _ = _one_epoch(MeshConfig(data=4))

    mesh = make_mesh(MeshConfig(data=4, zero1=True))
    trainer = Trainer(_cfg(MeshConfig(data=4, zero1=True)), mesh=mesh)
    try:
        z_t = trainer.train_one_epoch(1)
        mu = trainer.state.opt_state[0].mu
        # big kernels shard their leading dim; params stay replicated
        assert mu["block00"]["attn"]["qkv"]["kernel"].sharding.spec \
            == P("data")
        assert trainer.state.params["block00"]["attn"]["qkv"]["kernel"] \
            .sharding.spec == P()
        # leading dim 1 (pos_embed) is indivisible -> replicated
        assert mu["pos_embed"].sharding.spec == P()
    finally:
        trainer.close()
    assert abs(base_t["loss"] - z_t["loss"]) < 1e-4


def test_zero1_composes_with_tp():
    """With model>1 the TP rules win for matched moments; ZeRO-1 takes
    the rest."""
    mesh = make_mesh(MeshConfig(data=2, model=2, zero1=True))
    trainer = Trainer(_cfg(MeshConfig(data=2, model=2, zero1=True)),
                      mesh=mesh)
    try:
        mu = trainer.state.opt_state[0].mu
        assert mu["block00"]["attn"]["qkv"]["kernel"].sharding.spec \
            == P(None, "model")
        assert mu["block00"]["ln1"]["scale"].sharding.spec == P("data")
    finally:
        trainer.close()


@pytest.mark.slow
def test_dp_sp_tp_combined_training_parity():
    """The flagship composition: data=2 x seq=2 x model=2 over 8 devices,
    ring attention + Megatron-style param sharding, exact same math as
    the unsharded dense run."""
    base_t, base_e = _one_epoch(MeshConfig(data=2))
    full_t, full_e = _one_epoch(MeshConfig(data=2, seq=2, model=2),
                                attention="ring")
    assert abs(base_t["loss"] - full_t["loss"]) < 1e-4
    assert abs(base_e["loss"] - full_e["loss"]) < 1e-4
    assert abs(base_e["accuracy"] - full_e["accuracy"]) < 1e-6


def test_pp_stack_spec_matches_storage_rules():
    """pp_stack_spec (what the pipelined models hand the executors as
    shard_map in_specs) must resolve exactly what VIT_PP_RULES stores
    params/moments under — one source of truth, no silent reshards."""
    from jax.sharding import PartitionSpec as P

    from tpunet.parallel.tp import pp_stack_spec

    assert pp_stack_spec("blocks_qkv_k") == P("pipe")
    assert pp_stack_spec("blocks_fc1_k") == P("pipe")
    assert pp_stack_spec("blocks_moe_rk") == P("pipe")   # router repl.
    assert pp_stack_spec("blocks_moe_rb") == P("pipe")
    for leaf in ("wi", "bi", "wo", "bo"):
        assert pp_stack_spec(f"blocks_moe_{leaf}") == P("pipe", "model")
