"""tpucheck (tpunet/analysis/): rule fixtures, baseline semantics,
suppressions, CLI exit codes, and the tree-is-clean gate.

The fixture matrix under tests/fixtures/tpucheck/ carries the repo's
regression history: ``r1_bad_donated_restore`` is the PR-7
donated-orbax-restore heap corruption, ``r2_bad_scopeless_vjp`` the
PR-6 scope-less custom_vjp misattribution — each must stay RED
forever. ``test_tree_is_clean_against_baseline`` is the gate itself:
``python -m tpunet.analysis`` on this repo must exit 0 (clean or
baselined) on every commit.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "tpucheck")

from tpunet.analysis import (ALL_RULES, Project, rules_by_id,  # noqa: E402
                             run_rules)
from tpunet.analysis import baseline as baseline_mod  # noqa: E402
from tpunet.analysis.__main__ import main as tpucheck_main  # noqa: E402


def _run_fixture(case, rule_id):
    root = os.path.join(FIXTURES, case)
    assert os.path.isdir(root), root
    return run_rules(Project(root), [rules_by_id()[rule_id]])


# -- fixture matrix: every bad case fires its rule, every good case is
# clean under it ------------------------------------------------------

BAD_CASES = [
    ("r1_bad_donated_restore", "R1", 1),
    ("r1_bad_io_views", "R1", 2),
    ("r2_bad_scopeless_vjp", "R2", 3),   # fwd + bwd + naked primal kernel
    ("r2_bad_unknown_scope", "R2", 1),
    ("r3_bad_print_time", "R3", 2),
    ("r3_bad_numpy_global", "R3", 3),
    ("r4_bad_thread", "R4", 1),
    ("r4_bad_popen", "R4", 1),
    ("r5_bad_missing_flag", "R5", 1),
    ("r5_bad_missing_docs", "R5", 1),
    ("r6_bad_undocumented", "R6", 1),
    ("r6_bad_fstring", "R6", 1),
    ("r7_bad_cross_module", "R7", 1),
    ("r7_bad_transitive", "R7", 1),
]

GOOD_CASES = [
    ("r1_good_rematerialized", "R1"),
    ("r1_good_device_put", "R1"),
    ("r2_good_lexical", "R2"),
    ("r2_good_wrapper", "R2"),
    ("r3_good_host_side", "R3"),
    ("r3_good_static_numpy", "R3"),
    ("r4_good_registered", "R4"),
    ("r4_good_suppressed", "R4"),
    ("r5_good_wired", "R5"),
    ("r5_good_bool_negation", "R5"),
    ("r6_good_documented", "R6"),
    ("r6_good_dynamic", "R6"),
    ("r7_good_producer_copy", "R7"),
    ("r7_good_callsite_copy", "R7"),
]


@pytest.mark.parametrize("case,rule_id,min_findings", BAD_CASES)
def test_bad_fixture_fires(case, rule_id, min_findings):
    findings = _run_fixture(case, rule_id)
    assert len(findings) >= min_findings, \
        f"{case}: expected >= {min_findings} {rule_id} findings, " \
        f"got {[f.render() for f in findings]}"
    assert all(f.rule == rule_id for f in findings)
    for f in findings:
        assert f.line > 0 and f.path and f.key, f
        assert f.hint, f"finding without a fix hint: {f.render()}"


@pytest.mark.parametrize("case,rule_id", GOOD_CASES)
def test_good_fixture_clean(case, rule_id):
    findings = _run_fixture(case, rule_id)
    assert findings == [], [f.render() for f in findings]


# -- the named regression semantics, not just counts ------------------

def test_pr7_donated_restore_regression():
    """The exact PR-7 shape: restore -> self.state -> donated arg 0 of
    the jitted train step, flagged AT the call site."""
    findings = _run_fixture("r1_bad_donated_restore", "R1")
    f = findings[0]
    assert "restore_state" in f.message
    assert "donated arg 0" in f.message
    assert f.key == "donate:self.train_step<-self.state"
    assert f.path == "tpunet/train/loop.py"


def test_pr6_scopeless_vjp_regression():
    """The PR-6 shape: both custom_vjp halves flagged, the bwd finding
    naming the transpose(-marker gap."""
    findings = _run_fixture("r2_bad_scopeless_vjp", "R2")
    roles = {f.key for f in findings if f.key.startswith("vjp:")}
    assert "vjp:fused_op:fwd:_fwd" in roles
    assert "vjp:fused_op:bwd:_bwd" in roles
    bwd = [f for f in findings if ":bwd:" in f.key][0]
    assert "transpose(" in bwd.message


def test_r2_unknown_scope_names_marker_table():
    findings = _run_fixture("r2_bad_unknown_scope", "R2")
    assert any(f.key == "marker:tpunet_mystery_fwd" for f in findings)
    assert any("KERNEL_SCOPES" in f.message for f in findings)


def test_r3_flags_each_effect_kind():
    kinds = {f.key.split(":")[1]
             for f in (_run_fixture("r3_bad_print_time", "R3")
                       + _run_fixture("r3_bad_numpy_global", "R3"))}
    assert {"print", "time", "numpy", "global"} <= kinds


# -- suppressions and baseline ----------------------------------------

def test_inline_suppression_is_line_scoped(tmp_path):
    proj = tmp_path / "tpunet"
    proj.mkdir()
    (proj / "w.py").write_text(
        "import threading\n"
        "a = threading.Thread(target=print)  # tpucheck: disable=R4\n"
        "b = threading.Thread(target=print)\n")
    findings = run_rules(Project(str(tmp_path)), [rules_by_id()["R4"]])
    assert len(findings) == 1 and findings[0].line == 3


def test_suppression_wrong_rule_does_not_apply(tmp_path):
    proj = tmp_path / "tpunet"
    proj.mkdir()
    (proj / "w.py").write_text(
        "import threading\n"
        "a = threading.Thread(target=print)  # tpucheck: disable=R1\n")
    findings = run_rules(Project(str(tmp_path)), [rules_by_id()["R4"]])
    assert len(findings) == 1


def test_baseline_roundtrip_and_staleness(tmp_path):
    root = os.path.join(FIXTURES, "r1_bad_donated_restore")
    findings = run_rules(Project(root), [rules_by_id()["R1"]])
    assert findings
    path = str(tmp_path / "baseline.json")

    # write-baseline produces TODO entries the loader refuses...
    todo = baseline_mod.write(path, findings, baseline_mod.Baseline())
    assert todo == len({f.identity() for f in findings})
    with pytest.raises(ValueError, match="TODO"):
        baseline_mod.load(path)

    # ...until a human writes the why; then the findings are accepted.
    with open(path) as f:
        data = json.load(f)
    for e in data["entries"]:
        e["why"] = "fixture: intentionally kept"
    with open(path, "w") as f:
        json.dump(data, f)
    bl = baseline_mod.load(path)
    new, accepted, stale = bl.split(findings)
    assert new == [] and len(accepted) == len(findings) and stale == []

    # a fixed tree sheds the entry: same baseline, no findings -> stale
    new, accepted, stale = bl.split([])
    assert new == [] and accepted == [] and len(stale) >= 1


def test_baseline_rejects_unjustified_entries(tmp_path):
    path = str(tmp_path / "b.json")
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": [
            {"rule": "R1", "path": "x.py", "key": "k"}]}, f)
    with pytest.raises(ValueError, match="why"):
        baseline_mod.load(path)


# -- CLI ---------------------------------------------------------------

def test_cli_exit_codes_in_process():
    bad = os.path.join(FIXTURES, "r4_bad_thread")
    good = os.path.join(FIXTURES, "r4_good_registered")
    assert tpucheck_main(["--root", bad, "--baseline", "none"]) == 1
    assert tpucheck_main(["--root", good, "--baseline", "none"]) == 0
    assert tpucheck_main(["--list-rules"]) == 0
    assert tpucheck_main(["--rules", "R9", "--root", good]) == 2


def test_cli_json_output(capsys):
    bad = os.path.join(FIXTURES, "r3_bad_print_time")
    rc = tpucheck_main(["--root", bad, "--baseline", "none", "--json",
                        "--rules", "R3"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"] and payload["baselined"] == []
    assert {"rule", "path", "line", "message", "hint", "key"} <= set(
        payload["findings"][0])


def test_cli_module_entry_subprocess():
    """``python -m tpunet.analysis`` (the doc'd invocation) exits 1 on
    a bad fixture and 0 with --list-rules."""
    env = dict(os.environ, PYTHONPATH=REPO)
    bad = os.path.join(FIXTURES, "r2_bad_scopeless_vjp")
    res = subprocess.run(
        [sys.executable, "-m", "tpunet.analysis", "--root", bad,
         "--baseline", "none"],
        capture_output=True, text=True, env=env, timeout=120)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "[R2]" in res.stdout


def test_parse_error_is_a_finding(tmp_path):
    proj = tmp_path / "tpunet"
    proj.mkdir()
    (proj / "broken.py").write_text("def oops(:\n")
    findings = run_rules(Project(str(tmp_path)), list(ALL_RULES))
    assert any(f.rule == "PARSE" for f in findings)


# -- the gate ---------------------------------------------------------

def test_tree_is_clean_against_baseline():
    """THE tier-1 invariant: tpucheck on this repo exits 0 — every
    finding either fixed or baselined with a justification. Stale
    entries fail too: fixed code must shed its ledger line."""
    rc = tpucheck_main(["--root", REPO, "--strict-baseline"])
    assert rc == 0, "tpucheck found unbaselined findings (or stale " \
                    "baseline entries); run python -m tpunet.analysis"


def test_checked_in_baseline_is_justified():
    bl = baseline_mod.load(os.path.join(REPO, "docs",
                                        "tpucheck_baseline.json"))
    assert bl.entries, "ledger should carry the reviewed exceptions"
    for e in bl.entries:
        assert len(e["why"]) > 20, f"thin justification: {e}"
