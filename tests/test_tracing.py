"""Request tracing (tpunet/obs/tracing.py): trace-id validity, the
deterministic head sampler, breadcrumb wire round-trip through a real
flight-recorder ring, span-record field conditioning, the cross-ring
timeline JOIN (router + replicas on trace_id, failover seam
force-close), the fleet rollup's per-phase SLO decomposition, the
dashboard exemplar panel, and the multi-dir obs_timeline CLI."""

import json
import os
import sys

import pytest

from tpunet.obs.tracing import (build_trace_record, crumb,
                                mint_trace_id, observe_trace,
                                parse_crumb, should_sample,
                                valid_trace_id)

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


# ---------------------------------------------------------------------------
# ids + sampling
# ---------------------------------------------------------------------------

def test_trace_id_mint_and_validity():
    tid = mint_trace_id()
    assert valid_trace_id(tid) and len(tid) == 16
    assert valid_trace_id("0123456789abcdef")
    assert valid_trace_id("a" * 8) and valid_trace_id("a" * 32)
    for bad in (None, "", "xyz", "ABCDEF01", "a" * 7, "a" * 33,
                "0123456789abcde!", "deadbeef\n"):
        assert not valid_trace_id(bad), bad


def test_head_sampling_is_deterministic_in_the_id():
    tid = mint_trace_id()
    assert should_sample(1.0, tid)
    assert not should_sample(0.0, tid)
    # Same id, same verdict — a fleet of routers agrees without
    # coordination.
    assert should_sample(0.5, tid) == should_sample(0.5, tid)
    n = sum(should_sample(0.5, mint_trace_id()) for _ in range(1000))
    assert 350 < n < 650, f"head sampler badly biased: {n}/1000"


# ---------------------------------------------------------------------------
# breadcrumb wire format
# ---------------------------------------------------------------------------

def test_parse_crumb_roundtrip():
    c = parse_crumb("prefill 0123456789abcdef 2 rid=5 b=128")
    assert c == {"verb": "prefill", "trace_id": "0123456789abcdef",
                 "hop": 2, "rid": "5", "b": "128"}
    c = parse_crumb("recv feedc0dedeadbeef 0")
    assert c["verb"] == "recv" and c["hop"] == 0
    for bad in ("", "prefill", "prefill tid", "prefill tid x",
                "prefill tid -1"):
        assert parse_crumb(bad) is None, bad


def test_crumb_survives_the_ring_msg_cap(tmp_path):
    """A crumb written through a REAL ring comes back parseable —
    the 80-byte msg cap must never truncate the (verb, id, hop) key."""
    from tpunet.obs import flightrec
    from tpunet.obs.flightrec.ring import read_ring_file

    rec = flightrec.install(str(tmp_path), watcher=False,
                            native=False)
    try:
        crumb("seam", "f" * 32, 7, tokens=123456,
              rep="replica-name-quite-long")
    finally:
        rec.close()
        flightrec._REC = None     # disarm: other tests expect no-op
    ring = os.path.join(str(tmp_path), "flightrec", "events.ring")
    slots = [s for s in read_ring_file(ring)
             if s["kind"] == "trace"]
    assert slots, "crumb never reached the ring"
    parsed = parse_crumb(slots[-1]["msg"])
    assert parsed is not None
    assert parsed["verb"] == "seam" and parsed["hop"] == 7
    assert parsed["trace_id"] == "f" * 32
    assert parsed["tokens"] == "123456"


# ---------------------------------------------------------------------------
# span records + instruments
# ---------------------------------------------------------------------------

def test_build_trace_record_field_conditioning():
    rec = build_trace_record(
        trace_id="0123456789abcdef", hop=0, role="router",
        finish_reason="length", tokens=24, failover_count=0,
        e2e_s=0.123456789)
    # Zero failovers / absent optionals stay OFF the record.
    assert "failover_count" not in rec
    assert "queue_s" not in rec and "error" not in rec
    assert rec["e2e_s"] == 0.123457           # 6dp rounding
    rec = build_trace_record(
        trace_id="0123456789abcdef", hop=2, role="replica",
        finish_reason="error", queue_s=0.01, prefill_s=0.02,
        prefill_bucket=64, first_decode_s=0.003, tokens=5,
        preemptions=1, preempt_wall_s=0.5, resume_offset=12,
        error="x" * 500)
    assert rec["prefill_bucket"] == 64 and rec["resume_offset"] == 12
    assert rec["preemptions"] == 1
    assert len(rec["error"]) == 200           # truncated, never huge
    with pytest.raises(ValueError):
        build_trace_record(trace_id="t" * 16, hop=0, role="client",
                           finish_reason="length")


def test_observe_trace_feeds_the_trace_instruments():
    from tpunet.obs.registry import Registry

    reg = Registry()
    rec = build_trace_record(
        trace_id="0123456789abcdef", hop=1, role="replica",
        finish_reason="length", queue_s=0.01, prefill_s=0.04,
        first_decode_s=0.002, tokens=8, e2e_s=0.5)
    observe_trace(reg, rec)
    snap = reg.snapshot()
    assert snap["trace_requests_total"] == 1.0
    for phase in ("queue_s", "prefill_s", "first_decode_s", "e2e_s"):
        assert snap[f"trace_{phase}_count"] == 1, phase


# ---------------------------------------------------------------------------
# timeline join
# ---------------------------------------------------------------------------

def _ring_dir(tmp_path, name):
    from tpunet.obs.flightrec.ring import EventRing
    d = tmp_path / name / "flightrec"
    d.mkdir(parents=True)
    return EventRing(str(d / "events.ring"), 64), tmp_path / name


def test_timeline_joins_a_failover_trace_across_rings(tmp_path):
    """Router ring + two replica rings, one trace_id: the join renders
    a relay row, hop 1 cut (force-closed) at the failover seam on the
    SIGKILLed replica, hop 2 resuming on the survivor — one causal
    track across three processes."""
    from tpunet.obs.history import build_timeline

    tid = "abad1deafee1900d"
    router_ring, router_dir = _ring_dir(tmp_path, "router")
    rep0_ring, rep0_dir = _ring_dir(tmp_path, "rep0")
    rep1_ring, rep1_dir = _ring_dir(tmp_path, "rep1")
    router_ring.record("trace", f"recv {tid} 0")
    router_ring.record("trace", f"open {tid} 1 rep=r0")
    rep0_ring.record("trace", f"submit {tid} 1 rid=1")
    rep0_ring.record("trace", f"prefill {tid} 1 rid=1 b=64")
    rep0_ring.record("trace", f"first_token {tid} 1 rid=1")
    # r0 is SIGKILLed: no finish crumb ever lands on hop 1.
    router_ring.record("trace", f"seam {tid} 1 tokens=12 rep=r0")
    router_ring.record("trace", f"open {tid} 2 rep=r1")
    rep1_ring.record("trace", f"submit {tid} 2 rid=1")
    rep1_ring.record("trace", f"resume_prefill {tid} 2 rid=1 b=64")
    rep1_ring.record("trace", f"first_token {tid} 2 rid=1")
    rep1_ring.record("trace", f"finish {tid} 2 rid=1 reason=length")
    router_ring.record("trace", f"finish {tid} 0 reason=length")
    for ring in (router_ring, rep0_ring, rep1_ring):
        ring.close()

    trace = build_timeline([str(router_dir), str(rep0_dir),
                            str(rep1_dir)])
    joined = [e for e in trace["traceEvents"] if e["pid"] == 1]
    assert joined, "no cross-process join emitted"
    rows = {e["args"]["name"] for e in joined
            if e["name"] == "thread_name"}
    short = tid[:8]
    assert {f"trace {short} router", f"trace {short} hop 1",
            f"trace {short} hop 2"} <= rows
    data = [e for e in joined
            if e.get("args", {}).get("trace_id") == tid]
    relay = next(e for e in data if e["name"] == "relay")
    assert relay["ph"] == "X" and relay["dur"] > 0
    assert relay["args"]["finish_reason"] == "length"
    # Hop 1: the orphaned lifecycle is force-closed AT the seam.
    hop1 = [e for e in data
            if e.get("args", {}).get("replica") == "r0"]
    assert any(e.get("args", {}).get("force_closed")
               == "failover_seam" for e in hop1)
    seam = next(e for e in data if e["name"] == "seam")
    hop1_decode = next(e for e in hop1 if e["name"] == "decode")
    assert hop1_decode["ts"] + hop1_decode["dur"] \
        == pytest.approx(seam["ts"], abs=1.0)
    assert hop1_decode["args"]["tokens_relayed"] == "12"
    # Hop 2: the resume renders as its own phase on the survivor.
    hop2 = [e for e in data
            if e.get("args", {}).get("replica") == "r1"]
    assert any(e["name"] == "resume_prefill" and e["ph"] == "X"
               for e in hop2)
    assert {"r0", "r1"} == {e["args"]["replica"] for e in data
                            if e.get("args", {}).get("replica")}


def test_engine_resume_lifecycle_breadcrumbs(tmp_path):
    """PR-13 gap closed: a per-process ring whose request RESUMED
    (resume + resume_prefill verbs, no plain prefill) still renders a
    full queue/prefill/decode lifecycle instead of an orphan."""
    from tpunet.obs.flightrec.ring import EventRing
    from tpunet.obs.history import build_timeline

    d = tmp_path / "run" / "flightrec"
    d.mkdir(parents=True)
    ring = EventRing(str(d / "events.ring"), 64)
    ring.record("req", "submit 3 len=17")
    ring.record("req", "resume 3 off=12")
    ring.record("req", "resume_prefill 3")
    ring.record("req", "first_token 3")
    ring.record("req", "finish 3 length")
    ring.close()
    trace = build_timeline([str(tmp_path / "run")])
    phases = {e["name"] for e in trace["traceEvents"]
              if e["ph"] == "X" and e.get("args", {}).get("req") == "3"}
    assert phases == {"queue", "prefill", "decode"}
    assert any(e["ph"] == "i" and e["name"] == "resume"
               for e in trace["traceEvents"])


# ---------------------------------------------------------------------------
# fleet rollup + dashboard
# ---------------------------------------------------------------------------

def _trace_stream(run_id, e2es):
    recs = []
    for i, e2e in enumerate(e2es):
        recs.append({"kind": "obs_trace", "run_id": run_id,
                     "process_index": 0,
                     "trace_id": f"{i:016x}", "hop": 1,
                     "role": "replica", "finish_reason": "length",
                     "queue_s": 0.01 * (i + 1), "prefill_s": 0.02,
                     "first_decode_s": 0.001, "tokens": 8,
                     "e2e_s": e2e})
    return recs


def test_rollup_trace_decomposition_and_slow_exemplars():
    from tpunet.obs.agg import Aggregator

    agg = Aggregator()
    recs = _trace_stream("a", [0.1, 0.9, 0.5]) \
        + _trace_stream("b", [0.3, 0.7])
    for r in recs:
        agg.ingest(r)
    rollup = agg.rollup()
    assert rollup["trace_records_total"] == 5
    assert rollup["trace_queue_p50_s"] is not None
    assert rollup["trace_prefill_p99_s"] == pytest.approx(0.02)
    slow = rollup["trace_slow"]
    assert [t["e2e_s"] for t in slow] \
        == sorted((t["e2e_s"] for t in slow), reverse=True)
    assert slow[0]["e2e_s"] == 0.9
    # Replay purity: ingest order must not change the rollup.
    agg2 = Aggregator()
    for r in reversed(recs):
        agg2.ingest(r)
    assert agg2.rollup()["trace_slow"] == slow


def test_dashboard_renders_slow_trace_exemplars():
    from tpunet.obs.agg import Aggregator

    sys.path.insert(0, SCRIPTS)
    try:
        dash = __import__("obs_dashboard")
    finally:
        sys.path.pop(0)
    agg = Aggregator()
    for r in _trace_stream("a", [0.1, 0.9]):
        agg.ingest(r)
    rollup = agg.rollup()
    frame = dash.render_fleet_terminal(rollup, {}, "test")
    assert "trace:" in frame
    assert f"{1:016x}" in frame          # the slowest span's id
    assert "queue" in frame and "prefill" in frame
    html = dash.render_fleet_html(rollup, [], "test")
    assert "Slow-request exemplars" in html
    assert f"{1:016x}" in html


# ---------------------------------------------------------------------------
# obs_timeline CLI: repeatable --metrics-dir
# ---------------------------------------------------------------------------

def test_obs_timeline_cli_merges_multiple_metrics_dirs(tmp_path,
                                                       capsys):
    sys.path.insert(0, SCRIPTS)
    try:
        cli = __import__("obs_timeline")
    finally:
        sys.path.pop(0)
    tid = "0123456789abcdef"
    r1, d1 = _ring_dir(tmp_path, "router")
    r2, d2 = _ring_dir(tmp_path, "rep0")
    r1.record("trace", f"recv {tid} 0")
    r1.record("trace", f"open {tid} 1 rep=r0")
    r2.record("trace", f"submit {tid} 1 rid=1")
    r2.record("trace", f"finish {tid} 1 rid=1 reason=length")
    r1.record("trace", f"finish {tid} 0 reason=length")
    r1.close()
    r2.close()
    out = tmp_path / "trace.json"
    rc = cli.main(["--metrics-dir", str(d1), "--metrics-dir", str(d2),
                   "-o", str(out)])
    assert rc == 0
    trace = json.loads(out.read_text())
    pids = {e["pid"] for e in trace["traceEvents"]}
    assert 1 in pids and len(pids) >= 3   # join + both real rings
    assert any(e["name"] == "relay" for e in trace["traceEvents"])
    # A dangling --metrics-dir is a loud usage error.
    assert cli.main(["--metrics-dir"]) == 2
    capsys.readouterr()
