"""Training-core tests: optimization stack, step semantics, device-count
invariance (the TPU analogue of the reference's serial-vs-distributed
accuracy parity check, SURVEY.md section 4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpunet.config import (DataConfig, MeshConfig, ModelConfig, OptimConfig,
                           TrainConfig, CheckpointConfig)
from tpunet.data.cifar10 import synthetic_cifar10
from tpunet.parallel import make_mesh
from tpunet.train.loop import Trainer
from tpunet.train.state import lr_schedule
from tpunet.utils.prng import step_key


def tiny_config(tmpdir, batch=16, epochs=1, image_size=32):
    # Stochastic augmentations off: these tests validate optimization and
    # device-count invariance, not augmentation (covered in test_data).
    return TrainConfig(
        epochs=epochs,
        seed=42,
        data=DataConfig(dataset="synthetic", image_size=image_size,
                        batch_size=batch, rrc_scale=(1.0, 1.0),
                        rrc_ratio=(1.0, 1.0), jitter_brightness=0.0,
                        jitter_contrast=0.0, jitter_saturation=0.0,
                        jitter_hue=0.0, rotation_degrees=0.0),
        model=ModelConfig(dtype="float32", width_mult=0.5),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=str(tmpdir), save_best=False,
                                    save_last=False),
    )


@pytest.fixture(scope="module")
def tiny_dataset():
    return synthetic_cifar10(n_train=128, n_test=48, seed=7)


def test_steplr_schedule_matches_reference():
    # StepLR(step_size=10, gamma=0.1): lr 1e-4 for epochs 1-10, 1e-5 for
    # 11-20 (reference :149). 5 steps/epoch here.
    sched = lr_schedule(OptimConfig(), steps_per_epoch=5, epochs=20)
    assert np.isclose(sched(0), 1e-4)
    assert np.isclose(sched(49), 1e-4)       # end of epoch 10
    assert np.isclose(sched(50), 1e-5)       # start of epoch 11
    assert np.isclose(sched(99), 1e-5)


@pytest.mark.slow
def test_train_loss_decreases(tmp_path, tiny_dataset):
    cfg = tiny_config(tmp_path, epochs=3)
    t = Trainer(cfg, dataset=tiny_dataset)
    hist = t.train()
    assert len(hist) == 3
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert all(np.isfinite(h["train_loss"]) for h in hist)
    # Separable synthetic data: should beat the 10% random baseline fast.
    assert hist[-1]["train_accuracy"] > 0.2


def test_eval_counts_exact(tmp_path, tiny_dataset):
    cfg = tiny_config(tmp_path)
    t = Trainer(cfg, dataset=tiny_dataset)
    m = t.evaluate()
    assert m["count"] == 48  # exact despite batch padding (48 = 3*16)


@pytest.mark.slow
def test_metrics_identical_across_mesh_sizes(tmp_path, tiny_dataset):
    """Same global batch => same loss whether on 1 device or 8 (the
    reference validated distributed correctness by accuracy parity)."""
    cfg = tiny_config(tmp_path, batch=16, epochs=1)
    t1 = Trainer(cfg.replace(mesh=MeshConfig(data=1)), dataset=tiny_dataset)
    t8 = Trainer(cfg.replace(mesh=MeshConfig(data=8)), dataset=tiny_dataset)
    # Identical initial states => eval parity is tight (differences are
    # only float reduction order across device topologies).
    e1 = t1.evaluate()
    e8 = t8.evaluate()
    assert e1["count"] == e8["count"] == 48
    assert np.isclose(e1["loss"], e8["loss"], rtol=1e-4)
    assert np.isclose(e1["accuracy"], e8["accuracy"], atol=1e-6)
    # After a full epoch of updates, reduction-order noise is amplified
    # through Adam (eps=1e-8); parity is statistical, like the
    # reference's serial-vs-distributed accuracy comparison.
    m1 = t1.train_one_epoch(0)
    m8 = t8.train_one_epoch(0)
    assert m1["count"] == m8["count"]
    assert np.isclose(m1["loss"], m8["loss"], rtol=2e-2)
    assert np.isclose(m1["accuracy"], m8["accuracy"], atol=0.08)


def test_step_rng_differs_per_step():
    assert not np.array_equal(
        jax.random.key_data(step_key(42, 0)),
        jax.random.key_data(step_key(42, 1)))
