"""Type gate (scripts/check_types.py) as a non-slow test.

Layer 1 (mypy, pyproject ``[tool.mypy]``) runs when mypy is
installed; layer 2 (AST annotation coverage over ``tpunet/analysis``
fully and ``tpunet/obs/flightrec`` public surface) always runs — so
annotations can't rot even on hosts without a checker, and the day
mypy does run it has a fully-annotated tree to check.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import check_types  # noqa: E402


def test_annotation_coverage_clean():
    gaps = check_types.annotation_gaps()
    assert gaps == [], "annotation gaps (see scripts/check_types.py):\n" \
        + "\n".join(gaps)


def test_annotation_checker_detects_gaps(tmp_path, monkeypatch):
    """The floor actually measures something: an unannotated def in a
    target dir must be reported."""
    target = tmp_path / "tpunet" / "analysis"
    target.mkdir(parents=True)
    (target / "loose.py").write_text("def f(x):\n    return x\n")
    monkeypatch.setattr(check_types, "REPO", str(tmp_path))
    gaps = check_types.annotation_gaps()
    assert len(gaps) == 1
    assert "param 'x'" in gaps[0] and "return" in gaps[0]


def test_gate_cli():
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_types.py")],
        capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "check_types: OK" in res.stdout
    if "mypy is not installed" in res.stdout:
        # the skip must be loud, never silent
        assert "SKIPPED" in res.stdout


def test_mypy_config_present():
    with open(os.path.join(REPO, "pyproject.toml")) as f:
        text = f.read()
    assert "[tool.mypy]" in text
    assert "tpunet/analysis" in text and "tpunet/obs/flightrec" in text
    assert "disallow_untyped_defs" in text
