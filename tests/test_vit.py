"""ViT model family: shapes, registry dispatch, attention impl parity,
and end-to-end training through the model-agnostic Trainer."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.models import create_model, init_variables, num_params

VIT_CFG = ModelConfig(name="vit", vit_patch=4, vit_hidden=64, vit_depth=2,
                      vit_heads=4, dropout_rate=0.0, dtype="float32")


def _vars(cfg=VIT_CFG, size=32):
    model = create_model(cfg)
    return model, init_variables(model, jax.random.PRNGKey(0),
                                 image_size=size)


@pytest.mark.slow
def test_forward_shapes_and_no_batch_stats():
    model, variables = _vars()
    assert "batch_stats" not in variables
    x = jnp.zeros((3, 32, 32, 3), jnp.float32)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (3, 10)
    assert logits.dtype == jnp.float32


def test_registry_dispatch_and_presets():
    tiny = create_model(ModelConfig(name="vit_tiny"))
    assert (tiny.patch_size, tiny.hidden, tiny.depth, tiny.heads) == \
        (16, 192, 12, 3)
    with pytest.raises(ValueError):
        create_model(ModelConfig(name="nope"))


def test_param_count_scales_with_depth():
    _, v2 = _vars(dataclasses.replace(VIT_CFG, vit_depth=2))
    _, v4 = _vars(dataclasses.replace(VIT_CFG, vit_depth=4))
    assert num_params(v4["params"]) > num_params(v2["params"])


def test_indivisible_patch_raises():
    model, variables = _vars()
    with pytest.raises(ValueError):
        model.apply(variables, jnp.zeros((1, 30, 30, 3)), train=False)


@pytest.mark.slow
def test_blockwise_attention_matches_dense():
    dense_model, variables = _vars()
    bw_cfg = dataclasses.replace(VIT_CFG, attention="blockwise",
                                 attention_block=16)
    bw_model = create_model(bw_cfg)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    a = dense_model.apply(variables, x, train=False)
    b = bw_model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


def _train_cfg(**model_kw):
    model = dataclasses.replace(VIT_CFG, **model_kw)
    return TrainConfig(
        epochs=2,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=32,
                        synthetic_train_size=128, synthetic_test_size=32),
        model=model,
        optim=OptimConfig(learning_rate=1e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


@pytest.mark.slow
def test_remat_same_logits_and_gradients():
    """nn.remat blocks: identical forward and grads, less live memory."""
    plain = create_model(VIT_CFG)
    remat = create_model(dataclasses.replace(VIT_CFG, remat=True))
    variables = init_variables(plain, jax.random.PRNGKey(0), image_size=32)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    np.testing.assert_allclose(
        np.asarray(plain.apply(variables, x, train=False)),
        np.asarray(remat.apply(variables, x, train=False)),
        rtol=1e-6, atol=1e-6)

    def loss(m):
        return lambda p: jnp.sum(
            m.apply({"params": p}, x, train=False) ** 2)

    g1 = jax.grad(loss(plain))(variables["params"])
    g2 = jax.grad(loss(remat))(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_vit_trains_through_trainer():
    from tpunet.train.loop import Trainer
    trainer = Trainer(_train_cfg())
    try:
        m0 = trainer.train_one_epoch(1)
        m1 = trainer.train_one_epoch(2)
        ev = trainer.evaluate()
    finally:
        trainer.close()
    assert np.isfinite(m0["loss"]) and np.isfinite(m1["loss"])
    assert m1["loss"] < m0["loss"] + 0.5  # training is not diverging
    assert ev["count"] == 32


@pytest.mark.slow
def test_vit_ring_attention_through_trainer_matches_dense():
    """Full jitted train step with ring attention over a ('data','seq')
    mesh == the dense-attention step on the same data (task: sequence
    parallelism is exact, not approximate)."""
    import numpy as np
    from jax.sharding import Mesh

    from tpunet.train.loop import Trainer

    dense_tr = Trainer(_train_cfg())
    try:
        dense_m = dense_tr.train_one_epoch(1)
    finally:
        dense_tr.close()

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "seq"))
    ring_tr = Trainer(_train_cfg(attention="ring"), mesh=mesh)
    try:
        ring_m = ring_tr.train_one_epoch(1)
    finally:
        ring_tr.close()
    assert abs(dense_m["loss"] - ring_m["loss"]) < 1e-4
    assert abs(dense_m["accuracy"] - ring_m["accuracy"]) < 1e-6
