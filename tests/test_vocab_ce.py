"""Vocab-sharded cross-entropy (tpunet/ops/vocab_ce.py): parity with
the full-logits path (values, hits, grads), the XLA memory-analysis
peak drop at a 32k vocab, resolution rules, and end-to-end Trainer
integration for lm and lm_pp."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.models import create_model, init_variables
from tpunet.ops.vocab_ce import resolve_vocab_ce, vocab_parallel_ce
from tpunet.parallel import make_mesh


def _case(B=4, T=9, C=16, V=64, seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(B, T, C)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(V, C)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)
    return h, emb, tgt


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_vocab_parallel_ce_matches_full_logits(smoothing):
    """ce, hit, and the h/emb gradients all match the materialized
    optax path at 1e-6-level tolerance on a dp2 x vp4 mesh."""
    h, emb, tgt = _case()
    mesh = make_mesh(MeshConfig(data=2, model=4))

    def full(h, emb):
        lg = jnp.einsum("btc,vc->btv", h, emb)
        if smoothing > 0:
            ce = optax.softmax_cross_entropy(
                lg, optax.smooth_labels(
                    jax.nn.one_hot(tgt, lg.shape[-1]), smoothing))
        else:
            ce = optax.softmax_cross_entropy_with_integer_labels(lg, tgt)
        return ce, (jnp.argmax(lg, -1) == tgt).astype(jnp.float32)

    def sharded(h, emb):
        with mesh:
            return vocab_parallel_ce(h, emb, tgt, mesh,
                                     smoothing=smoothing)

    ce_f, hit_f = full(h, emb)
    ce_s, hit_s = sharded(h, emb)
    np.testing.assert_allclose(np.asarray(ce_s), np.asarray(ce_f),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(hit_s), np.asarray(hit_f))

    g_f = jax.grad(lambda a: full(*a)[0].mean(), allow_int=True)((h, emb))
    g_s = jax.grad(lambda a: sharded(*a)[0].mean(),
                   allow_int=True)((h, emb))
    for a, b in zip(jax.tree_util.tree_leaves(g_s),
                    jax.tree_util.tree_leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_resolve_vocab_ce():
    mesh = make_mesh(MeshConfig(data=2, model=4))
    assert resolve_vocab_ce("auto", mesh, 64) == "sharded"
    assert resolve_vocab_ce("auto", mesh, 63) == "full"
    assert resolve_vocab_ce("auto", None, 64) == "full"
    assert resolve_vocab_ce("full", mesh, 64) == "full"
    assert resolve_vocab_ce("sharded", mesh, 64) == "sharded"
    with pytest.raises(ValueError, match="divides"):
        resolve_vocab_ce("sharded", mesh, 63)
    with pytest.raises(ValueError, match="divides"):
        resolve_vocab_ce("sharded", None, 64)
    with pytest.raises(ValueError, match="unknown"):
        resolve_vocab_ce("nope", mesh, 64)
    mesh1 = make_mesh(MeshConfig(data=8))
    assert resolve_vocab_ce("auto", mesh1, 64) == "full"


def test_vocab_ce_peak_memory_drops_at_32k_vocab():
    """The documented claim: at V=32k the [B, T, V] float32 logits are
    the train step's largest tensor; sharding them over vp=4 drops the
    loss+grad program's temp allocation by ~vp. Both programs get the
    same batch sharding (h over 'data'), so the delta isolates the
    vocab dim."""
    V, C, B, T = 32768, 64, 8, 64
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(B, T, C)), jnp.float32)
    emb = jnp.asarray(rng.normal(0, 0.1, (V, C)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)
    mesh = make_mesh(MeshConfig(data=2, model=4))
    in_sh = (NamedSharding(mesh, P("data")), NamedSharding(mesh, P()),
             NamedSharding(mesh, P("data")))

    def loss_full(h, emb, tgt):
        lg = jnp.einsum("btc,vc->btv", h, emb)
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, tgt).mean()

    def loss_sharded(h, emb, tgt):
        ce, _ = vocab_parallel_ce(h, emb, tgt, mesh)
        return ce.mean()

    def temp_bytes(fn):
        with mesh:
            c = jax.jit(jax.grad(fn, argnums=(0, 1)),
                        in_shardings=in_sh).lower(h, emb, tgt).compile()
        m = c.memory_analysis()
        return None if m is None else m.temp_size_in_bytes

    t_full = temp_bytes(loss_full)
    t_sharded = temp_bytes(loss_sharded)
    if t_full is None or t_sharded is None:
        pytest.skip("memory_analysis unavailable on this backend")
    assert t_sharded < 0.5 * t_full, (
        f"sharded CE temp {t_sharded} not < 50% of full-logits temp "
        f"{t_full}")


LM_CFG = ModelConfig(name="lm", vit_hidden=32, vit_depth=2, vit_heads=2,
                     dropout_rate=0.0, dtype="float32", vocab_size=64,
                     max_seq_len=32)


@pytest.mark.slow
@pytest.mark.parametrize("name,mesh_cfg", [
    ("lm", MeshConfig(data=2, model=2)),
    ("lm_pp", MeshConfig(data=2, pipe=2, model=2)),
])
def test_lm_loss_grads_match_full_logits(name, mesh_cfg):
    """End-to-end parity through the models: CE from return_hidden +
    vocab_parallel_ce == CE from the model's own logits — same value,
    same grads for every param (embedding included: its cotangent sums
    the input-lookup and output-projection paths)."""
    mesh = make_mesh(mesh_cfg)
    cfg = dataclasses.replace(LM_CFG, name=name, vit_heads=2,
                              pp_microbatches=2)
    model = create_model(cfg, mesh=mesh)
    variables = init_variables(model, jax.random.PRNGKey(0),
                               batch_size=4, seq_len=16)
    toks = jnp.asarray(np.random.default_rng(7).integers(0, 64, (4, 16)),
                       jnp.int32)

    def loss_full(p):
        lg = model.apply({"params": p}, toks)[:, :-1]
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, toks[:, 1:]).mean()

    def loss_sharded(p):
        hdn = model.apply({"params": p}, toks, return_hidden=True)
        ce, _ = vocab_parallel_ce(hdn[:, :-1], p["embed"]["embedding"],
                                  toks[:, 1:], mesh)
        return ce.mean()

    with mesh:
        v_f, g_f = jax.value_and_grad(loss_full)(variables["params"])
        v_s, g_s = jax.value_and_grad(loss_sharded)(variables["params"])
    np.testing.assert_allclose(float(v_s), float(v_f), rtol=1e-6)
    for (pth, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(g_s),
                                jax.tree_util.tree_leaves_with_path(g_f)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
            err_msg=f"{name}: {jax.tree_util.keystr(pth)}")


@pytest.mark.slow
def test_trainer_sharded_ce_matches_full():
    """One epoch of the lm through the Trainer on dp2 x tp2: --vocab-ce
    sharded vs full agree on loss/accuracy (single epoch: float
    reduction order differs, so tolerances are loose-tight, not
    bitwise), and auto resolves to sharded on this mesh."""
    from tpunet.data.lm import synthetic_lm
    from tpunet.train.loop import Trainer

    def run(vocab_ce):
        sb = 8
        cfg = TrainConfig(
            epochs=1,
            data=DataConfig(dataset="synthetic_lm", batch_size=sb,
                            seq_len=32, vocab_size=32),
            model=ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                              vit_heads=2, dropout_rate=0.0,
                              dtype="float32", vocab_size=32,
                              max_seq_len=32, vocab_ce=vocab_ce),
            optim=OptimConfig(learning_rate=3e-3, schedule="constant"),
            mesh=MeshConfig(data=2, model=2),
            checkpoint=CheckpointConfig(save_best=False, save_last=False),
        )
        tr = Trainer(cfg, dataset=synthetic_lm(2 * sb, sb, seq_len=32,
                                               vocab=32))
        try:
            m = tr.train_one_epoch(1)
            e = tr.evaluate()
        finally:
            tr.close()
        return m, e

    m_f, e_f = run("full")
    m_s, e_s = run("sharded")
    assert abs(m_s["loss"] - m_f["loss"]) < 1e-4
    assert abs(e_s["loss"] - e_f["loss"]) < 1e-4
    assert abs(e_s["accuracy"] - e_f["accuracy"]) < 1e-6
