"""Torch-side MobileNetV2 used ONLY as a test oracle for the weight
converter (torchvision is not installed in this environment).

Built from the MobileNetV2 paper recipe with module nesting chosen to
reproduce torchvision's state_dict key naming (``features.0.0.weight``,
``features.N.conv...``, ``classifier.1.weight``), so the converter is
exercised against the exact key layout it must handle in production.
"""

from __future__ import annotations

import torch
from torch import nn

SETTINGS = [
    (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
    (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
]


def conv_bn_relu(cin, cout, k, stride=1, groups=1):
    return nn.Sequential(
        nn.Conv2d(cin, cout, k, stride, (k - 1) // 2, groups=groups, bias=False),
        nn.BatchNorm2d(cout),
        nn.ReLU6(inplace=True),
    )


class TorchInvertedResidual(nn.Module):
    def __init__(self, cin, cout, stride, expand):
        super().__init__()
        hidden = cin * expand
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand != 1:
            layers.append(conv_bn_relu(cin, hidden, 1))
        layers.extend([
            conv_bn_relu(hidden, hidden, 3, stride, groups=hidden),
            nn.Conv2d(hidden, cout, 1, bias=False),
            nn.BatchNorm2d(cout),
        ])
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        y = self.conv(x)
        return x + y if self.use_res else y


class TorchMobileNetV2(nn.Module):
    def __init__(self, num_classes=10):
        super().__init__()
        features = [conv_bn_relu(3, 32, 3, 2)]
        cin = 32
        for t, c, n, s in SETTINGS:
            for i in range(n):
                features.append(
                    TorchInvertedResidual(cin, c, s if i == 0 else 1, t))
                cin = c
        features.append(conv_bn_relu(cin, 1280, 1))
        self.features = nn.Sequential(*features)
        self.classifier = nn.Sequential(nn.Dropout(0.2), nn.Linear(1280, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.mean(dim=(2, 3))
        return self.classifier(x)
