"""tpunet — a TPU-native distributed training framework.

Rebuilds the capabilities of the reference project "Distributed AI Model
Training using MPI and GPU Acceleration" (C-DAC PG-HPC diploma project:
MobileNetV2 transfer learning on CIFAR-10 at 224x224 run serial / single
accelerator / distributed data-parallel, plus top-k inference behind a web
app and cluster launchers) as an idiomatic JAX/XLA framework:

- ``tpunet.config``   — dataclass config with the reference hyperparameter
  defaults (224px, batch 64/128, Adam 1e-4, StepLR(10, 0.1), 20 epochs,
  seed 42; cf. reference cifar10_mpi_mobilenet_224.py:58,70,117,147-149,158).
- ``tpunet.models``   — Flax MobileNetV2 + torch-state_dict weight converter.
- ``tpunet.data``     — CIFAR-10 loading, per-host sharding iterator, and
  fully on-device fused augmentation (replaces torchvision transforms +
  DataLoader workers; cf. reference :68-133).
- ``tpunet.train``    — jitted train/eval steps, metrics, epoch loop with
  best-checkpoint tracking (cf. reference :163-240).
- ``tpunet.parallel`` — device mesh / sharding / multi-host bootstrap
  (replaces mpi4py + torch.distributed NCCL; cf. reference :22-48).
- ``tpunet.ckpt``     — Orbax best-params + full-state save/resume
  (upgrade over reference's torch.save-at-end, :238-249).
- ``tpunet.infer``    — jitted top-k inference + (optional) Gradio app
  (cf. reference cifar10_serial_mobilenet_224.py:159-188, GROUP03.pdf
  pp.22-23).
"""

__version__ = "0.1.0"

