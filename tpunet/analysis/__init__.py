"""tpucheck — repo-native JAX/TPU static analysis.

The correctness-tooling leg next to the perf and observability legs:
an AST-based checker whose rules encode this repo's own failure
history as machine-enforced invariants, so the bug classes that cost
whole debugging rounds can't ship twice:

- **R1 donation-aliasing** — IO-origin arrays (orbax restore, np
  loads, dlpack/ctypes views) passed into ``donate_argnums`` jitted
  callables without re-materialization: the exact PR-7 resume
  heap-corruption class (root-caused with the flight recorder after
  three rounds of misattribution to the native prefetcher).
- **R2 named-scope coverage** — every Pallas kernel call and
  custom_vjp fwd/bwd body in ``tpunet/ops/`` must sit under a
  ``tpunet_*`` named scope that ``tpunet/obs/hlo_bytes.py``'s marker
  table knows, so byte/phase attribution can't silently rot (the
  PR-6 scope-misattribution class).
- **R3 host side-effects inside jit** — ``print`` / ``time.*`` /
  global mutation / numpy ops on traced values inside
  jit/shard_map/pallas bodies (they run once at trace time, then
  silently never again).
- **R4 thread-registry enforcement** — every ``threading.Thread`` /
  ``subprocess.Popen`` spawn in ``tpunet/`` registers with the
  flightrec ``THREADS`` registry (PR-7's host-thread inventory) or is
  explicitly allowlisted: an unregistered thread is invisible to
  crash forensics and the ``thread_stalled`` watchdog.
- **R5 config/CLI/docs drift** — every ``ObsConfig`` / ``ModelConfig``
  / ``ServeConfig`` field has a wired CLI flag and a docs mention.

Run ``python -m tpunet.analysis`` (or ``scripts/tpucheck.py``).
Accepted findings live in ``docs/tpucheck_baseline.json`` with a
one-line justification each; line-level escapes use
``# tpucheck: disable=R3`` comments. docs/static_analysis.md is the
full catalog.
"""

from __future__ import annotations

from tpunet.analysis.baseline import Baseline
from tpunet.analysis.core import Finding, Project, Rule, run_rules
from tpunet.analysis.rules import ALL_RULES, rules_by_id

__all__ = [
    "ALL_RULES", "Baseline", "Finding", "Project", "Rule",
    "run_rules", "rules_by_id",
]
