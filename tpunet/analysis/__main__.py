"""tpucheck CLI: ``python -m tpunet.analysis`` (docs/static_analysis.md).

Exit codes: 0 = clean (or every finding baselined), 1 = new findings
(or stale baseline entries under ``--strict-baseline``), 2 = usage or
internal error. ``--write-baseline`` accepts the current findings into
the ledger (preserving existing justifications; new entries get a
``TODO: justify`` a human must replace before the baseline loads).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from tpunet.analysis import baseline as baseline_mod
from tpunet.analysis.core import Finding, Project, run_rules
from tpunet.analysis.rules import ALL_RULES, rules_by_id

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join("docs", "tpucheck_baseline.json")


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpunet.analysis",
        description="tpucheck: repo-native JAX/TPU static analysis "
                    "(rule catalog in docs/static_analysis.md)")
    p.add_argument("--root", default=_REPO_ROOT,
                   help="tree to analyze (default: this repo)")
    p.add_argument("--baseline", default=None, metavar="PATH|none",
                   help="accepted-findings ledger (default: "
                        f"<root>/{DEFAULT_BASELINE}; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept the current findings into the baseline "
                        "(existing justifications preserved; new "
                        "entries need a human-written 'why')")
    p.add_argument("--rules", default=None, metavar="R1,R2",
                   help="comma-separated rule ids to run (default all)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings on stdout")
    p.add_argument("--strict-baseline", action="store_true",
                   help="stale baseline entries fail the run (fixed "
                        "code must shed its entry)")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_argparser().parse_args(argv)
    by_id = rules_by_id()
    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.name}: {rule.doc}")
        return 0
    rules = list(ALL_RULES)
    if args.rules:
        wanted = [r.strip().upper() for r in args.rules.split(",")
                  if r.strip()]
        unknown = [w for w in wanted if w not in by_id]
        if unknown:
            print(f"tpucheck: unknown rule id(s): {', '.join(unknown)} "
                  f"(have {', '.join(by_id)})", file=sys.stderr)
            return 2
        rules = [by_id[w] for w in wanted]
    root = os.path.abspath(args.root)
    if not os.path.isdir(root):
        print(f"tpucheck: --root {root} is not a directory",
              file=sys.stderr)
        return 2

    project = Project(root)
    findings = run_rules(project, rules)

    baseline_path: Optional[str]
    if args.baseline == "none":
        baseline_path = None
    elif args.baseline is not None:
        baseline_path = args.baseline
    else:
        baseline_path = os.path.join(root, DEFAULT_BASELINE)
    try:
        bl = (baseline_mod.load(baseline_path) if baseline_path
              else baseline_mod.Baseline())
    except ValueError as e:
        print(f"tpucheck: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        if baseline_path is None:
            print("tpucheck: --write-baseline needs a baseline path",
                  file=sys.stderr)
            return 2
        todo = baseline_mod.write(baseline_path, findings, bl)
        print(f"tpucheck: wrote {len(findings)} entries to "
              f"{baseline_path}"
              + (f" ({todo} need a human-written 'why' before the "
                 "baseline will load)" if todo else ""))
        return 0

    new, accepted, stale = bl.split(findings)
    # A --rules subset run never produces other rules' findings; their
    # baseline entries are unevaluated, not stale.
    run_ids = {r.id for r in rules}
    stale = [e for e in stale if e["rule"] in run_ids]

    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in accepted],
            "stale_baseline_entries": stale,
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        for entry in stale:
            print(f"tpucheck: STALE baseline entry: {entry['rule']} "
                  f"{entry['path']} ({entry['key']}) — the finding no "
                  "longer occurs; drop the entry", file=sys.stderr)
        n_files = len(project.files())
        print(f"tpucheck: {len(new)} new finding(s), {len(accepted)} "
              f"baselined, {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} across {n_files} "
              f"files [{', '.join(r.id for r in rules)}]")
    if new:
        return 1
    if stale and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
