"""tpucheck baseline: accepted findings, each with a justification.

The baseline (``docs/tpucheck_baseline.json``) is the reviewed debt
ledger: a finding listed there is *intentionally kept*, and the entry
says why in one line. Matching is on ``(rule, path, key)`` — keys are
rule-generated stable identities with no line numbers in them, so an
accepted finding survives unrelated edits to the same file but a NEW
instance of the same rule in the same file still fails the gate.

Two staleness guarantees keep the ledger honest:

- an entry whose finding no longer occurs is reported as *stale*
  (fixed code must shed its baseline entry in the same change);
- ``--write-baseline`` regenerates entries from the current findings
  but preserves the ``why`` of entries that still match, and refuses
  to invent justifications (new entries get ``TODO: justify`` which
  the loader rejects — a human must write the reason).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set, Tuple

from tpunet.analysis.core import Finding

VERSION = 1
TODO_WHY = "TODO: justify"


@dataclass
class Baseline:
    """In-memory baseline: entries keyed by finding identity."""

    path: str = ""
    entries: List[Dict[str, str]] = field(default_factory=list)

    def _index(self) -> Dict[Tuple[str, str, str], Dict[str, str]]:
        return {(e["rule"], e["path"], e["key"]): e for e in self.entries}

    def matches(self, finding: Finding) -> bool:
        return finding.identity() in self._index()

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[Dict[str, str]]]:
        """(new, accepted, stale_entries) for a findings list."""
        index = self._index()
        new: List[Finding] = []
        accepted: List[Finding] = []
        seen: Set[Tuple[str, str, str]] = set()
        for f in findings:
            ident = f.identity()
            if ident in index:
                accepted.append(f)
                seen.add(ident)
            else:
                new.append(f)
        stale = [e for key, e in index.items() if key not in seen]
        return new, accepted, stale


def load(path: str) -> Baseline:
    """Load a baseline file; loudly reject malformed or unjustified
    entries (an unjustified suppression is not a suppression)."""
    if not os.path.exists(path):
        return Baseline(path=path)
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != VERSION:
        raise ValueError(f"{path}: expected a tpucheck baseline with "
                         f"version {VERSION}")
    entries = data.get("entries", [])
    for e in entries:
        for req in ("rule", "path", "key", "why"):
            if not isinstance(e.get(req), str) or not e[req].strip():
                raise ValueError(f"{path}: baseline entry missing "
                                 f"'{req}': {e!r}")
        if e["why"] == TODO_WHY:
            raise ValueError(
                f"{path}: entry for {e['rule']} {e['path']} ({e['key']}) "
                f"still says '{TODO_WHY}' — write the one-line reason "
                "this finding is intentionally kept")
    return Baseline(path=path, entries=list(entries))


def write(path: str, findings: Sequence[Finding],
          previous: Baseline) -> int:
    """Write a baseline covering ``findings``, preserving the ``why``
    of still-matching entries from ``previous``. Returns the number of
    entries that need a human-written justification."""
    prev = previous._index()
    entries: List[Dict[str, str]] = []
    todo = 0
    for f in findings:
        old = prev.get(f.identity())
        why = old["why"] if old else TODO_WHY
        if why == TODO_WHY:
            todo += 1
        entries.append({"rule": f.rule, "path": f.path, "key": f.key
                        or f.message, "why": why,
                        "message": f.message})
    payload = {
        "_comment": [
            "tpucheck accepted-findings ledger (docs/static_analysis.md).",
            "Every entry is an intentionally-kept finding; 'why' is the",
            "one-line review justification. Matching is (rule, path,",
            "key) - stable across line drift. Fixed code must drop its",
            "entry (stale entries are reported).",
        ],
        "version": VERSION,
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    return todo
