"""tpucheck core: file discovery, parsed sources, findings, rule runner.

Stdlib-only on purpose (``ast`` + ``re``): the checker must run in a
bare CI container, before jax/flax import, and on fixture trees that
are not importable packages. Rules therefore work on syntax, not on
live objects — the one exception is R2's marker table, imported from
``tpunet.obs.hlo_bytes`` (itself stdlib-only) so the check can't
drift from the attribution it protects.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: Inline escape hatch: ``# tpucheck: disable=R1`` (or ``R1,R4`` or
#: ``all``) on the finding's line or the line directly above it.
_SUPPRESS_RE = re.compile(r"#\s*tpucheck:\s*disable=([A-Za-z0-9_,]+|all)")


@dataclass(frozen=True)
class Finding:
    """One diagnostic: rule id + location + stable identity.

    ``key`` is the baseline-matching identity — it must NOT contain
    line numbers, so accepted findings survive unrelated edits above
    them. ``message`` says what is wrong; ``hint`` says how to fix it.
    """

    rule: str
    path: str               # repo-relative, posix separators
    line: int
    message: str
    hint: str = ""
    key: str = ""

    def identity(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.key or self.message)

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint,
                "key": self.key}


class SourceFile:
    """One python file: source text, lines, AST, suppression map."""

    def __init__(self, abs_path: str, rel_path: str) -> None:
        self.abs_path = abs_path
        self.rel = rel_path.replace(os.sep, "/")
        with open(abs_path, "r", encoding="utf-8", errors="replace") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=self.rel)
        except SyntaxError as e:  # surfaced as a finding by run_rules
            self.parse_error = f"{e.msg} (line {e.lineno})"
        # line -> (rules, standalone): a TRAILING comment suppresses
        # its own line only; a comment-ONLY line suppresses the next.
        self._suppress: Dict[int, Tuple[Set[str], bool]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if m:
                spec = m.group(1)
                rules = ({"all"} if spec == "all"
                         else {r.strip().upper()
                               for r in spec.split(",") if r.strip()})
                self._suppress[i] = (rules, text.lstrip().startswith("#"))

    def suppressed(self, rule: str, line: int) -> bool:
        """True when ``line`` carries a trailing ``# tpucheck:
        disable=`` comment naming this rule, or the line directly
        above is a standalone one."""
        for ln, need_standalone in ((line, False), (line - 1, True)):
            entry = self._suppress.get(ln)
            if entry is None:
                continue
            rules, standalone = entry
            if need_standalone and not standalone:
                continue
            if "all" in rules or rule.upper() in rules:
                return True
        return False


class Project:
    """The file set one tpucheck run analyzes.

    ``root`` is a repo (or fixture) directory; files are discovered
    under ``roots`` — by default the production code only (``tests/``
    and fixture trees are never analyzed: test files legitimately
    spawn raw threads and poke jit internals).
    """

    DEFAULT_ROOTS: Tuple[str, ...] = ("tpunet", "scripts", "train.py",
                                      "bench.py")
    EXCLUDE_DIR_PARTS: Tuple[str, ...] = ("__pycache__", "_lib",
                                          "fixtures", ".git")

    def __init__(self, root: str,
                 roots: Optional[Sequence[str]] = None) -> None:
        self.root = os.path.abspath(root)
        self.roots: Tuple[str, ...] = tuple(roots or self.DEFAULT_ROOTS)
        self._files: Optional[List[SourceFile]] = None
        self._mds: Optional[List[Tuple[str, str]]] = None

    def _excluded(self, rel: str) -> bool:
        parts = rel.replace(os.sep, "/").split("/")
        return any(p in self.EXCLUDE_DIR_PARTS for p in parts)

    def files(self) -> List[SourceFile]:
        """All analyzed python files, parsed, sorted by path."""
        if self._files is not None:
            return self._files
        found: List[SourceFile] = []
        for entry in self.roots:
            path = os.path.join(self.root, entry)
            if os.path.isfile(path) and path.endswith(".py"):
                found.append(SourceFile(path, entry))
                continue
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d not in self.EXCLUDE_DIR_PARTS]
                for name in sorted(filenames):
                    if not name.endswith(".py"):
                        continue
                    abs_path = os.path.join(dirpath, name)
                    rel = os.path.relpath(abs_path, self.root)
                    if not self._excluded(rel):
                        found.append(SourceFile(abs_path, rel))
        found.sort(key=lambda f: f.rel)
        self._files = found
        return found

    def md_files(self) -> List[Tuple[str, str]]:
        """(rel path, text) of root-level and docs/ markdown files —
        the corpus R5's docs-mention check searches."""
        if self._mds is not None:
            return self._mds
        out: List[Tuple[str, str]] = []
        candidates: List[str] = []
        for name in sorted(os.listdir(self.root)):
            if name.endswith(".md"):
                candidates.append(name)
        docs = os.path.join(self.root, "docs")
        if os.path.isdir(docs):
            for name in sorted(os.listdir(docs)):
                if name.endswith(".md"):
                    candidates.append(os.path.join("docs", name))
        for rel in candidates:
            with open(os.path.join(self.root, rel), "r",
                      encoding="utf-8", errors="replace") as f:
                out.append((rel.replace(os.sep, "/"), f.read()))
        self._mds = out
        return out


class Rule:
    """A tpucheck rule: stable ``id`` (R1..), short ``name``, and a
    ``run`` over a Project returning findings (unsuppressed filtering
    and sorting belong to ``run_rules``, not the rule)."""

    id: str = ""
    name: str = ""
    doc: str = ""

    def run(self, project: Project) -> List[Finding]:
        raise NotImplementedError


def dotted(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain ('jax.jit',
    'self.ckpt.restore_state'); '' for anything else."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Call):
        # functools.partial(jax.jit, ...)(f) style chains: fold the
        # callee in so suffix matching still works.
        inner = dotted(cur.func)
        if inner:
            parts.append(inner)
        else:
            return ""
    else:
        return ""
    return ".".join(reversed(parts))


def call_name(node: ast.Call) -> str:
    return dotted(node.func)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def run_rules(project: Project, rules: Sequence[Rule]) -> List[Finding]:
    """Run rules, drop inline-suppressed findings, sort by location.

    Unparseable files produce one synthetic finding each (rule id
    ``PARSE``) instead of being silently skipped — a checker that
    skips broken files reads as 'clean' exactly when the tree is not.
    """
    findings: List[Finding] = []
    by_rel = {f.rel: f for f in project.files()}
    for src in project.files():
        if src.parse_error is not None:
            findings.append(Finding(
                rule="PARSE", path=src.rel, line=1,
                message=f"file does not parse: {src.parse_error}",
                key=f"parse:{src.rel}"))
    seen: Set[Tuple[str, str, int, str, str]] = set()
    for rule in rules:
        for finding in rule.run(project):
            src = by_rel.get(finding.path)
            if src is not None and src.suppressed(finding.rule,
                                                  finding.line):
                continue
            ident = (finding.rule, finding.path, finding.line,
                     finding.key, finding.message)
            if ident in seen:
                continue
            seen.add(ident)
            findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return findings
