"""tpucheck rule registry."""

from __future__ import annotations

from typing import Dict, Tuple

from tpunet.analysis.core import Rule
from tpunet.analysis.rules.donation import DonationRule
from tpunet.analysis.rules.drift import DriftRule
from tpunet.analysis.rules.instruments import InstrumentRule
from tpunet.analysis.rules.jit_effects import JitEffectsRule
from tpunet.analysis.rules.scopes import ScopeRule
from tpunet.analysis.rules.threads import ThreadRule
from tpunet.analysis.rules.xmodule import CrossModuleDonationRule

ALL_RULES: Tuple[Rule, ...] = (
    DonationRule(),
    ScopeRule(),
    JitEffectsRule(),
    ThreadRule(),
    DriftRule(),
    InstrumentRule(),
    CrossModuleDonationRule(),
)


def rules_by_id() -> Dict[str, Rule]:
    return {r.id: r for r in ALL_RULES}
