"""R1 — donation-aliasing: IO-origin arrays into donated jit args.

The PR-7 resume heap corruption in one rule: ``donate_argnums`` tells
XLA it may free/reuse an argument's buffers the moment the call is
dispatched — safe for device arrays the caller truly abandons, but an
array that *aliases host memory something else still owns* (an orbax
restore's mmap, a ``np.asarray`` view over a ctypes/dlpack buffer, a
file load) gets its backing store handed to the allocator while the
real owner still writes through it. glibc aborts a few dispatches
later, nowhere near the cause; it took a flight recorder and ten
reproductions to attribute. The checker attributes it at review time:

- **donated callables**: ``X = jax.jit(f, donate_argnums=...)`` (or
  ``donate_argnames``), including ``self.X = ...`` method slots;
- **IO-origin taint**: values returned by restore/load-like calls
  (``*.restore*``, ``np.load``, ``np.asarray``, ``np.frombuffer``,
  ``np.fromfile``, ``np.memmap``, ``pickle.load``, ``from_dlpack``,
  ``ctypeslib.as_array``), propagated through subscripts, attribute
  stores, tuples, and conditionals;
- **re-materialization** clears taint: ``jnp.copy`` / ``jnp.array`` /
  ``jnp.asarray`` / ``jax.device_put``, alone or as the mapped
  function of a ``tree_map``.

A call passing a tainted value in a donated position is the finding.

Precision notes (documented approximations, tuned for this repo's
idioms): module and function bodies are analyzed in order with
reassignment clearing taint; class bodies are analyzed
flow-insensitively over ``self.*`` (methods run in arbitrary order at
runtime — ``_try_resume`` taints ``self.state`` long after ``train``
was defined), so a ``self`` attribute that is *ever* IO-tainted stays
tainted for every donated call in the class. Calls into other modules
are opaque: a function whose *name* looks restore-like taints its
result even if its body re-materializes — that is what the baseline
ledger (with its one-line justification) is for.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tpunet.analysis.core import (Finding, Project, Rule, SourceFile,
                                  call_name)

_IO_NAME_RE = re.compile(
    r"(^|_)(restore|load|loads|frombuffer|fromfile|memmap|from_dlpack"
    r"|as_array|unpack)(_|$)|^asarray$", re.IGNORECASE)

# Re-materialization wrappers: dotted-name suffixes whose result owns
# fresh device (or at least fresh) buffers.
_SAFE_SUFFIXES = (
    "jnp.copy", "jnp.array", "jnp.asarray", "numpy.copy", "numpy.array",
    "numpy.asarray", "jax.device_put", "device_put",
)

_TREE_MAP_SUFFIXES = ("tree_map", "tree.map")

_JIT_SUFFIXES = (".jit", ".pjit")


def _is_jit_call(node: ast.Call) -> bool:
    name = call_name(node)
    return bool(name) and (name == "jit" or name == "pjit"
                           or name.endswith(_JIT_SUFFIXES))


def _donated_spec(node: ast.Call
                  ) -> Optional[Tuple[Tuple[int, ...], Tuple[str, ...]]]:
    """(donated positions, donated argnames) when this is a jit call
    with donation, else None."""
    if not _is_jit_call(node):
        return None
    positions: List[int] = []
    names: List[str] = []
    found = False
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            found = True
            positions.extend(_int_list(kw.value))
        elif kw.arg == "donate_argnames":
            found = True
            names.extend(_str_list(kw.value))
    if not found:
        return None
    return tuple(positions), tuple(names)


def _int_list(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out: List[int] = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return out
    return []


def _str_list(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [elt.value for elt in node.elts
                if isinstance(elt, ast.Constant)
                and isinstance(elt.value, str)]
    return []


def _target_name(node: ast.AST) -> Optional[str]:
    """'x' for Name targets, 'self.x' for self-attribute targets."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return None


def _expr_ref(node: ast.AST) -> Optional[str]:
    """The tracked name an expression is rooted at ('x', 'self.x'),
    looking through subscripts/attribute reads."""
    cur = node
    while True:
        name = _target_name(cur)
        if name is not None:
            return name
        if isinstance(cur, ast.Subscript):
            cur = cur.value
            continue
        if isinstance(cur, ast.Attribute):
            cur = cur.value
            continue
        return None


class _Analyzer:
    """Taint/donation bookkeeping over one scope unit (module body,
    function body, or class)."""

    def __init__(self, src: SourceFile, findings: List[Finding],
                 flow_sensitive: bool) -> None:
        self.src = src
        self.findings = findings
        self.flow_sensitive = flow_sensitive
        self.donated: Dict[str, Tuple[Tuple[int, ...],
                                      Tuple[str, ...]]] = {}
        self.tainted: Dict[str, Tuple[str, int]] = {}  # name -> (origin, line)

    # -- taint classification ------------------------------------------

    def is_io_call(self, node: ast.Call) -> bool:
        name = call_name(node)
        if not name:
            return False
        last = name.rsplit(".", 1)[-1]
        if name.endswith(_SAFE_SUFFIXES):
            # np.asarray is BOTH: a copy for device arrays but a view
            # over buffer-protocol objects (the dlpack/ctypes path).
            # Treat it as IO-origin when fed an already-tainted or
            # non-trivial buffer expression, safe when re-wrapping.
            if last == "asarray" and node.args \
                    and self._tainted_expr(node.args[0]) is None:
                return False
            if last != "asarray":
                return False
        return bool(_IO_NAME_RE.search(last))

    def is_safe_wrapper(self, node: ast.Call) -> bool:
        name = call_name(node)
        if not name:
            return False
        if name.endswith(_TREE_MAP_SUFFIXES) and node.args:
            mapped = node.args[0]
            if isinstance(mapped, ast.Call):
                return False
            mapped_name = ""
            if isinstance(mapped, (ast.Name, ast.Attribute)):
                from tpunet.analysis.core import dotted
                mapped_name = dotted(mapped)
            return mapped_name.endswith(_SAFE_SUFFIXES)
        if name.endswith(("jnp.asarray", "numpy.asarray")) \
                or name.rsplit(".", 1)[-1] == "asarray":
            # asarray of a tainted host view is a no-copy alias, not a
            # re-materialization.
            return False
        return name.endswith(_SAFE_SUFFIXES)

    def _tainted_expr(self, node: ast.AST) -> Optional[Tuple[str, int]]:
        """(origin, line) when the expression carries IO taint."""
        if isinstance(node, ast.Call):
            if self.is_safe_wrapper(node):
                return None
            if self.is_io_call(node):
                return (call_name(node), node.lineno)
            return None
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                t = self._tainted_expr(elt)
                if t:
                    return t
            return None
        if isinstance(node, ast.Dict):
            for v in node.values:
                if v is not None:
                    t = self._tainted_expr(v)
                    if t:
                        return t
            return None
        if isinstance(node, ast.IfExp):
            return (self._tainted_expr(node.body)
                    or self._tainted_expr(node.orelse))
        ref = _expr_ref(node)
        if ref is not None and ref in self.tainted:
            return self.tainted[ref]
        return None

    # -- statement processing ------------------------------------------

    def handle_assign(self, node: ast.Assign) -> None:
        targets = []
        for t in node.targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
            else:
                targets.append(t)
        names = [_target_name(t) for t in targets]
        spec = (_donated_spec(node.value)
                if isinstance(node.value, ast.Call) else None)
        taint = self._tainted_expr(node.value)
        for name in names:
            if name is None:
                continue
            if spec is not None:
                self.donated[name] = spec
                self.tainted.pop(name, None)
            elif taint is not None:
                self.tainted[name] = taint
            else:
                self.donated.pop(name, None)
                if self.flow_sensitive:
                    self.tainted.pop(name, None)

    def handle_call_site(self, node: ast.Call) -> None:
        from tpunet.analysis.core import dotted
        callee = dotted(node.func)
        if not callee or callee not in self.donated:
            return
        positions, argnames = self.donated[callee]
        checks: List[Tuple[str, ast.AST]] = []
        for pos in positions:
            if pos < len(node.args):
                checks.append((f"arg {pos}", node.args[pos]))
        for kw in node.keywords:
            if kw.arg is not None and kw.arg in argnames:
                checks.append((f"arg '{kw.arg}'", kw.value))
        for label, arg in checks:
            taint = self._tainted_expr(arg)
            if taint is None:
                continue
            origin, origin_line = taint
            arg_src = ast.unparse(arg) if hasattr(ast, "unparse") else "?"
            self.findings.append(Finding(
                rule="R1", path=self.src.rel, line=node.lineno,
                message=(f"IO-origin value '{arg_src}' (tainted via "
                         f"'{origin}' at line {origin_line}) is passed "
                         f"as donated {label} of '{callee}' — donation "
                         "frees buffers that may alias host memory the "
                         "producer still owns (the PR-7 resume "
                         "heap-corruption class)"),
                hint=("re-materialize before donating: x = jax.tree_util"
                      ".tree_map(jnp.copy, restored) or jax.device_put("
                      "x); if the producer already re-materializes, "
                      "record that in docs/tpucheck_baseline.json"),
                key=f"donate:{callee}<-{arg_src}"))

    def scan_statements(self, stmts: Sequence[ast.stmt],
                        passes: int = 1) -> None:
        """Process assignments and call sites. With ``passes=2`` the
        first pass only collects donation/taint facts (flow-insensitive
        class analysis); the last pass reports call sites."""
        for is_last in ([True] if passes == 1 else [False, True]):
            for stmt in stmts:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Assign):
                        self.handle_assign(node)
                    elif isinstance(node, ast.AnnAssign) \
                            and node.value is not None:
                        self.handle_assign(ast.Assign(
                            targets=[node.target], value=node.value,
                            lineno=node.lineno))
                    elif isinstance(node, ast.Call) and is_last:
                        self.handle_call_site(node)


class DonationRule(Rule):
    id = "R1"
    name = "donation-aliasing"
    doc = ("IO-origin arrays (orbax restore, np loads, dlpack/ctypes "
           "views) passed into donate_argnums/donate_argnames jitted "
           "callables without re-materialization")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files():
            if src.tree is None:
                continue
            assert isinstance(src.tree, ast.Module)
            module_stmts: List[ast.stmt] = []
            for stmt in src.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    # Class unit: methods share the self.* namespace;
                    # two-pass flow-insensitive (see module docstring).
                    _Analyzer(src, findings, flow_sensitive=False) \
                        .scan_statements(stmt.body, passes=2)
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    _Analyzer(src, findings, flow_sensitive=True) \
                        .scan_statements(stmt.body)
                else:
                    module_stmts.append(stmt)
            _Analyzer(src, findings, flow_sensitive=True) \
                .scan_statements(module_stmts)
        return findings
