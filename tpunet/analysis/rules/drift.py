"""R5 — config/CLI/docs drift for the user-facing config surfaces.

A dataclass field that no CLI flag reaches is a knob only code edits
can turn; a flag no doc mentions is a knob only archaeology finds.
Both happen one innocent field at a time. This rule closes the loop
for the three surfaces operators actually touch — ``ObsConfig``,
``ModelConfig``, ``ServeConfig``:

- **CLI**: every field must correspond to an ``add_argument`` flag
  somewhere in the tree — by name (``step_records_every`` ↔
  ``--step-records-every``), by the repo's historical renames
  (``_FLAG_ALIASES``), or by a ``--no-X`` boolean form;
- **docs**: the field name (or its flag) must appear in README.md or
  docs/*.md — with ``docs/static_analysis.md`` excluded from the
  corpus so the rule's own catalog can't satisfy the check it
  enforces.

``RouterConfig`` joined the target set with the routing front tier
(tpunet/router/): its knobs are exactly the kind operators reach for
mid-incident (probe cadence, eviction budget, scale thresholds), so
an unwired field there is drift at its most expensive.

Fields that are deliberately not CLI-wired (derived values, research
knobs) belong in the baseline with the reason — that is a reviewed
decision, not drift.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tpunet.analysis.core import (Finding, Project, Rule, call_name,
                                  const_str)

TARGET_CLASSES: Tuple[str, ...] = ("ObsConfig", "ModelConfig",
                                   "ServeConfig", "RouterConfig")

#: Historical flag renames: "Class.field" -> the flag that wires it.
_FLAG_ALIASES: Dict[str, str] = {
    "ModelConfig.name": "--model",
    "ModelConfig.pretrained_path": "--pretrained",
    "ModelConfig.use_pallas_depthwise": "--pallas-depthwise",
    "ObsConfig.enabled": "--no-obs",
    "ObsConfig.step_records_every": "--obs-step-every",
    "ObsConfig.hbm_attrib": "--obs-hbm-attrib",
    "ObsConfig.heartbeat_timeout_s": "--heartbeat-timeout",
    "ObsConfig.gauge_rules": "--obs-rule",
    "ObsConfig.histogram_max_samples": "--obs-hist-samples",
    "ServeConfig.default_max_new_tokens": "--max-new-tokens",
    "ServeConfig.default_deadline_s": "--deadline-s",
}

#: Markdown files excluded from the docs corpus (self-reference guard).
_DOCS_EXCLUDE = ("docs/static_analysis.md",)


def _is_dataclass_class(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        name = (call_name(dec) if isinstance(dec, ast.Call)
                else (dec.id if isinstance(dec, ast.Name) else ""))
        if isinstance(dec, ast.Attribute):
            name = dec.attr
        if name.rsplit(".", 1)[-1] == "dataclass":
            return True
    return False


def _nested_config_default(node: ast.AnnAssign) -> bool:
    """True for ``field(default_factory=SomeConfig)`` fields — nested
    config objects are surfaces of their own, not scalar knobs."""
    if isinstance(node.value, ast.Call) \
            and call_name(node.value).rsplit(".", 1)[-1] == "field":
        for kw in node.value.keywords:
            if kw.arg == "default_factory" \
                    and isinstance(kw.value, ast.Name) \
                    and kw.value.id.endswith("Config"):
                return True
    return False


class DriftRule(Rule):
    id = "R5"
    name = "config-cli-docs-drift"
    doc = ("every ObsConfig/ModelConfig/ServeConfig/RouterConfig "
           "field has a wired CLI flag and a docs mention")

    def run(self, project: Project) -> List[Finding]:
        fields: List[Tuple[str, str, str, int]] = []  # cls, field, path, line
        flags: Set[str] = set()
        for src in project.files():
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef) \
                        and node.name in TARGET_CLASSES \
                        and _is_dataclass_class(node):
                    for stmt in node.body:
                        if not isinstance(stmt, ast.AnnAssign) \
                                or not isinstance(stmt.target, ast.Name):
                            continue
                        fname = stmt.target.id
                        if fname.startswith("_") \
                                or _nested_config_default(stmt):
                            continue
                        fields.append((node.name, fname, src.rel,
                                       stmt.lineno))
                if isinstance(node, ast.Call) \
                        and call_name(node).endswith("add_argument"):
                    for arg in node.args:
                        s = const_str(arg)
                        if s and s.startswith("--"):
                            flags.add(s)
        docs_text = "\n".join(
            text for rel, text in project.md_files()
            if rel not in _DOCS_EXCLUDE)

        findings: List[Finding] = []
        for cls, fname, path, line in fields:
            dashed = "--" + fname.replace("_", "-")
            candidates = {dashed, f"--no-{fname.replace('_', '-')}"}
            alias = _FLAG_ALIASES.get(f"{cls}.{fname}")
            if alias:
                candidates.add(alias)
            wired = sorted(candidates & flags)
            if not wired:
                findings.append(Finding(
                    rule="R5", path=path, line=line,
                    message=(f"{cls}.{fname} has no CLI flag (looked "
                             f"for {', '.join(sorted(candidates))}) — "
                             "the knob is unreachable without a code "
                             "edit"),
                    hint=("add the flag (and wire it in the config "
                          "builder), add a rename to tpucheck's "
                          "_FLAG_ALIASES, or baseline with the reason "
                          "it is deliberately not CLI-wired"),
                    key=f"{cls}.{fname}:cli"))
            mentions = [fname] + wired + ([alias] if alias else [])
            pattern = "|".join(re.escape(m) for m in mentions if m)
            if not re.search(pattern, docs_text):
                findings.append(Finding(
                    rule="R5", path=path, line=line,
                    message=(f"{cls}.{fname} is mentioned nowhere in "
                             "README.md or docs/ (neither the field "
                             "name nor its flag)"),
                    hint=("document the knob where its subsystem is "
                          "described (docs/static_analysis.md is "
                          "excluded from this check on purpose)"),
                    key=f"{cls}.{fname}:docs"))
        return findings
