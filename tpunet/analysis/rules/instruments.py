"""R6 — metrics-schema instrument naming.

``docs/metrics_schema.md`` is the contract every obs consumer reads,
and ``scripts/check_metrics_schema.py`` enforces it for *records* —
but only for the emission paths the check drives, at runtime. An
instrument created with ``registry.counter("new_thing_total")`` in a
path the check never exercises drifts in silently: the gauge ships to
exporters and shows up in ``GET /metrics`` with no documentation
anywhere. This rule closes that gap statically: every literal
``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)`` name in
``tpunet/`` must appear in docs/metrics_schema.md; f-string names
(``f"export_{name}_dropped"``) must match a documented placeholder
pattern (``export_<name>_dropped``).

Scope is ``tpunet/`` only: scripts drive fake instruments on purpose
(check_metrics_schema's ``some_gauge``), and tests are never
analyzed. Names passed as variables are out of reach for a syntax
checker — the runtime schema check still covers the records those
feed.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set, Tuple

from tpunet.analysis.core import Finding, Project, Rule, const_str

SCHEMA_DOC = "docs/metrics_schema.md"

_METHODS = ("counter", "gauge", "histogram")
_IDENT = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_PLACEHOLDER = re.compile(r"<[^<>]+>")
#: Stand-in for an f-string's formatted values when probing doc
#: patterns: any placeholder must cover it.
_PROBE = "X0"


def _expand_braces(text: str) -> Iterator[str]:
    """``ttft_{p50,p90}_s`` -> ttft_p50_s, ttft_p90_s (one level,
    mirroring check_metrics_schema's schema parser)."""
    m = re.search(r"\{([^{}]*)\}", text)
    if not m:
        yield text
        return
    for alt in m.group(1).split(","):
        yield from _expand_braces(text[:m.start()] + alt.strip()
                                  + text[m.end():])


def parse_schema_names(text: str) -> Tuple[Set[str], List[re.Pattern]]:
    """(literal identifier tokens, placeholder patterns) from every
    backticked span of the schema doc. ``export_<name>_dropped``
    becomes a regex whose ``<...>`` holes match any identifier run —
    the documented shape for dynamically-named instrument families."""
    literals: Set[str] = set()
    patterns: List[re.Pattern] = []
    for span in re.findall(r"`([^`]+)`", text):
        for expanded in _expand_braces(span):
            if "<" in expanded:
                for piece in expanded.split():
                    if "<" not in piece:
                        continue
                    stripped = _PLACEHOLDER.sub("\x00", piece)
                    if not _IDENT.search(stripped.replace("\x00", "")):
                        # A bare `<name>` span has no literal anchor:
                        # compiling it would yield a match-everything
                        # wildcard that silences the whole rule.
                        continue
                    rx = (re.escape(stripped)
                          .replace(re.escape("\x00"), "[A-Za-z0-9_]+")
                          .replace("\x00", "[A-Za-z0-9_]+"))
                    try:
                        patterns.append(re.compile(rx + r"\Z"))
                    except re.error:
                        continue
            else:
                literals.update(_IDENT.findall(expanded))
    return literals, patterns


def _probe_name(arg: ast.AST) -> Optional[Tuple[str, bool]]:
    """(name-or-probe, is_dynamic) for an instrument-name argument:
    a constant string verbatim, an f-string with formatted values
    replaced by a probe token, None for anything else (variables —
    out of static reach)."""
    s = const_str(arg)
    if s is not None:
        return s, False
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append(_PROBE)
        return "".join(parts), True
    return None


class InstrumentRule(Rule):
    id = "R6"
    name = "metrics-schema-instruments"
    doc = ("every literal registry.counter/gauge/histogram name in "
           "tpunet/ is documented in docs/metrics_schema.md")

    def run(self, project: Project) -> List[Finding]:
        schema_text = ""
        for rel, text in project.md_files():
            if rel == SCHEMA_DOC:
                schema_text = text
                break
        literals, patterns = parse_schema_names(schema_text)

        findings: List[Finding] = []
        for src in project.files():
            if src.tree is None \
                    or not src.rel.startswith("tpunet/"):
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute) \
                        or node.func.attr not in _METHODS \
                        or not node.args:
                    continue
                probe = _probe_name(node.args[0])
                if probe is None:
                    continue
                name, dynamic = probe
                if not dynamic and name in literals:
                    continue
                if any(p.match(name) for p in patterns):
                    continue
                shown = (name.replace(_PROBE, "<...>")
                         if dynamic else name)
                findings.append(Finding(
                    rule="R6", path=src.rel, line=node.lineno,
                    message=(f"instrument {shown!r} "
                             f"({node.func.attr}) is not documented "
                             f"in {SCHEMA_DOC}"),
                    hint=("add the name to the schema doc (the "
                          "'Registry instruments' list or the record "
                          "kind that carries it); dynamic families "
                          "document their shape as name_<hole>_suffix"),
                    key=f"instrument:{shown}"))
        return findings
