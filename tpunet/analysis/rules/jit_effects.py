"""R3 — host side-effects inside jit/shard_map/pallas bodies.

A jitted function body runs ONCE, at trace time. ``print`` prints a
tracer once and never again; ``time.time()`` stamps compilation, not
execution; mutating a global records the trace-time value forever; a
``np.*`` op on a traced value either crashes (TracerArrayConversion)
or silently constant-folds host data into the program. All four read
as working code in a quick local test (the first call does execute
them) and rot into wrong numbers in production.

Detected jit contexts (syntactic):

- ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` decorators;
- local defs passed to ``jax.jit(f)``, ``shard_map(f, ...)`` (the
  compat shim included), or as the kernel of ``pl.pallas_call(f, ..)``.

Inside those bodies the rule flags ``print(...)``, ``time.*()`` calls,
``global``-declared assignment, and ``np.* (traced-param)`` calls —
the numpy check requires a direct function parameter as an argument
to keep static-shape numpy math (``np.prod(shape)``) legal.
``jax.debug.*`` and the ``*_callback`` APIs are the sanctioned
escape hatches and are never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from tpunet.analysis.core import (Finding, Project, Rule, SourceFile,
                                  call_name, dotted)

_TIME_CALLS = {"time", "perf_counter", "monotonic", "process_time",
               "sleep", "time_ns", "perf_counter_ns"}
_ALLOWED_PREFIXES = ("jax.debug.",)
_ALLOWED_SUBSTR = ("callback",)
_JIT_WRAP_SUFFIXES = ("jit", "pjit")
_FN_WRAPPERS = ("shard_map", "pallas_call")


def _is_jit_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, (ast.Name, ast.Attribute)):
        return dotted(dec).rsplit(".", 1)[-1] in _JIT_WRAP_SUFFIXES
    if isinstance(dec, ast.Call):
        name = call_name(dec)
        if name.rsplit(".", 1)[-1] in _JIT_WRAP_SUFFIXES:
            return True
        if name.rsplit(".", 1)[-1] == "partial" and dec.args:
            inner = dec.args[0]
            if isinstance(inner, (ast.Name, ast.Attribute)):
                return (dotted(inner).rsplit(".", 1)[-1]
                        in _JIT_WRAP_SUFFIXES)
    return False


def _wrapped_local_defs(tree: ast.AST) -> Set[str]:
    """Names of local functions passed to jit/shard_map/pallas_call
    anywhere in the module."""
    wrapped: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, (ast.Name, ast.Attribute)):
            continue
        last = call_name(node).rsplit(".", 1)[-1]
        if last in _JIT_WRAP_SUFFIXES or last in _FN_WRAPPERS:
            if node.args and isinstance(node.args[0], ast.Name):
                wrapped.add(node.args[0].id)
    return wrapped


class JitEffectsRule(Rule):
    id = "R3"
    name = "jit-host-side-effects"
    doc = ("print/time.*/global mutation/numpy-on-traced-values inside "
           "jit, shard_map, or pallas kernel bodies (trace-time-only "
           "execution)")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files():
            if src.tree is None:
                continue
            wrapped = _wrapped_local_defs(src.tree)
            for node in ast.walk(src.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                jitted = (node.name in wrapped
                          or any(_is_jit_decorator(d)
                                 for d in node.decorator_list))
                if jitted:
                    findings.extend(self._check_body(src, node))
        return findings

    # ------------------------------------------------------------------

    def _check_body(self, src: SourceFile,
                    fn: ast.FunctionDef) -> List[Finding]:
        findings: List[Finding] = []
        params: Set[str] = {a.arg for a in (
            fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs)}
        global_names: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                global_names.update(node.names)

        def emit(line: int, kind: str, detail: str, message: str,
                 hint: str) -> None:
            findings.append(Finding(
                rule="R3", path=src.rel, line=line, message=message,
                hint=hint, key=f"{fn.name}:{kind}:{detail}"))

        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets: Iterable[ast.AST] = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in global_names:
                        emit(node.lineno, "global", t.id,
                             f"jitted '{fn.name}' mutates global "
                             f"'{t.id}' — the mutation happens once at "
                             "trace time, never per step",
                             "return the value (or use jax.debug."
                             "callback for host-side accounting)")
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if not name:
                continue
            if name.startswith(_ALLOWED_PREFIXES) \
                    or any(s in name for s in _ALLOWED_SUBSTR):
                continue
            last = name.rsplit(".", 1)[-1]
            root = name.split(".", 1)[0]
            if name == "print":
                emit(node.lineno, "print", str(node.lineno),
                     f"print() inside jitted '{fn.name}' executes at "
                     "trace time only (and prints a tracer)",
                     "use jax.debug.print for per-execution output")
            elif root == "time" and last in _TIME_CALLS:
                emit(node.lineno, "time", last,
                     f"time.{last}() inside jitted '{fn.name}' stamps "
                     "trace time, not step time",
                     "time around the jitted call on the host (the obs "
                     "Timer), not inside it")
            elif root in ("np", "numpy"):
                traced = [a for a in node.args
                          if isinstance(a, ast.Name) and a.id in params]
                if traced:
                    emit(node.lineno, "numpy", f"{last}:{traced[0].id}",
                         f"np.{last}({traced[0].id}) inside jitted "
                         f"'{fn.name}' applies a host numpy op to a "
                         "traced value — TracerArrayConversionError at "
                         "best, silent trace-time constant-folding at "
                         "worst",
                         f"use jnp.{last} (or move the numpy math "
                         "outside the jitted body)")
        return findings
