"""R2 — named-scope coverage for the kernels in ``tpunet/ops/``.

Byte/phase attribution (``tpunet/obs/hlo_bytes.py``) classifies HLO
instructions by the framework ``op_name`` — and a custom_vjp'd Pallas
kernel has nothing classifiable in its op_name unless the code wraps
it in a ``tpunet_*`` named scope: the kernel lowers to a custom call
(no convolution/dot opcode) and a custom_vjp backward carries no
``transpose(`` autodiff marker. PR 6 burned three review passes
rediscovering this per kernel; this rule makes it structural:

1. every ``pl.pallas_call`` in ``tpunet/ops/`` must sit under a
   ``tpunet_*`` named scope — lexically, or via a wrapper function
   whose every in-module call site is scoped (the depthwise layout);
2. every ``defvjp``-registered fwd/bwd body must be *scope-bearing*:
   contain a tpunet scope or (transitively, through in-module calls)
   reach one (the flash layout, where the scope lives inside the
   shared kernel-invocation helpers);
3. every ``tpunet_*`` scope string used in ``tpunet/ops/`` must be a
   ``<prefix>_fwd`` / ``<prefix>_bwd`` of ``hlo_bytes.KERNEL_SCOPES``
   — the actual marker table attribution matches on — so a renamed or
   invented scope fails the tree instead of silently bucketing into
   ``elementwise``.

The cross-check imports the live table, not a copy: adding a kernel
means adding its scope prefix to ``KERNEL_SCOPES`` (with its fwd/bwd
byte categories) in the same change, or R2 says so.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tpunet.analysis.core import (Finding, Project, Rule, SourceFile,
                                  call_name, const_str, dotted)
from tpunet.obs.hlo_bytes import KERNEL_SCOPES

_OPS_PATH_RE = re.compile(r"(^|/)ops/[^/]+\.py$")

#: Assignments whose value wraps a function without renaming its body:
#: ``X = custom_partitioning(F, ...)`` / ``X = functools.partial(F, ..)``
_ALIAS_WRAPPERS = ("custom_partitioning", "partial")


def _valid_scope_names() -> Set[str]:
    return {f"{p}_{d}" for p in KERNEL_SCOPES for d in ("fwd", "bwd")}


class _FileScopes(ast.NodeVisitor):
    """Per-file collection pass: function defs, named-scope contexts,
    call sites, pallas_call sites, defvjp registrations, aliases."""

    def __init__(self) -> None:
        self.funcs: Dict[str, ast.AST] = {}
        self.func_stack: List[str] = []
        self.scope_stack: List[str] = []
        # fn -> scope names lexically opened inside its body
        self.scopes_in: Dict[str, Set[str]] = {}
        # callee -> [(caller or '' for module level, scoped bool)]
        self.call_sites: Dict[str, List[Tuple[str, bool]]] = {}
        # caller -> set of in-module callees
        self.calls_out: Dict[str, Set[str]] = {}
        # (line, enclosing fn, scoped bool) per pallas_call
        self.pallas: List[Tuple[int, str, bool]] = []
        # (primal name, fwd name, bwd name, line)
        self.vjp: List[Tuple[str, str, str, int]] = []
        self.aliases: Dict[str, str] = {}
        self.scope_strings: List[Tuple[str, int]] = []

    # -- helpers -------------------------------------------------------

    def _cur_fn(self) -> str:
        return self.func_stack[-1] if self.func_stack else ""

    def _record_call(self, callee: str, scoped: bool) -> None:
        self.call_sites.setdefault(callee, []).append(
            (self._cur_fn(), scoped))
        if self._cur_fn():
            self.calls_out.setdefault(self._cur_fn(), set()).add(callee)

    # -- visitors ------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.funcs[node.name] = node
        self.func_stack.append(node.name)
        outer_scopes = self.scope_stack
        self.scope_stack = []   # scopes do not cross function bodies
        self.generic_visit(node)
        self.scope_stack = outer_scopes
        self.func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_With(self, node: ast.With) -> None:
        opened: List[str] = []
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                name = call_name(item.context_expr)
                if name.endswith("named_scope") and item.context_expr.args:
                    scope = const_str(item.context_expr.args[0])
                    if scope is not None:
                        opened.append(scope)
                        self.scope_strings.append(
                            (scope, item.context_expr.lineno))
                        if self._cur_fn():
                            self.scopes_in.setdefault(
                                self._cur_fn(), set()).add(scope)
        self.scope_stack.extend(opened)
        self.generic_visit(node)
        for _ in opened:
            self.scope_stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        if (isinstance(node.value, ast.Call)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            callee = call_name(node.value)
            last = callee.rsplit(".", 1)[-1]
            if last in _ALIAS_WRAPPERS and node.value.args:
                wrapped = node.value.args[0]
                if isinstance(wrapped, ast.Name):
                    self.aliases[node.targets[0].id] = wrapped.id
        self.generic_visit(node)

    def _under_tpunet_scope(self) -> bool:
        return any(s.startswith("tpunet_") for s in self.scope_stack)

    def visit_Call(self, node: ast.Call) -> None:
        # Only direct Name/Attribute callees: ``pl.pallas_call(f, ..)
        # (*args)`` is two Call nodes whose dotted names both fold to
        # pallas_call — count the inner one only.
        if not isinstance(node.func, (ast.Name, ast.Attribute)):
            self.generic_visit(node)
            return
        name = call_name(node)
        scoped = self._under_tpunet_scope()
        if name.endswith("pallas_call"):
            self.pallas.append((node.lineno, self._cur_fn(), scoped))
        elif name.endswith(".defvjp"):
            primal = name.rsplit(".", 1)[0]
            if len(node.args) >= 2 \
                    and isinstance(node.args[0], ast.Name) \
                    and isinstance(node.args[1], ast.Name):
                self.vjp.append((primal, node.args[0].id,
                                 node.args[1].id, node.lineno))
        elif isinstance(node.func, ast.Name):
            self._record_call(node.func.id, scoped)
        self.generic_visit(node)


class ScopeRule(Rule):
    id = "R2"
    name = "named-scope-coverage"
    doc = ("every Pallas kernel call and custom_vjp fwd/bwd body in "
           "tpunet/ops/ sits under a tpunet_* named scope known to "
           "hlo_bytes.KERNEL_SCOPES")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files():
            if src.tree is None or not _OPS_PATH_RE.search(src.rel):
                continue
            findings.extend(self._check_file(src))
        return findings

    # ------------------------------------------------------------------

    def _check_file(self, src: SourceFile) -> List[Finding]:
        collect = _FileScopes()
        assert src.tree is not None
        collect.visit(src.tree)
        findings: List[Finding] = []

        def resolve(name: str) -> str:
            seen: Set[str] = set()
            while name in collect.aliases and name not in seen:
                seen.add(name)
                name = collect.aliases[name]
            return name

        # Fold aliased call sites onto the wrapped function: a call to
        # ``_partitioned`` IS a call to ``_pallas_forward``.
        call_sites: Dict[str, List[Tuple[str, bool]]] = {}
        for callee, sites in collect.call_sites.items():
            call_sites.setdefault(resolve(callee), []).extend(sites)

        # scope-bearing: body opens a tpunet scope, or transitively
        # calls (in-module) a scope-bearing function.
        bearing: Set[str] = {
            fn for fn, scopes in collect.scopes_in.items()
            if any(s.startswith("tpunet_") for s in scopes)}
        changed = True
        while changed:
            changed = False
            for caller, callees in collect.calls_out.items():
                if caller in bearing:
                    continue
                if any(resolve(c) in bearing for c in callees):
                    bearing.add(caller)
                    changed = True

        # covered: every COUNTED in-module call site is scoped, or sits
        # inside a covered caller (and at least one counted site
        # exists — an uncalled function has no scoped context to
        # inherit). Call sites inside functions that are themselves
        # never called in-module (callbacks handed to the partitioner:
        # custom_partitioning lower_fns, infer_sharding handlers) are
        # NOT counted — they execute under the partitioned op's trace
        # context, which is the scoped call we already track through
        # the alias; custom_vjp fwd/bwd are invoked by jax machinery
        # and DO count as live callers.
        vjp_fns = {name for _, fwd, bwd, _ in collect.vjp
                   for name in (fwd, bwd)}

        def counted(sites: List[Tuple[str, bool]]
                    ) -> List[Tuple[str, bool]]:
            return [(caller, scoped) for caller, scoped in sites
                    if caller == "" or caller in vjp_fns
                    or call_sites.get(caller)]

        covered: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for fn in collect.funcs:
                if fn in covered:
                    continue
                sites = counted(call_sites.get(fn, []))
                if sites and all(
                        scoped or (caller and caller in covered)
                        for caller, scoped in sites):
                    covered.add(fn)
                    changed = True

        for line, enclosing, scoped in collect.pallas:
            if scoped or (enclosing and enclosing in covered):
                continue
            findings.append(Finding(
                rule="R2", path=src.rel, line=line,
                message=(f"pl.pallas_call in '{enclosing or '<module>'}' "
                         "is not under a tpunet_* named scope (directly "
                         "or via its call sites) — its custom call will "
                         "attribute to 'elementwise' and its backward "
                         "to the fwd phase in hlo_bytes breakdowns"),
                hint=("wrap the kernel invocation in with jax.named_"
                      "scope(\"tpunet_<kernel>_fwd\") (or _bwd) and "
                      "register the prefix in hlo_bytes.KERNEL_SCOPES"),
                key=f"pallas:{enclosing or '<module>'}"))

        for primal, fwd, bwd, line in collect.vjp:
            for role, fn_name in (("fwd", fwd), ("bwd", bwd)):
                fn = collect.funcs.get(fn_name)
                if fn is None:
                    continue
                if fn_name in bearing or fn_name in covered:
                    continue
                findings.append(Finding(
                    rule="R2", path=src.rel,
                    line=getattr(fn, "lineno", line),
                    message=(f"custom_vjp {role} '{fn_name}' (defvjp of "
                             f"'{primal}') contains no tpunet_* named "
                             "scope — a custom_vjp body carries no "
                             "transpose( marker, so without the scope "
                             "its ops misattribute (PR-6 class)"),
                    hint=("wrap the body: with jax.named_scope("
                          f"\"tpunet_<kernel>_{role}\"): ... (prefix "
                          "must exist in hlo_bytes.KERNEL_SCOPES)"),
                    key=f"vjp:{primal}:{role}:{fn_name}"))

        valid = _valid_scope_names()
        for scope, line in collect.scope_strings:
            if scope.startswith("tpunet_") and scope not in valid:
                findings.append(Finding(
                    rule="R2", path=src.rel, line=line,
                    message=(f"named scope '{scope}' is not in hlo_bytes"
                             ".KERNEL_SCOPES (expected <prefix>_fwd/"
                             "_bwd with a registered prefix) — byte/"
                             "phase attribution will not classify it"),
                    hint=("add the prefix to KERNEL_SCOPES with its "
                          "fwd/bwd byte categories, or use an existing "
                          "marker"),
                    key=f"marker:{scope}"))
        return findings
