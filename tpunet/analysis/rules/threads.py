"""R4 — thread-registry enforcement for host thread/process spawns.

PR 7's host-thread registry (``tpunet/obs/flightrec/threads.py``)
exists because background threads used to be invisible: no inventory
in crash reports, no liveness gauges, no ``thread_stalled`` paging.
That only holds if every spawn actually registers — one forgotten
``threading.Thread`` and the next wedged-process postmortem is back
to guessing. This rule makes registration structural: every
``threading.Thread(...)`` / ``subprocess.Popen(...)`` in ``tpunet/``
must sit in a scope (enclosing class, else enclosing function, else
module) that references the flightrec registry (``register_thread``
or ``THREADS``), or be explicitly allowlisted.

Scope granularity is the class on purpose: the idiom is "register in
``__init__``/``start``, beat in ``_run``" — the registration and the
spawn are different methods of one object.

``subprocess.run`` is deliberately NOT flagged: it is synchronous
(the child is reaped before the call returns), so there is nothing
long-lived to inventory. The flight recorder's own plumbing
(``tpunet/obs/flightrec/``) is allowlisted — the watcher subprocess
is the thing that reports on everyone else and cannot register with
itself.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from tpunet.analysis.core import (Finding, Project, Rule, SourceFile,
                                  call_name, const_str)

_SPAWN_SUFFIXES = {"threading.Thread": "thread", "Thread": "thread",
                   "subprocess.Popen": "process", "Popen": "process"}

#: Paths (prefix match on the repo-relative posix path) where spawns
#: are the registry's own machinery — or, for the elastic agent, a
#: deliberately jax-free supervisor process: the agent launches and
#: reaps the trainer children that HOST the registry; it has no obs
#: runtime of its own to register with, and its supervise loop (poll
#: + heartbeat files) is its own inventory.
_ALLOWLIST_PREFIXES = ("tpunet/obs/flightrec/",
                       "tpunet/elastic/agent.py")

_REGISTRY_NAMES = {"register_thread", "THREADS"}


def _scope_chain(tree: ast.AST) -> List[Tuple[ast.AST, ast.AST]]:
    """(node, enclosing scope node) for every Call, where scope is the
    nearest ClassDef if any, else nearest FunctionDef, else module."""
    out: List[Tuple[ast.AST, ast.AST]] = []

    def walk(node: ast.AST, cls: Optional[ast.AST],
             fn: Optional[ast.AST], module: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            next_cls, next_fn = cls, fn
            if isinstance(child, ast.ClassDef):
                next_cls, next_fn = child, None
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                next_fn = child
            if isinstance(child, ast.Call):
                out.append((child, cls or fn or module))
            walk(child, next_cls, next_fn, module)

    walk(tree, None, None, tree)
    return out


def _references_registry(scope: ast.AST) -> bool:
    for node in ast.walk(scope):
        if isinstance(node, ast.Name) and node.id in _REGISTRY_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _REGISTRY_NAMES:
            return True
        if isinstance(node, ast.ImportFrom):
            if any(a.name in _REGISTRY_NAMES for a in node.names):
                return True
    return False


class ThreadRule(Rule):
    id = "R4"
    name = "thread-registry"
    doc = ("every threading.Thread/subprocess.Popen spawn in tpunet/ "
           "registers with the flightrec THREADS registry or is "
           "allowlisted")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for src in project.files():
            if src.tree is None:
                continue
            if not src.rel.startswith("tpunet/"):
                continue
            if src.rel.startswith(_ALLOWLIST_PREFIXES):
                continue
            for call, scope in _scope_chain(src.tree):
                kind = _SPAWN_SUFFIXES.get(call_name(call))
                if kind is None:
                    continue
                if _references_registry(scope):
                    continue
                scope_name = getattr(scope, "name", "<module>")
                spawn_name = ""
                for kw in call.keywords:
                    if kw.arg == "name":
                        spawn_name = const_str(kw.value) or ""
                detail = spawn_name or f"in {scope_name}"
                findings.append(Finding(
                    rule="R4", path=src.rel, line=call.lineno,
                    message=(f"{kind} spawn ({detail}) does not register "
                             "with the flightrec host-thread registry — "
                             "it will be invisible to crash reports, "
                             "thread_* gauges, and the thread_stalled "
                             "watchdog"),
                    hint=("handle = flightrec.register_thread(\"<name>\""
                          ", stall_after_s=...) next to the spawn and "
                          "beat busy/idle around blocking work; "
                          "genuinely unmanaged spawns go in the "
                          "baseline with a justification"),
                    key=f"{kind}:{scope_name}:{spawn_name or 'anon'}"))
        return findings
