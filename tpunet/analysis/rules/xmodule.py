"""R7 — cross-module donation taint (the R1 gap the re-mesh closed).

R1's taint analysis stops at module edges: a call into another module
is opaque, so it falls back to a *name* heuristic (``restore``/
``load``-ish names taint) that both over-approximates (a function that
re-materializes before returning is still flagged — hence the
baseline ledger) and under-approximates (``grab_state()`` returning a
``pickle.load`` escapes entirely). The elastic re-mesh made the gap
load-bearing: the restore path now spans ``elastic/`` -> ``ckpt/`` ->
``train/loop.py``, and the property that keeps it crash-free — the
restored state is re-materialized (``jnp.copy``) BEFORE the trainer
donates it — is a cross-module contract no single-file rule can see.

R7 sees it. Two phases over the whole project:

1. **summaries** (to a fixpoint): for every function/method, decide
   whether its *return value* carries IO taint, using R1's own
   analyzer over the body — ``pickle.load``-style origins taint,
   ``jnp.copy``/``device_put``/``tree_map(jnp.copy, ...)`` clear, and
   calls to already-summarized tainted functions propagate
   (transitive). A function whose return is re-materialized gets a
   CLEAN summary, exactly the precision R1's name heuristic lacks.
2. **reporting**: re-run the call-site analysis with the summarized
   tainted names as the ONLY taint sources. Names R1's heuristic
   already matches are excluded from summaries on purpose: those
   findings belong to R1 (and its baseline entries), so R7 never
   duplicates them — it reports only what crossing the module
   boundary revealed.

Approximations (same spirit as R1's): resolution is by bare callee
name, not import graph — two modules defining same-named functions
share a summary (over-approximation, baseline-able); a summary is
flow-insensitive over returns (ANY tainted return taints the
function).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tpunet.analysis.core import (Finding, Project, Rule, SourceFile,
                                  call_name)
from tpunet.analysis.rules.donation import _IO_NAME_RE, _Analyzer


class _SummaryAnalyzer(_Analyzer):
    """R1's analyzer + 'calls to summarized-tainted names are IO'."""

    def __init__(self, src: SourceFile, findings: List[Finding],
                 flow_sensitive: bool, extra_io: Set[str]) -> None:
        super().__init__(src, findings, flow_sensitive)
        self.extra_io = extra_io

    def is_io_call(self, node: ast.Call) -> bool:
        name = call_name(node)
        if name:
            last = name.rsplit(".", 1)[-1]
            if last in self.extra_io and not self.is_safe_wrapper(node):
                return True
        return super().is_io_call(node)


class _ReportAnalyzer(_Analyzer):
    """Call-site reporter whose ONLY taint sources are the summarized
    cross-module names — R1-heuristic origins are invisible here, so
    R7 findings never duplicate R1 findings."""

    def __init__(self, src: SourceFile, findings: List[Finding],
                 flow_sensitive: bool, extra_io: Set[str]) -> None:
        super().__init__(src, findings, flow_sensitive)
        self.extra_io = extra_io

    def is_io_call(self, node: ast.Call) -> bool:
        name = call_name(node)
        if not name:
            return False
        last = name.rsplit(".", 1)[-1]
        return last in self.extra_io and not self.is_safe_wrapper(node)


def _return_exprs(fn: ast.AST) -> List[ast.AST]:
    """Return expressions of ``fn``'s own body (nested function defs
    return for themselves, not for ``fn``)."""
    out: List[ast.AST] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            if isinstance(child, ast.Return) and child.value is not None:
                out.append(child.value)
            walk(child)

    for stmt in getattr(fn, "body", []):
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            out.append(stmt.value)
        walk(stmt)
    return out


def _function_defs(src: SourceFile
                   ) -> List[Tuple[str, ast.AST, bool]]:
    """(bare name, def node, is_method) for module-level functions and
    class methods."""
    out: List[Tuple[str, ast.AST, bool]] = []
    assert isinstance(src.tree, ast.Module)
    for stmt in src.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((stmt.name, stmt, False))
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out.append((sub.name, sub, True))
    return out


def _returns_tainted(src: SourceFile, fn: ast.AST,
                     extra_io: Set[str]) -> bool:
    """Does ``fn``'s return value carry IO taint? Runs the R1 machinery
    over the body (populating local taint), then evaluates each return
    expression against it."""
    analyzer = _SummaryAnalyzer(src, [], flow_sensitive=True,
                                extra_io=extra_io)
    analyzer.scan_statements(getattr(fn, "body", []))
    return any(analyzer._tainted_expr(expr) is not None
               for expr in _return_exprs(fn))


class CrossModuleDonationRule(Rule):
    id = "R7"
    name = "cross-module-donation"
    doc = ("IO-tainted values returned by project functions (whose "
           "names R1's heuristic misses) flowing into donated jit "
           "args across module boundaries — the elastic re-mesh "
           "restore-path contract")

    MAX_FIXPOINT = 8

    def run(self, project: Project) -> List[Finding]:
        files = [src for src in project.files() if src.tree is not None]
        # Phase 1: whole-project taint summaries, to a fixpoint so
        # wrapper-of-wrapper chains (transitive) converge. Names the
        # R1 heuristic already matches are R1's jurisdiction.
        tainted_names: Set[str] = set()
        defs: Dict[str, List[Tuple[SourceFile, ast.AST]]] = {}
        for src in files:
            for name, fn, _ in _function_defs(src):
                defs.setdefault(name, []).append((src, fn))
        for _ in range(self.MAX_FIXPOINT):
            grew = False
            for name, sites in defs.items():
                if name in tainted_names \
                        or _IO_NAME_RE.search(name):
                    continue
                # Conservative across same-name collisions: tainted if
                # ANY definition's return is tainted.
                if any(_returns_tainted(src, fn, tainted_names)
                       for src, fn in sites):
                    tainted_names.add(name)
                    grew = True
            if not grew:
                break
        if not tainted_names:
            return []
        # Phase 2: call-site reporting with ONLY the summarized names
        # as taint sources (R1's own scope/class discipline reused).
        findings: List[Finding] = []
        for src in files:
            assert isinstance(src.tree, ast.Module)
            module_stmts: List[ast.stmt] = []
            for stmt in src.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    _ReportAnalyzer(src, findings, False,
                                    tainted_names) \
                        .scan_statements(stmt.body, passes=2)
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    _ReportAnalyzer(src, findings, True,
                                    tainted_names) \
                        .scan_statements(stmt.body)
                else:
                    module_stmts.append(stmt)
            _ReportAnalyzer(src, findings, True, tainted_names) \
                .scan_statements(module_stmts)
        return [Finding(
            rule="R7", path=f.path, line=f.line,
            message=f.message.replace(
                "(the PR-7 resume heap-corruption class)",
                "(cross-module: the producer lives in another "
                "module and its return is IO-tainted — the PR-7 "
                "resume heap-corruption class, invisible to "
                "single-module R1)"),
            hint=("re-materialize in the producer (return "
                  "jnp.copy(...) / tree_map(jnp.copy, ...)) or at "
                  "the call site before donating; a reviewed "
                  "exception goes in docs/tpucheck_baseline.json"),
            key=f"x{f.key}") for f in findings]
