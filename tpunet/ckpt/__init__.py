from tpunet.ckpt.orbax_io import Checkpointer  # noqa: F401
