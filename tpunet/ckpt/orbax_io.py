"""Orbax checkpointing: best-params (parity) + full-state resume (upgrade).

The reference deep-copies the state_dict whenever test accuracy improves
and torch.saves the best copy once at the very end, from rank 0 only
(cifar10_mpi_mobilenet_224.py:160,238-240,249); optimizer/scheduler/epoch
state is never persisted, so a crashed run restarts from scratch
(SURVEY.md section 5). Here:

- ``save_best`` persists the best params+batch_stats *when* they improve
  (crash-safe, unlike save-at-end), under ``best/``;
- ``save_state`` persists the FULL train state (params, batch_stats,
  optimizer state, step, epoch, best accuracy) per epoch under a
  step-numbered directory, enabling exact resume;
- restores are sharding-aware: arrays come back laid out for the current
  mesh (orbax handles multi-host saves natively).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import orbax.checkpoint as ocp

from tpunet.config import CheckpointConfig


class Checkpointer:
    def __init__(self, cfg: CheckpointConfig):
        self.cfg = cfg
        self.directory = os.path.abspath(os.path.expanduser(cfg.directory))
        self._mgr: Optional[ocp.CheckpointManager] = None
        self._best = ocp.StandardCheckpointer()

    @property
    def manager(self) -> ocp.CheckpointManager:
        if self._mgr is None:
            self._mgr = ocp.CheckpointManager(
                os.path.join(self.directory, "state"),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.cfg.keep, create=True),
            )
        return self._mgr

    # -- full train state (resume) -------------------------------------

    def save_state(self, step: int, payload: Dict[str, Any]) -> None:
        if not self.cfg.save_last:
            return
        self.manager.save(step, args=ocp.args.StandardSave(payload))

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore_state(self, target: Dict[str, Any],
                      step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Restore the latest (or given) step into ``target``'s structure
        and shardings; returns None when no checkpoint exists."""
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            return None
        return self.manager.restore(
            step, args=ocp.args.StandardRestore(target))

    # -- best params (reference parity) --------------------------------

    def save_best(self, payload: Dict[str, Any]) -> None:
        if not self.cfg.save_best:
            return
        path = os.path.join(self.directory, "best")
        self._best.save(path, payload, force=True)

    def restore_best(self, target: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        path = os.path.join(self.directory, "best")
        if not os.path.isdir(path):
            return None
        return self._best.restore(path, target=target)

    def wait(self) -> None:
        """Block until async writes are durable (end of run)."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()
        self._best.wait_until_finished()

    def close(self) -> None:
        self.wait()
        if self._mgr is not None:
            self._mgr.close()
