"""Orbax checkpointing: best-params (parity) + full-state resume (upgrade).

The reference deep-copies the state_dict whenever test accuracy improves
and torch.saves the best copy once at the very end, from rank 0 only
(cifar10_mpi_mobilenet_224.py:160,238-240,249); optimizer/scheduler/epoch
state is never persisted, so a crashed run restarts from scratch
(SURVEY.md section 5). Here:

- ``save_best`` persists the best params+batch_stats *when* they improve
  (crash-safe, unlike save-at-end), under ``best/``;
- ``save_state`` persists the FULL train state (params, batch_stats,
  optimizer state, step, epoch, best accuracy) per epoch under a
  step-numbered directory, enabling exact resume;
- restores are sharding-aware: arrays come back laid out for the current
  mesh (orbax handles multi-host saves natively);
- saves are FULLY async. Orbax's own async mode still runs a blocking
  phase on the caller (per-array spec/metadata setup + the device->host
  copy — measured ~1s for MobileNetV2's 585-leaf state, ~13s on the
  first save), so save_state/save_best instead (1) snapshot every jax
  array ON-DEVICE (``jnp.copy`` — an async HBM copy that decouples the
  checkpoint from the train step's donated buffers) and (2) hand the
  whole orbax save to a single background worker thread. The step loop
  pays only the copy dispatch (~ms); orbax's blocking phase, the
  serialization and the IO all run behind the next epoch
  (runs/ckpt-async/STALL.json measures the before/after). The on-device
  snapshot keeps multi-host sharded state on its native orbax path
  (device_get would break non-addressable FSDP shards).
  ``wait()`` is the durability barrier — end of run, before raising
  past a checkpoint an error message promises, and inside close();
  background save errors surface there (and at the next restore, which
  drains pending saves first). The worker is one thread, so saves
  stay ordered; on multi-host every process dispatches the same saves
  in the same order, preserving orbax's cross-host barrier pairing.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from tpunet.config import CheckpointConfig
from tpunet.obs import flightrec


def emit_io_retry_alert(registry, *, what: str, error: str,
                        max_retries: int, backoff_s: float) -> None:
    """One loud ``obs_alert`` (reason ``ckpt_io_retry``) per retry
    burst: the page says checkpoint IO went transiently bad BEFORE the
    run either recovers silently or dies on the exhausted retry.
    Module-level so the schema-conformance check can drive the exact
    emission shape without a Checkpointer."""
    if registry is None:
        return
    registry.counter("obs_alerts").inc()
    registry.emit("obs_alert", {
        "reason": "ckpt_io_retry", "step": 0, "severity": "warn",
        "what": what, "error": error, "max_retries": max_retries,
        "backoff_s": backoff_s,
    })


def _chaos():
    """The installed fault injector (``--chaos``), or None. Looked up
    lazily at each IO point so the Checkpointer costs nothing when
    chaos is not armed and never imports the elastic package first."""
    from tpunet.elastic import chaos
    return chaos.current()


def _multiprocessing_options() -> Optional["ocp.options.MultiprocessingOptions"]:
    """Coordination-service barriers for multi-host orbax.

    Orbax's default cross-host barrier is an XLA computation
    (``sync_global_devices``) — run from our background writer thread
    it interleaves with the step loop's own cross-process
    computations and aborts the transport (observed on CPU gangs as
    gloo's "op.preamble.length <= op.nbytes" hard abort mid-save;
    the same enqueue-order hazard exists on any backend). With
    ``active_processes`` set, orbax switches every barrier to the
    jax coordination-service KV barrier, which its own docs mark
    "safe to use from independent background threads" — exactly our
    writer-thread situation. None single-process or when no
    coordination client exists (then no barriers run at all)."""
    if jax.process_count() <= 1:
        return None
    from tpunet.parallel.dist import coordination_client
    if coordination_client() is None:
        return None
    return ocp.options.MultiprocessingOptions(
        active_processes=set(range(jax.process_count())))


def _snapshot(tree):
    """On-device copy of every jax array leaf: the checkpoint's view
    survives the train step's buffer donation, at the cost of one
    transient HBM copy (freed when the background write completes)."""
    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, tree)


class Checkpointer:
    # Transient-IO discipline: a save/restore OSError is retried this
    # many times with exponential backoff (base IO_BACKOFF_S, doubling
    # per attempt) before propagating. One obs_alert per burst
    # (reason ckpt_io_retry) + the ckpt_io_retries counter make the
    # flakiness visible even when every retry succeeds.
    IO_RETRIES = 3
    IO_BACKOFF_S = 0.1

    def __init__(self, cfg: CheckpointConfig, obs=None):
        self.cfg = cfg
        self.directory = os.path.abspath(os.path.expanduser(cfg.directory))
        self._mgr: Optional[ocp.CheckpointManager] = None
        self._mp_options = _multiprocessing_options()
        self._best = (ocp.StandardCheckpointer()
                      if self._mp_options is None
                      else ocp.StandardCheckpointer(
                          multiprocessing_options=self._mp_options))
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending = []
        # 1-based dispatch ordinals: the chaos injector addresses "the
        # K-th save" / "the K-th restore" deterministically.
        self._save_index = 0
        self._restore_index = 0
        # Escalated-preemption escape hatch: once abandoned, nothing
        # here blocks again (wait/close become no-ops) — the process
        # is exiting inside a nearly-spent grace window.
        self._abandoned = False
        # Optional Observability (tpunet/obs/): labels save dispatch
        # and durability waits as xprof spans and accounts their host
        # cost (ckpt_saves / ckpt_wait_s) — the "is the step loop
        # stalling on checkpoints?" half of the stall split.
        self._obs = obs

    def _with_io_retry(self, what: str, fn: Callable[[int], Any]) -> Any:
        """Run ``fn(attempt)`` with bounded retry + exponential backoff
        on OSError (the transient-IO shape: NFS blips, GCS 5xx surfaced
        as IOError, chaos injection). Non-OSErrors propagate untouched
        — a corrupt checkpoint is not transient."""
        attempt = 0
        while True:
            try:
                return fn(attempt)
            except OSError as e:
                attempt += 1
                if attempt > self.IO_RETRIES:
                    raise
                if attempt == 1:
                    emit_io_retry_alert(
                        self._obs.registry if self._obs is not None
                        else None,
                        what=what, error=str(e),
                        max_retries=self.IO_RETRIES,
                        backoff_s=self.IO_BACKOFF_S)
                if self._obs is not None:
                    self._obs.registry.counter("ckpt_io_retries").inc()
                flightrec.record(
                    "ckpt", f"io retry {what} attempt={attempt}")
                time.sleep(self.IO_BACKOFF_S * (2 ** (attempt - 1)))

    def _span(self, name: str):
        if self._obs is not None:
            return self._obs.span(name)
        from contextlib import nullcontext
        return nullcontext()

    @property
    def manager(self) -> ocp.CheckpointManager:
        if self._mgr is None:
            state_dir = os.path.join(self.directory, "state")
            kw = {}
            create = True
            if self._mp_options is not None:
                # KV barriers (see _multiprocessing_options). Orbax
                # refuses create=True with active_processes, so make
                # the directory ourselves (shared fs, idempotent).
                kw["multiprocessing_options"] = self._mp_options
                create = False
                os.makedirs(state_dir, exist_ok=True)
            self._mgr = ocp.CheckpointManager(
                state_dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.cfg.keep, create=create,
                    # Explicit, not default-dependent: even with the
                    # worker thread owning the blocking phase, the
                    # write itself should overlap manager bookkeeping.
                    enable_async_checkpointing=True, **kw),
            )
        return self._mgr

    def _submit(self, fn) -> None:
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="tpunet-ckpt")
            # Host-thread registry: the orbax writer is the
            # longest-lived background competitor of the step loop —
            # register it with a generous budget (a multi-GB sharded
            # save can legitimately take minutes; past that, page).
            self._thread = flightrec.register_thread(
                "ckpt-writer", stall_after_s=600.0)

        def run(fn=fn):
            self._thread.beat("busy")
            flightrec.record("ckpt", "save begin")
            try:
                fn()
            finally:
                self._thread.beat("idle")
                flightrec.record("ckpt", "save end")
        # Back-pressure: each queued save pins an on-device snapshot,
        # so never hold more than one in flight + one queued — when the
        # writer lags the step loop (epochs shorter than writes), the
        # loop degrades to waiting rather than accumulating HBM copies.
        # Completed futures are JOINED (.result()), not just dropped:
        # a background save that failed must raise at the next save,
        # not vanish (the docstring's errors-surface promise).
        still = []
        for f in self._pending:
            if f.done():
                f.result()
            else:
                still.append(f)
        self._pending = still
        if len(self._pending) > 1:
            # THIS join is where the step loop actually stalls on
            # checkpoints mid-run (wait() only runs at end-of-run,
            # after the last obs record) — so it is the accumulation
            # point that makes ckpt_wait_s a live number.
            import time
            t0 = time.perf_counter()
            while len(self._pending) > 1:
                self._pending.pop(0).result()
            if self._obs is not None:
                self._obs.registry.counter("ckpt_wait_s").inc(
                    time.perf_counter() - t0)
        self._pending.append(self._pool.submit(run))

    def _drain(self) -> None:
        """Join queued background saves, surfacing their errors."""
        pending, self._pending = self._pending, []
        for f in pending:
            f.result()

    def saving_in_progress(self) -> bool:
        """True while a dispatched save is queued or being written (the
        async-overlap observability hook the tests use)."""
        if any(not f.done() for f in self._pending):
            return True
        return (self._mgr is not None
                and self._mgr.is_saving_in_progress())

    # -- full train state (resume) -------------------------------------

    def save_state(self, step: int, payload: Dict[str, Any]) -> None:
        if not self.cfg.save_last:
            return
        flightrec.record("ckpt", f"dispatch state step={step}")
        with self._span("tpunet/ckpt_dispatch"):
            snap = _snapshot(payload)
        if self._obs is not None:
            self._obs.registry.counter("ckpt_saves").inc()
        self._save_index += 1
        save_index = self._save_index
        # The manager is created INSIDE the worker lambda on purpose:
        # CheckpointManager.__init__ runs a cross-host barrier
        # (sync_global_processes), so on multi-host it must stay
        # serialized with every other orbax collective on the ONE
        # worker thread — creating it on the caller thread while a
        # background best-save is mid-barrier interleaves the two
        # barrier sequences differently per process ("sync_global_
        # devices name mismatch", caught by test_two_process_
        # checkpoint_roundtrip). The observability race this once
        # suggested (saving_in_progress() reading self._mgr mid-
        # construction) is covered by its pending-futures check: the
        # submitted future is not done while the manager is being
        # built.
        def write(attempt: int) -> None:
            chaos = _chaos()
            if chaos is not None:
                # May raise the injected transient OSError (exercised
                # by the retry wrapper below) — addressed by dispatch
                # ordinal + attempt, so the scenario is deterministic.
                chaos.save_attempt(save_index, attempt)
            self.manager.save(step, args=ocp.args.StandardSave(snap))
            if chaos is not None:
                # Mid-checkpoint-write kill point: the orbax write is
                # dispatched but not yet finalized — dying here leaves
                # a torn, uncommitted step directory that restore MUST
                # skip in favor of the previous intact checkpoint.
                chaos.save_in_flight(save_index)

        self._submit(lambda: self._with_io_retry("save", write))

    def latest_step(self) -> Optional[int]:
        self._drain()
        return self.manager.latest_step()

    def restore_state(self, target: Dict[str, Any],
                      step: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Restore the latest (or given) step into ``target``'s structure
        and shardings; returns None when no checkpoint exists."""
        self._drain()
        self.manager.wait_until_finished()
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            return None
        # Restore only the keys the checkpoint actually HAS: payloads
        # grow keys across versions (e.g. pp_layout), and StandardRestore
        # raises opaquely on a target leaf the save never wrote. Filtering
        # here lets the caller's restored.get(key, default) handle older
        # checkpoints gracefully.
        try:
            meta = self.manager.item_metadata(step)
            tree = getattr(meta, "tree", None) if meta is not None else None
            if tree is None:
                # Fresh manager (no save/restore yet in this process):
                # item_metadata can't infer the handler — read the tree
                # structure straight off the step's default item.
                with ocp.Checkpointer(
                        ocp.StandardCheckpointHandler()) as probe:
                    sm = probe.metadata(
                        os.path.join(str(self.manager.directory),
                                     str(step), "default"))
                tree = getattr(getattr(sm, "item_metadata", None),
                               "tree", None)
            if isinstance(tree, dict) and isinstance(target, dict):
                target = {k: v for k, v in target.items() if k in tree}
        except Exception as e:
            # Best-effort — restore decides. But LOG it: on multi-host,
            # one controller's probe failing while the others' succeed
            # means asymmetric restore targets (a missing-leaf raise on
            # one host vs a barrier wait on the rest); the message is
            # the breadcrumb that makes that diagnosable.
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint metadata probe failed (restoring with the "
                "full target): %s", e)
        self._restore_index += 1
        restore_index = self._restore_index

        def read(attempt: int):
            chaos = _chaos()
            if chaos is not None:
                chaos.restore_attempt(restore_index, attempt)
            return self.manager.restore(
                step, args=ocp.args.StandardRestore(target))

        restored = self._with_io_retry("restore", read)
        # Re-materialize every restored array as an XLA-owned copy
        # (one transient duplicate, freed immediately). ROOT CAUSE of
        # the long-open resume heap corruption (ROADMAP bug, flight-
        # recorder A/B in runs/flightrec-repro-r7): arrays coming out
        # of the orbax/tensorstore restore can alias IO-path host
        # buffers, and the trainer DONATES the state to its first
        # step (donate_argnums=0) — XLA then frees/reuses memory the
        # IO path still owns, and glibc aborts ("corrupted
        # double-linked list" / "free(): invalid size" / SIGSEGV) at
        # the next allocation, right after "Starting training...".
        # On the repro dir: 10/10 crash with donation, 4/4 clean with
        # donation disabled, 4/4 clean with donation + this copy;
        # fresh runs were never affected because init states are
        # XLA-allocated from birth.
        return jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
            restored)

    # -- best params (reference parity) --------------------------------

    def save_best(self, payload: Dict[str, Any],
                  meta: Optional[Dict[str, Any]] = None) -> None:
        if not self.cfg.save_best:
            return
        flightrec.record("ckpt", "dispatch best")
        with self._span("tpunet/ckpt_dispatch"):
            snap = _snapshot(payload)
        if self._obs is not None:
            self._obs.registry.counter("ckpt_saves").inc()
        path = os.path.join(self.directory, "best")
        meta_path = os.path.join(self.directory, "best_meta.json")

        def write():
            wrote_sidecar = prev = None
            if meta is not None and jax.process_index() == 0:
                # Sidecar layout metadata (JSON, human-readable): lets
                # serving recover e.g. the interleaved schedule's
                # chunk permutation without operator-remembered flags
                # (tpunet/infer/generate.py load_lm). Written BEFORE
                # the orbax save so the save's cross-host commit
                # barrier orders it: any process that observes the new
                # best/ (via wait()/restore_best()) also observes the
                # matching sidecar — writing it after would let a
                # non-zero host pair fresh params with a stale sidecar
                # whenever process 0's worker thread lags the barrier.
                import json
                # The sidecar may be the FIRST write under directory
                # (the orbax save that used to create it now runs
                # after us).
                os.makedirs(self.directory, exist_ok=True)
                if os.path.isfile(meta_path):
                    with open(meta_path) as f:
                        prev = f.read()
                tmp = meta_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(meta, f, indent=1)
                os.replace(tmp, meta_path)
                wrote_sidecar = True
            def write_best(attempt: int) -> None:
                self._best.save(path, snap, force=True)
                # StandardCheckpointer is an AsyncCheckpointer: the
                # write/commit runs on orbax's own background thread
                # and its failure surfaces only at
                # wait_until_finished(). Join it HERE, inside the same
                # try — we already run on the dedicated worker thread,
                # so blocking costs the step loop nothing, and an
                # async-phase failure (disk full mid-write) now rolls
                # the sidecar back like a synchronous one (or is
                # retried as a transient by the wrapper).
                self._best.wait_until_finished()

            try:
                self._with_io_retry("save_best", write_best)
            except BaseException:
                # Roll the sidecar back: a failed best-save must not
                # leave a NEW sidecar durably paired with the OLD
                # best/ params (serving would trust its pp_layout and
                # mis-permute the old stack).
                if wrote_sidecar:
                    if prev is None:
                        os.unlink(meta_path)
                    else:
                        tmp = meta_path + ".tmp"
                        with open(tmp, "w") as f:
                            f.write(prev)
                        os.replace(tmp, meta_path)
                raise

        self._submit(write)

    def best_meta(self) -> Optional[Dict[str, Any]]:
        """The sidecar metadata written alongside best/, or None."""
        self._drain()  # like restore_best: never pair new params with a
        # stale sidecar while a save_best is still queued behind us
        path = os.path.join(self.directory, "best_meta.json")
        if not os.path.isfile(path):
            return None
        import json
        with open(path) as f:
            return json.load(f)

    def restore_best(self, target: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        self._drain()
        self._best.wait_until_finished()
        path = os.path.join(self.directory, "best")
        if not os.path.isdir(path):
            return None
        return self._best.restore(path, target=target)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until async writes are durable (end of run). With a
        ``timeout`` (the preemption path's remaining grace budget),
        the wait is bounded: on expiry it logs loudly and returns
        False — the checkpoint may not be durable, but blowing the
        platform's grace window guarantees a SIGKILL mid-write, which
        is strictly worse. Returns True when everything committed."""
        if self._abandoned:
            return False
        t0 = time.perf_counter()
        flightrec.record("ckpt", "wait begin")
        with self._span("tpunet/ckpt_wait"):
            if timeout is None:
                self._drain()
                if self._mgr is not None:
                    self._mgr.wait_until_finished()
                self._best.wait_until_finished()
                durable = True
            else:
                durable = self._bounded_drain(timeout)
        flightrec.record("ckpt", "wait end")
        if self._obs is not None:
            self._obs.registry.counter("ckpt_wait_s").inc(
                time.perf_counter() - t0)
        return durable

    def _bounded_drain(self, timeout: float) -> bool:
        import threading
        from concurrent.futures import TimeoutError as FutTimeout
        deadline = time.perf_counter() + max(0.0, timeout)
        pending = list(self._pending)
        for f in pending:
            budget = deadline - time.perf_counter()
            try:
                f.result(timeout=max(0.0, budget))
            except FutTimeout:
                # The budget is spent: give up for good (abandon —
                # this and every later future's result is forfeit by
                # design, the process is exiting). Any later blocking
                # wait (close() in main's finally) would hold the
                # process past the platform's SIGKILL — strictly
                # worse than resuming from the previous intact
                # checkpoint.
                return self._grace_expired(timeout)
        self._pending = []
        # The orbax managers expose no timed wait — bound their
        # commit join with a side thread so a slow async finalize
        # cannot blow the window either.
        finished = threading.Event()

        def _orbax_join() -> None:
            try:
                if self._mgr is not None:
                    self._mgr.wait_until_finished()
                self._best.wait_until_finished()
            finally:
                finished.set()

        threading.Thread(target=_orbax_join,
                         name="tpunet-ckpt-grace-join",
                         daemon=True).start()
        if finished.wait(timeout=max(0.0,
                                     deadline - time.perf_counter())):
            return True
        return self._grace_expired(timeout)

    def _grace_expired(self, timeout: float) -> bool:
        """The grace budget ran out mid-drain: warn loudly and go
        permanently non-blocking (abandon) so no later wait/close can
        stall the exiting process."""
        print("WARNING: checkpoint durability wait exceeded the "
              f"{timeout:.1f}s grace budget — the last save may not "
              "be committed; resume will fall back to the previous "
              "intact checkpoint", flush=True)
        self.abandon()
        return False

    def abandon(self) -> None:
        """Escalated preemption: stop blocking on checkpoint work,
        permanently. Queued saves are dropped (their futures may still
        run on daemon-irrelevant worker threads, but nothing joins
        them) and every later ``wait``/``close`` is a no-op — the
        caller is exiting NOW and resume falls back to the last
        committed checkpoint."""
        flightrec.record("ckpt", "abandon")
        self._abandoned = True
        self._pending = []
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def close(self) -> None:
        if self._abandoned:
            return
        self.wait()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        if self._mgr is not None:
            self._mgr.close()
