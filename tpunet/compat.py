"""jax version-compatibility layer.

The codebase targets the modern jax surface; these wrappers let the
same call sites run on older installs. Import from here instead of
reaching into jax version-conditionally at each site — and never patch
the jax namespace itself (other libraries in the process probe
``hasattr(jax, ...)`` and must see the real jax).

- ``shard_map``: ``jax.shard_map`` (with ``check_vma``) on new jax;
  on older installs, ``jax.experimental.shard_map.shard_map`` with
  ``check_vma`` translated to its old spelling ``check_rep``.
- ``def_partition_compat``: ``custom_partitioning.def_partition``
  minus the Shardy keywords (``sharding_rule``,
  ``need_replication_factors``) on pre-Shardy jax, where the GSPMD
  callbacks carry the full partitioning behavior — passing them there
  raises TypeError at import time.
"""

from __future__ import annotations

import functools
import inspect

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_old

    _old_params = set(inspect.signature(_shard_map_old).parameters)

    @functools.wraps(_shard_map_old)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs and "check_vma" not in _old_params:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_old(f, *args, **kwargs)


def _supported_kwargs(fn) -> set:
    sig = inspect.signature(fn)
    if any(p.kind is inspect.Parameter.VAR_KEYWORD
           for p in sig.parameters.values()):
        return set()  # sentinel: accepts anything
    return set(sig.parameters)


def def_partition_compat(partitioned, **kwargs) -> None:
    """``partitioned.def_partition(**kwargs)`` minus any keyword the
    installed jax does not know (Shardy args on pre-Shardy jax)."""
    supported = _supported_kwargs(partitioned.def_partition)
    if supported:
        kwargs = {k: v for k, v in kwargs.items() if k in supported}
    partitioned.def_partition(**kwargs)
