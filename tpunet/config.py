"""Configuration system.

The reference hardcodes every hyperparameter as a module literal
(cifar10_mpi_mobilenet_224.py: IMG_SIZE=224 at :70, batch=128 at :117,
Adam lr=1e-4 at :148, StepLR(10, 0.1) at :149, EPOCHS=20 at :158,
seed=42 at :58). We keep those exact values as *defaults* of a frozen
dataclass tree so every benchmark config is reproducible, and expose an
argparse front-end with presets matching the reference's three launch
modes (serial CPU / single accelerator / distributed data-parallel).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple

# ImageNet normalization statistics — the reference trains with these
# (cifar10_mpi_mobilenet_224.py:81-82) and its Gradio app wrongly serves
# with CIFAR-10 stats (GROUP03.pdf p.22, a train/serve skew bug we fix by
# using one constant everywhere).
IMAGENET_MEAN: Tuple[float, float, float] = (0.485, 0.456, 0.406)
IMAGENET_STD: Tuple[float, float, float] = (0.229, 0.224, 0.225)

CIFAR10_CLASSES: Tuple[str, ...] = (
    "airplane", "automobile", "bird", "cat", "deer",
    "dog", "frog", "horse", "ship", "truck",
)


@dataclass(frozen=True)
class DataConfig:
    """Data pipeline config (reference transforms at :72-89, loaders :117-133)."""

    data_dir: str = "data"
    dataset: str = "cifar10"          # "cifar10" | "synthetic"
    # Auto-download CIFAR-10 when absent (reference download=True, :97).
    # --no-download disables; the error then documents the drop-in path.
    download: bool = True
    image_size: int = 224             # reference IMG_SIZE (:70)
    batch_size: int = 128             # GLOBAL batch (reference :117 is per-rank)
    eval_batch_size: int = 0          # 0 -> same as batch_size
    num_classes: int = 10
    # Augmentation parameters mirroring the reference torchvision stack
    # (:72-82): RandomResizedCrop scale, ColorJitter strengths, rotation.
    rrc_scale: Tuple[float, float] = (0.7, 1.0)
    rrc_ratio: Tuple[float, float] = (0.75, 4.0 / 3.0)
    jitter_brightness: float = 0.3
    jitter_contrast: float = 0.3
    jitter_saturation: float = 0.3
    jitter_hue: float = 0.1
    rotation_degrees: float = 15.0
    mean: Tuple[float, float, float] = IMAGENET_MEAN
    std: Tuple[float, float, float] = IMAGENET_STD
    # Mixup / CutMix (beyond the reference's transforms; 0 = off, the
    # reference behavior). Beta(alpha, alpha) mixing inside the jitted
    # step; with both > 0 each step picks one at random.
    mixup_alpha: float = 0.0
    cutmix_alpha: float = 0.0
    # Synthetic-dataset sizes (CIFAR-10-shaped stand-in for hermetic runs).
    synthetic_train_size: int = 50_000
    synthetic_test_size: int = 10_000
    # Token datasets (model "lm"): "synthetic_lm" generates seeded
    # bigram data with this sequence length and vocab; "text_lm" chunks
    # the raw bytes of `text_path` (byte-level, vocab 256, no
    # tokenizer/downloads). vocab_size must match ModelConfig.vocab_size
    # (the CLI --vocab-size sets both).
    seq_len: int = 128
    vocab_size: int = 256
    text_path: str = ""
    # text_lm only: split the corpus into newline-delimited documents
    # and PACK them into seq_len rows with per-token segment ids;
    # attention and the next-token loss are then masked so nothing
    # crosses a document boundary or touches padding.
    pack_docs: bool = False
    # Deviation from torch DistributedSampler (which pads shards to equal
    # length, :119-124): we drop the train remainder and evaluate the test
    # set exactly (padding with masked examples), which also fixes the
    # reference's rank-local-accuracy wart (:196,224).
    drop_remainder: bool = True
    # Host-side batch assembly through the native C++ prefetcher
    # (cxx/batcher.cc) when its shared library is buildable; falls back
    # to the pure-numpy path silently otherwise.
    native_loader: bool = True

    @property
    def effective_eval_batch_size(self) -> int:
        return self.eval_batch_size or self.batch_size


@dataclass(frozen=True)
class ModelConfig:
    """Model config (reference model at :137-139: torchvision MobileNetV2
    with the classifier head swapped to 10 classes)."""

    name: str = "mobilenet_v2"        # mobilenet_v2 | vit | vit_{tiny,small,base} | vit_pp | lm
    num_classes: int = 10
    width_mult: float = 1.0
    dropout_rate: float = 0.2         # torchvision MobileNetV2 default
    dtype: str = "bfloat16"           # MXU-friendly compute dtype
    param_dtype: str = "float32"
    # ViT family fields (tpunet/models/vit.py); used when name == "vit"
    # (the vit_tiny/small/base presets fix patch/hidden/depth/heads).
    vit_patch: int = 16
    vit_hidden: int = 192
    vit_depth: int = 6
    vit_heads: int = 3
    vit_mlp_ratio: float = 4.0
    # Core attention implementation for attention models:
    # auto (flash on TPU — it wins every measured regime, README
    # long-context table — dense elsewhere) | dense | blockwise
    # (chunked K/V, bounded memory) | flash (Pallas TPU kernel: fused
    # online softmax, scores stay in VMEM; dense fallback off-TPU) |
    # ring (sequence-parallel K/V rotation over the mesh 'seq' axis) |
    # ulysses (sequence-parallel via two all-to-alls, heads resharded).
    # Default 'auto': defaults should encode the measured policy — the
    # flash kernel is fastest in every measured regime on TPU and auto
    # degrades to dense semantics elsewhere. Pass --attention dense for
    # the cross-backend reference implementation.
    attention: str = "auto"
    # K/V chunk for attention="blockwise"; block_q/block_k for "flash".
    attention_block: int = 512
    # Local core inside the sequence-parallel attentions ("ring" and
    # "ulysses"): "auto" (flash kernel on TPU, the pure-JAX path
    # elsewhere), or force "flash"/"blockwise" (the escape hatch if
    # the kernel misbehaves on some shape).
    attention_core: str = "auto"
    # Mixture-of-Experts (ViT family): 0 experts = dense MLPs. Experts
    # are sharded over the mesh 'model' axis (expert parallelism).
    moe_experts: int = 0
    moe_every: int = 2                # sparse MLP in every Nth block
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01      # load-balance loss weight
    # Expert-parallel token dispatch (tpunet/models/moe.py): "auto"
    # prefers the GShard capacity-buffer all_to_all over the expert
    # axis when shapes divide, falling back to the replicated-routing
    # psum lowering; "alltoall"/"replicated" force one (alltoall
    # raises where auto would fall back).
    moe_dispatch: str = "auto"
    # Pipeline parallelism (model name "vit_pp"): GPipe microbatches per
    # step; stages = the mesh 'pipe' axis size.
    pp_microbatches: int = 4
    # Pipeline schedule: "gpipe" (AD-emitted backward: all forwards,
    # then all backwards), "1f1b" (manual-VJP backward interleaving
    # fwd/bwd per microbatch — O(min(S, M)) live stage inputs instead
    # of O(M) stacked per-layer internals; same grads, parity-tested),
    # or "interleaved" (virtual pipeline stages: pp_virtual chunks per
    # device cut the bubble fraction ~pp_virtual-fold at a bounded
    # 1F1B-style memory cost — tpunet/parallel/pp.py interleaved).
    pp_schedule: str = "gpipe"
    # Chunks per device for pp_schedule="interleaved" (Megatron's v);
    # depth must divide into pipe * pp_virtual chunks and
    # pp_microbatches into whole pipe-axis groups.
    pp_virtual: int = 2
    # LM family (model name "lm"): vocab and the learned-position table
    # size (max trainable sequence length).
    vocab_size: int = 256
    max_seq_len: int = 1024
    # Vocab-sharded cross-entropy (tpunet/ops/vocab_ce.py): "auto"
    # shards the tied output projection + CE over the mesh 'model'
    # axis whenever it divides the vocab, so the replicated [B, T, V]
    # float32 logits never materialize; "sharded"/"full" force one.
    vocab_ce: str = "auto"
    # Rematerialize encoder blocks (jax.checkpoint): recompute block
    # activations in the backward pass instead of storing them — trades
    # ~1/3 more FLOPs for O(depth) less activation memory, the standard
    # lever for long-context training (ViT and LM families).
    remat: bool = False
    # Optional path to a torch state_dict (.pth) with ImageNet-pretrained
    # weights to convert (transfer learning is load-bearing for the ~96%
    # accuracy target — reference README.md:24-26).
    pretrained_path: Optional[str] = None
    # Route 3x3 depthwise convs through the Pallas kernel (tpunet/ops/).
    # Off by default: with properly synchronized timing the kernel is
    # ~2.8x SLOWER end-to-end than XLA's conv emitter on a v5e (it is
    # bit-exact and SPMD-partitioned — kept as the worked TPU-kernel
    # example and for experimentation). Only takes effect on a TPU
    # backend; parameter trees are identical either way, so the flag
    # can be flipped on existing checkpoints.
    use_pallas_depthwise: bool = False
    # MobileNetV2 HBM-traffic levers (tpunet/models/mobilenetv2.py;
    # the step is bandwidth-bound at ~5% MFU — see docs/performance.md
    # for the bytes/image budget these move):
    # fused_bn (default ON): conv -> BN -> ReLU6 epilogue as one
    # fusable region (single-pass batch stats, per-channel FMA +
    # clamp, bf16 residency) instead of nn.BatchNorm + separate clamp.
    # Measured -8.4% xla_bytes_accessed/image on the CPU-compiled
    # 224px step; same variable tree, so flippable on checkpoints.
    fused_bn: bool = True
    # fused_ir (default ON): route the inverted-residual expand /
    # project 1x1 convs through the fused Pallas kernel pair
    # (tpunet/ops/fused_ir.py): one-pass conv + BN-stats forward (the
    # training-BN statistics read of the conv output never hits HBM)
    # and an IO-aware backward that recomputes the elementwise
    # epilogue in VMEM. TPU-only and per-shape (the kernel engages
    # only where its dw-partial cost is below the saved reads — see
    # use_fused_ir_kernel); elsewhere the ops are numerically the
    # fused_bn path, eval mode is always the plain path (bit-identical
    # logits across the flag), and the variable tree is unchanged, so
    # it flips freely on checkpoints (--no-fused-ir, or
    # TPUNET_FUSED_IR_REF=1 without re-lowering configs). Requires
    # fused_bn (the fused epilogue math is what the kernel computes).
    fused_ir: bool = True
    # block_remat (default OFF): saved-residual policy for the
    # inverted-residual blocks — keep only conv outputs + (C,)-sized
    # BN stats as residuals and recompute the elementwise epilogues in
    # the backward replay (jax.checkpoint save_only_these_names).
    # Default off because the CPU-compiled module measures MORE bytes
    # accessed with it on (the replay's recomputes don't all fuse);
    # the per-op byte attribution (bench.py bytes_per_image_breakdown)
    # is the tool for deciding it per backend — flip with
    # --block-remat and compare on real TPU.
    block_remat: bool = False


@dataclass(frozen=True)
class OptimConfig:
    """Optimizer config (reference :147-149: Adam 1e-4 + StepLR(10, 0.1))."""

    name: str = "adam"
    learning_rate: float = 1e-4
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    # LR schedule: "step" is the reference's StepLR (decay by `gamma`
    # every `step_size_epochs`); "cosine" decays to 0 over training;
    # "constant" holds the base rate. `warmup_epochs` (fractional ok)
    # prepends a linear warmup from 0 to any of them.
    schedule: str = "step"
    step_size_epochs: int = 10
    gamma: float = 0.1
    warmup_epochs: float = 0.0
    label_smoothing: float = 0.0
    # Global-gradient-norm clipping (torch clip_grad_norm_ idiom);
    # 0 = off (the reference does not clip).
    clip_norm: float = 0.0
    # Parameter EMA decay (e.g. 0.999); 0 = off. When on, evaluation
    # and the best-checkpoint use the EMA weights.
    ema_decay: float = 0.0
    # Gradient accumulation: split each global batch into this many
    # microbatches inside the jitted step (lax.scan), average the
    # microbatch gradients, apply ONE optimizer update — 1/N the
    # activation memory, the lever for reference-scale batches on
    # small-HBM chips. Gradient math matches the full batch exactly
    # (mean of equal-sized means) for the LM path; image models differ
    # benignly: BN stats update per microbatch and each microbatch
    # draws fresh augmentation/dropout RNG.
    grad_accum: int = 1


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh config. The reference's only strategy is data parallelism
    (DDP at :142-145); we build a 4-D ('data', 'seq', 'pipe', 'model')
    mesh so sequence parallelism (ring attention over 'seq'), pipeline
    parallelism (GPipe over 'pipe') and tensor/expert-parallel sharding
    (over 'model') layer on without restructuring (SURVEY.md 2b).
    """

    data: int = -1                    # -1 -> all remaining devices
    seq: int = 1                      # sequence/context-parallel axis
    pipe: int = 1                     # pipeline-parallel axis (GPipe)
    model: int = 1                    # tensor-parallel axis
    # ZeRO-1: shard Adam moments over 'data' (params stay replicated,
    # exactly the reference's layout); GSPMD gathers as needed.
    zero1: bool = False
    # FSDP / ZeRO-3: shard params AND Adam moments over 'data' (largest
    # divisible dim per leaf) — 1/N resident param+optimizer memory; the
    # train step gathers params to their compute layout once at its
    # start and Adam updates the 1/N moment shards. Subsumes zero1.
    fsdp: bool = False

    def shape(self, n_devices: int) -> Tuple[int, int, int, int]:
        seq = max(1, self.seq)
        pipe = max(1, self.pipe)
        model = max(1, self.model)
        data = (self.data if self.data > 0
                else max(1, n_devices // (seq * pipe * model)))
        return (data, seq, pipe, model)


@dataclass(frozen=True)
class ExportConfig:
    """Live telemetry export (tpunet/obs/export/): push finished obs
    records to off-host endpoints through a bounded queue drained by a
    background thread — a dead endpoint can never stall a step; full
    queues drop and count (``export_*_dropped``). Coordinator-only,
    like the metrics.jsonl writes."""

    statsd: str = ""                  # "HOST:PORT" UDP statsd endpoint
    statsd_prefix: str = "tpunet"
    http: str = ""                    # line-JSON POST URL
    # Alert webhook (tpunet/obs/export/webhook.py): POST one templated
    # JSON payload per obs_alert / obs_crash / obs_regression record
    # (--obs-webhook URL). Retries with backoff; exhausted pages land
    # in the dead-letter list and the webhook_dead_letter counter.
    webhook: str = ""
    webhook_max_retries: int = 3
    webhook_backoff_s: float = 0.25
    # Bounded export queue: put_nowait from the step path; overflow
    # drops (counted) rather than blocking.
    queue_size: int = 1024
    # close() flush budget and the per-request HTTP socket timeout.
    flush_timeout_s: float = 5.0
    http_timeout_s: float = 1.0


@dataclass(frozen=True)
class ObsConfig:
    """Step-level observability (tpunet/obs/): per-step timing
    histograms, throughput/MFU and input-stall accounting, epoch-
    boundary device-memory gauges and multi-host heartbeat, all
    emitted as ``obs_epoch`` records into ``metrics.jsonl``.

    The default path is deliberately sync-free: every number is a
    host-side ``perf_counter`` lap or an epoch-boundary runtime query,
    so enabling it adds no device round-trips to the step loop."""

    enabled: bool = True
    # Emit an ``obs_step`` record every N steps (0 = per-epoch records
    # only). Host-side values only — no device sync either way.
    step_records_every: int = 0
    # Windowed profiling: capture a jax profiler trace for exactly
    # [profile_start_step, profile_start_step + profile_num_steps).
    # num_steps == 0 traces from start_step to the end of the run
    # (with both at 0 and --profile-dir set: the old whole-run trace);
    # either knob without --profile-dir writes under
    # <checkpoint-dir>/profile.
    profile_start_step: int = 0
    profile_num_steps: int = 0
    # Histogram memory bound: windows beyond this many observations
    # switch from exact percentiles to seeded reservoir sampling
    # (count/mean stay exact; the summary carries ``approx: 1``).
    histogram_max_samples: int = 65536
    # --obs-hbm-attrib: once, at the first step, AOT-compile the train
    # step and decompose its cost-analysis HBM bytes by op category
    # into the hbm_bytes_per_image_* gauge family
    # (tpunet/obs/hlo_bytes.py). Off by default: the extra lowering is
    # one redundant compile (cheap under the persistent cache, not
    # free).
    hbm_attrib: bool = False
    # -- run-health watchdog (tpunet/obs/health.py) -----------------
    # A step slower than stall_factor x the rolling median (and at
    # least stall_min_s) emits a step_stall obs_alert. 0 disables.
    stall_factor: float = 10.0
    stall_min_s: float = 1.0
    # A host-available loss above loss_spike_factor x its warmed-up
    # EMA emits a loss_spike alert (non-finite always alerts). 0
    # disables spike detection.
    loss_spike_factor: float = 5.0
    # No heartbeat for this long emits stale_heartbeat; 0 (default)
    # disables — epoch length varies too much for a universal budget.
    heartbeat_timeout_s: float = 0.0
    # Same-reason alerts within this many steps are suppressed
    # (counted in obs_alerts_suppressed) so a stall pages once.
    alert_cooldown_steps: int = 50
    # Fatal alerts raise RunUnhealthyError instead of just recording:
    # the --halt-on-unhealthy knob, for runs nobody is watching.
    halt_on_unhealthy: bool = False
    # Run identity (docs/metrics_schema.md "Run identity"): every
    # emitted record is stamped run_id/process_index/host so a fleet
    # aggregator can route streams. Empty = generate (and persist
    # under <checkpoint-dir>/run_id; --resume reuses it, so a
    # preemption restore continues the same stream).
    run_id: str = ""
    # Operator GaugePredicate alert rules over exported gauges,
    # evaluated each epoch against registry.snapshot(): "NAME > N",
    # "NAME < N", or "NAME + N/s" (growth rate). Fired rules emit
    # gauge_predicate obs_alerts (--obs-rule, repeatable).
    gauge_rules: Tuple[str, ...] = ()
    # Proactive checkpoint-and-evict (--evict-on-straggler,
    # docs/elasticity.md): a straggler-shaped watchdog alert on THIS
    # replica (step_stall / thread_stalled) triggers the agreed stop
    # with an evict marker — the pod checkpoints now and re-meshes
    # without the slow host instead of letting it stall every step.
    # Off by default; meaningful under the elastic agent.
    evict_on_straggler: bool = False
    # -- flight recorder (tpunet/obs/flightrec/) --------------------
    # Always-on black box: a crash-durable mmap ring of recent
    # structured events, faulthandler + native SIGSEGV/SIGABRT/SIGBUS
    # hooks, the host-thread registry, and a post-mortem watcher that
    # materializes <checkpoint-dir>/flightrec/crash_report.json when
    # the process dies uncleanly. Near-zero cost (~1-2 us per event,
    # no syscalls on the step path); --no-flightrec disables.
    flightrec: bool = True
    # Event-ring capacity (slots; the file is ~120 bytes per slot).
    flightrec_events: int = 1024
    export: ExportConfig = field(default_factory=ExportConfig)


@dataclass(frozen=True)
class ServeConfig:
    """Production inference server (tpunet/serve/): a fixed pool of KV
    slots decoded together by one jitted masked step (continuous
    batching — requests join mid-flight, finished ones free their slot,
    no recompilation), a bounded admission queue with backpressure, and
    a stdlib HTTP frontend. The Gradio app (tpunet/infer/app.py) stays
    the reference-parity demo; this is the heavy-traffic path."""

    host: str = "127.0.0.1"
    port: int = 8000
    # KV-slot pool size = max in-flight decodes = the jitted step's
    # batch dimension. Compiled once; sizing it is the HBM/latency
    # trade (docs/serving.md capacity guidance).
    slots: int = 8
    # Bounded admission: requests beyond this many waiting are REJECTED
    # (429 queue-full) instead of growing latency unboundedly.
    queue_max: int = 64
    # Prefill programs are compiled per padded prompt-length bucket —
    # the compile count is len(buckets), not one per prompt length.
    # Prompts longer than the largest bucket are rejected.
    prefill_buckets: Tuple[int, ...] = (32, 128, 512)
    # Paged KV cache (default ON; --no-paged-kv restores the dense
    # [slots, max_seq_len] pool): per layer, K/V live in a SHARED pool
    # of fixed-size pages addressed through per-slot page tables, so a
    # slot pins HBM proportional to its prompt+generated length — the
    # concurrent-slot multiplier at fixed HBM (docs/serving.md "Paged
    # KV cache & device-side sampling").
    paged_kv: bool = True
    # Usable data pages in the pool (0 = auto: slots *
    # ceil(max_seq_len / kv_page_tokens), i.e. dense-equivalent
    # capacity). Size it DOWN to oversubscribe slots against typical
    # request lengths; exhaustion defers admissions and, when nothing
    # can advance, preempts the youngest slot back to the queue with
    # its progress kept.
    kv_pages: int = 0
    # Tokens per KV page: the allocation granule. Smaller pages track
    # request length tighter (less tail waste) at more gather/table
    # overhead per step.
    kv_page_tokens: int = 16
    # KV page payload dtype: "auto" stores at the model compute dtype;
    # "bf16" halves float32 payloads; "int8" quantizes each written
    # token row against its own absmax (float32 scale stored with the
    # page, dequantized on gather; eval-parity-gated in
    # tests/test_serve_paged.py). Requires paged_kv.
    kv_dtype: str = "auto"
    # Device-side batched sampling (default ON; --no-device-sampling
    # restores the host loop): temperature/top-k/top-p and the
    # categorical draw run as one [slots]-wide jitted step fused onto
    # decode (per-slot PRNG keys folded per step) — only sampled
    # tokens cross the host boundary. Greedy output is token-identical
    # either way (parity-tested).
    device_sampling: bool = True
    # Prefix KV cache (default ON with paged_kv; --no-prefix-cache
    # disables): finished prefill pages stay in the pool as immutable,
    # content-addressed, refcounted objects keyed by token-prefix
    # digest at page granularity. Admission pins the longest cached
    # page-aligned prefix into the new slot's table and re-prefills
    # only the suffix (COW at the divergence page); LRU-evicted under
    # pool pressure — docs/serving.md "Prefix KV cache".
    prefix_cache: bool = True
    # Pool pages the prefix cache may hold (pinned + idle); 0 = auto
    # (half the usable pool). Bounding it below the pool keeps paying
    # slots from ever being starved by cached pages.
    prefix_cache_pages: int = 0
    # Shared-filesystem prefix spill/warm-start (--prefix-store DIR):
    # freshly-cached pages publish to DIR (content-digest tmp+rename,
    # flock first-writer-wins — the AOT store's commit discipline via
    # tpunet/utils/fsatomic.py), and a respawned or scaled-up replica
    # adopts the fleet's prefix set at boot so its first shared-prefix
    # request prefills only the suffix. Entries are scoped by model
    # config + kv levers + runtime, so a lever change is a clean miss.
    # Empty = per-replica cache only.
    prefix_store: str = ""
    # Per-request caps: default/max new tokens, and a wall-clock
    # deadline after which a request is cancelled and its slot freed
    # (0 = no deadline).
    default_max_new_tokens: int = 128
    max_new_tokens_cap: int = 1024
    default_deadline_s: float = 0.0
    # Classifier micro-batching: hold a /v1/classify request at most
    # this long to coalesce a batch, up to classify_batch_max images
    # per jitted batched forward.
    classify_batch_max: int = 8
    classify_window_ms: float = 2.0
    # Emit an ``obs_serve`` record (SLO counters/gauges/histograms)
    # every this many seconds; 0 disables periodic emission (records
    # still flush once on drain).
    emit_every_s: float = 10.0
    # Graceful-drain budget on SIGTERM: stop admitting, finish
    # in-flight work for up to this long, then cancel survivors.
    drain_timeout_s: float = 30.0
    # Replica identity on obs_serve records (fleet SLO rollups route
    # by it). Empty = "serve-<host>-<pid>".
    run_id: str = ""
    # AOT warm-start (--aot-cache DIR, tpunet/utils/cache.py
    # AotProgramStore): serialize the fully-compiled decode +
    # bucketed-prefill executables under DIR at first boot and
    # deserialize them on every later boot — no tracing, no lowering,
    # no XLA — so a respawned replica serves its first token in
    # seconds instead of recompiling (the router tier's autoscaling
    # depends on it; docs/serving.md "AOT warm-start"). Empty = off
    # (the persistent compilation cache still applies). Single-device
    # replicas only; ignored with --mesh-model > 1.
    aot_cache: str = ""
    # Serve-tier fault injection (--chaos, tpunet/serve/chaos.py):
    # deterministic SIGKILL/stall/probe-drop/slow-stream faults
    # addressed by generated-token count or prefill ordinal —
    # docs/serving.md "Mid-stream failover & serve-tier chaos". Empty
    # = no injector installed.
    chaos: str = ""
    # Standalone-serve request tracing (--trace-sample, docs/serving.md
    # "Request tracing"): head-sample this fraction of requests that
    # arrive WITHOUT trace headers, minting a trace_id locally. Under
    # a router the router decides (its headers win); a client-supplied
    # ``X-Trace-Id`` is always sampled. 0 = only header-carried traces.
    trace_sample: float = 0.0
    # Speculative decoding (--spec-decode, docs/serving.md
    # "Speculative decoding"): a small drafter model proposes spec_k
    # tokens per active slot against its OWN paged KV pool, then the
    # main model verifies every slot's drafts in ONE [slots, K+1]-wide
    # jitted forward over the existing pool — up to K+1 verified
    # tokens per slot per verify. Every emitted token comes from the
    # VERIFY distribution, so greedy output is bitwise-identical to
    # spec-off and sampled output stays deterministic per (seed, step)
    # (failover/replay safe). Rejection rewinds the slot's page-table
    # cursor to the last accepted position and recycles the tail
    # pages. Requires paged_kv AND device_sampling.
    spec_decode: bool = False
    # Draft tokens proposed per verify cycle (the K in draft-then-
    # verify). Higher K amortizes the verify gather over more tokens
    # but wastes drafter work when acceptance is low — docs/serving.md
    # "Speculative decoding" has the tuning math.
    spec_k: int = 4
    # Drafter width multiplier on the serving model's vit_hidden
    # (rounded to stay divisible by vit_heads). 1.0 shares the main
    # model's parameters (self-speculation — useful for parity tests,
    # never a throughput win); < 1.0 builds a second, narrower model
    # instance whose parameters come from --spec-draft-checkpoint or
    # a deterministic init.
    spec_draft_width_mult: float = 0.5
    # Drafter parameters (.npz from tpunet/serve/spec.py
    # ``save_drafter_params``; empty = deterministic random init,
    # which accepts ~nothing — fit or distill a drafter against real
    # traffic, e.g. ``spec.fit_drafter`` as bench_serve.py --spec
    # does).
    spec_draft_checkpoint: str = ""


@dataclass(frozen=True)
class RouterConfig:
    """Routing + autoscaling front tier (tpunet/router/,
    docs/serving.md "Routing & autoscaling"): a stdlib-threaded HTTP
    proxy that spreads /v1/generate + /v1/classify over N serve
    replicas (least-loaded with session/prefix affinity), evicts and
    respawns unhealthy replicas, and emits hysteresis scale-up/down
    decisions as ``obs_router`` records."""

    host: str = "127.0.0.1"
    port: int = 8100
    # Health/load probe cadence against each replica's /healthz +
    # /metrics; a probe slower than probe_timeout_s counts as a
    # failure, and unhealthy_after consecutive failures evict.
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    unhealthy_after: int = 3
    # Session/prefix affinity: requests with the same "session" field
    # (or the same first affinity_prefix prompt tokens/bytes) hash to
    # a stable preferred replica so shared-prompt traffic lands on
    # warm KV — unless the preferred replica's load score exceeds the
    # least-loaded replica's by more than affinity_slack (fraction of
    # its pool), in which case least-loaded wins.
    affinity_prefix: int = 16
    affinity_slack: float = 0.5
    # Re-route budget: a request that hits a dead/draining replica is
    # retried against another replica up to route_retries times (only
    # before any response byte reached the client).
    route_retries: int = 2
    # Per-proxied-request socket timeout toward a replica.
    request_timeout_s: float = 600.0
    # obs_router window record cadence (0 = final record only).
    emit_every_s: float = 10.0
    # Autoscale hysteresis over fleet queue depth per slot (and TTFT
    # SLO burn when ttft_slo_ms > 0): the condition must hold for
    # scale_window_probes consecutive probe rounds to fire, and after
    # any action the policy holds for scale_cooldown_s.
    scale_up_queue_per_slot: float = 1.0
    scale_down_queue_per_slot: float = 0.1
    scale_window_probes: int = 5
    scale_cooldown_s: float = 60.0
    min_replicas: int = 1
    max_replicas: int = 8
    # TTFT SLO (ms): fleet TTFT p99 above it counts as SLO burn > 1
    # and arms scale-up like queue pressure; 0 disables the term.
    ttft_slo_ms: float = 0.0
    # Drain-then-restart budget: SIGTERM -> graceful drain for up to
    # this long -> SIGKILL; in-flight streams finish inside it.
    drain_grace_s: float = 30.0
    # Boot grace: probe failures while a replica is STARTING (loading
    # weights, warming/deserializing programs) don't count toward
    # eviction until this much time has passed since its (re)spawn.
    boot_timeout_s: float = 120.0
    # Backoff before respawning an evicted/dead replica child.
    respawn_backoff_s: float = 1.0
    # Mid-stream failover (--failover / --no-failover, docs/serving.md
    # "Mid-stream failover & serve-tier chaos"): the frontend journals
    # every streamed /v1/generate request (prompt, sampling params,
    # relayed tokens) and, when the serving replica dies mid-stream,
    # re-submits to a survivor with ``resume_tokens`` — the client's
    # ndjson stream continues with no error frame (greedy:
    # token-identical; sampled: deterministic per (seed, step)).
    failover: bool = True
    # Per-request journal bound: a stream that has relayed more than
    # this many tokens is no longer failover-protected (on replica
    # death it gets the honest error frame — the documented
    # degradation mode). Bounds router memory per in-flight stream.
    failover_journal_tokens: int = 4096
    # Resume attempts per request after a mid-stream replica death
    # (each attempt picks a different surviving replica).
    failover_retries: int = 2
    # Serve-tier fault injection forwarded to spawned replicas
    # (--chaos, tpunet/serve/chaos.py grammar plus a ``:replica=I``
    # scope key naming the child index; unscoped events reach every
    # child). Empty = no injection.
    chaos: str = ""
    # End-to-end request tracing (--trace-sample, docs/serving.md
    # "Request tracing"): the frontend mints a trace_id per request
    # and head-samples this fraction of them (deterministic on the
    # id); sampled requests carry ``X-Trace-Id`` to every replica hop
    # — including failover re-submits — and every layer records trace
    # breadcrumbs + an ``obs_trace`` record. A client-supplied
    # ``X-Trace-Id`` is always sampled (explicit opt-in).
    trace_sample: float = 0.01
    # Tail capture for the requests sampling missed
    # (--no-trace-all-on-error disables): an UNsampled request that
    # hits a mid-stream failover or errors still gets a router-hop
    # ``obs_trace`` record — replica-side phases are absent (the
    # replicas never saw trace context), but the seam and outcome are
    # on the books.
    trace_all_on_error: bool = True
    # Synthetic canary probing (--probe-every-s, tpunet/router/
    # prober.py, docs/serving.md "SLOs & probing"): every this many
    # seconds the router issues a pinned greedy known-answer request
    # through its OWN public endpoint — the full proxy path — and
    # judges availability, TTFT/e2e latency, and bitwise golden-output
    # correctness from the client's side, feeding the SLO engine's SLI
    # streams. Each probe carries a minted always-sampled X-Trace-Id,
    # so a failed or slow probe points at a replayable trace. 0 = off.
    probe_every_s: float = 0.0
    # SLO policy file (--slo-policy, docs/slos.json format:
    # objectives + compliance windows + multi-window burn-rate alert
    # rules; full-line // comments allowed). Arming it (or the
    # prober) starts the tpunet/obs/slo.py engine: obs_slo records,
    # slo_* gauges, and edge-latched fast-burn pages / slow-burn
    # tickets through the obs_alert webhook path. Empty = built-in
    # default policy when the prober is armed, otherwise off.
    slo_policy: str = ""
    # Router identity on obs_router records (empty =
    # "router-<host>-<pid>").
    run_id: str = ""


@dataclass(frozen=True)
class CheckpointConfig:
    directory: str = "checkpoints"
    save_best: bool = True            # reference best-by-test-acc (:238-240)
    save_last: bool = True            # upgrade: full state for resume
    resume: bool = False
    keep: int = 2


@dataclass(frozen=True)
class TrainConfig:
    """Top-level config."""

    epochs: int = 20                  # reference EPOCHS (:158)
    seed: int = 42                    # reference torch.manual_seed(42) (:58)
    # Fault injection (--chaos, tpunet/elastic/chaos.py): deterministic
    # SIGKILL/SIGTERM/slow-host/checkpoint-IO faults addressed by step
    # or save ordinal — docs/elasticity.md "Chaos spec grammar". Empty
    # = no injector installed.
    chaos: str = ""
    # Preemption grace window (--preempt-grace-s): seconds the platform
    # grants after SIGTERM. The guard budgets the checkpoint-durability
    # wait inside it and a second SIGTERM escalates to an immediate
    # checkpoint-abandon exit. 0 = unknown/unbounded (legacy behavior).
    preempt_grace_s: float = 0.0
    # Evaluate a saved checkpoint (best params if present, else the
    # last full state) and exit — no training.
    eval_only: bool = False
    log_every_steps: int = 0          # 0 -> per-epoch only, like the reference
    profile_dir: str = ""             # non-empty -> jax.profiler traces
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Presets: the reference's three launch modes (SURVEY.md section 0).
# ---------------------------------------------------------------------------

def preset(name: str) -> TrainConfig:
    """Return the config for a named launch mode.

    - ``serial``      — reference cifar10_serial_mobilenet_224.py: batch 64.
    - ``single``      — reference cifar10_128batch.py: batch 128, one chip.
    - ``distributed`` — reference cifar10_mpi_mobilenet_224.py: 128 per
      device (global batch = 128 * n_devices is resolved at runtime).
    """
    base = TrainConfig()
    if name == "serial":
        return base.replace(data=dataclasses.replace(base.data, batch_size=64))
    if name == "single":
        return base
    if name == "distributed":
        return base  # global batch scaled by the caller from mesh size
    raise ValueError(f"unknown preset {name!r}; expected serial|single|distributed")


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="tpunet trainer")
    p.add_argument("--preset", default="single",
                   choices=["serial", "single", "distributed"])
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--batch-size", type=int, default=None,
                   help="global batch size")
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--optimizer", default=None,
                   choices=["adam", "adamw", "sgd"],
                   help="adam is the reference stack (:148); adamw "
                        "activates --weight-decay; sgd uses momentum 0.9")
    p.add_argument("--weight-decay", type=float, default=None)
    p.add_argument("--label-smoothing", type=float, default=None)
    p.add_argument("--eval-batch-size", type=int, default=None,
                   help="global eval batch (default: --batch-size)")
    p.add_argument("--lr-schedule", default=None,
                   choices=["step", "cosine", "constant"],
                   help="step = the reference's StepLR(10, 0.1); cosine "
                        "decays to 0 over training")
    p.add_argument("--warmup-epochs", type=float, default=None,
                   help="linear LR warmup over this many (fractional) "
                        "epochs, before any schedule")
    p.add_argument("--clip-norm", type=float, default=None,
                   help="global gradient-norm clip; 0 = off")
    p.add_argument("--ema-decay", type=float, default=None,
                   help="parameter EMA decay (e.g. 0.999); eval and the "
                        "best checkpoint use the EMA weights; 0 = off")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--data-dir", default=None)
    p.add_argument("--dataset", default=None,
                   choices=["cifar10", "synthetic", "synthetic_lm",
                            "text_lm"])
    p.add_argument("--text-file", default=None,
                   help="byte-level corpus file for --dataset text_lm")
    p.add_argument("--pack-docs", action="store_true",
                   help="text_lm: pack newline-delimited documents into "
                        "seq_len rows with segment-masked attention and "
                        "loss (no cross-document attention/prediction)")
    p.add_argument("--no-download", action="store_true",
                   help="never fetch CIFAR-10/pretrained weights over "
                        "the network; fail with drop-in instructions "
                        "instead (reference auto-downloads, :97)")
    p.add_argument("--pretrained", default=None, metavar="PATH|auto",
                   help="torch MobileNetV2 state_dict to convert; 'auto' "
                        "fetches torchvision's ImageNet checkpoint into "
                        "~/.cache/tpunet (the reference's "
                        "pretrained=True, :137)")
    p.add_argument("--model", default=None,
                   choices=["mobilenet_v2", "vit", "vit_tiny", "vit_small",
                            "vit_base", "vit_pp", "lm", "lm_pp"])
    p.add_argument("--seq-len", type=int, default=None,
                   help="sequence length for token datasets (model lm)")
    p.add_argument("--max-seq-len", type=int, default=None,
                   help="LM position-table size (defaults to at least "
                        "--seq-len)")
    p.add_argument("--vocab-size", type=int, default=None,
                   help="vocab for the lm model + synthetic_lm data")
    p.add_argument("--pp-microbatches", type=int, default=None,
                   help="GPipe microbatches per step (vit_pp)")
    p.add_argument("--pp-schedule", default=None,
                   choices=["gpipe", "1f1b", "interleaved"],
                   help="pipeline schedule: gpipe (AD backward), 1f1b "
                        "(manual-VJP backward, bounded activation "
                        "memory), or interleaved (virtual stages: "
                        "--pp-virtual chunks per device, ~v-fold "
                        "smaller bubble at 1F1B-style memory)")
    p.add_argument("--pp-virtual", type=int, default=None,
                   help="chunks per device for --pp-schedule "
                        "interleaved (depth must divide pipe x v)")
    p.add_argument("--attention", default=None,
                   choices=["auto", "dense", "blockwise", "flash",
                            "ring", "ulysses"],
                   help="core attention impl for ViT/LM models; 'flash' "
                        "is the fused Pallas TPU kernel (dense fallback "
                        "off-TPU); 'ring' and 'ulysses' are "
                        "sequence-parallel over the mesh 'seq' axis")
    p.add_argument("--attention-block", type=int, default=None,
                   help="K/V chunk size for --attention blockwise; "
                        "block_q/block_k for --attention flash")
    p.add_argument("--attention-core", default=None,
                   choices=["auto", "flash", "blockwise"],
                   help="local core inside --attention ring/ulysses: "
                        "auto = flash kernel on TPU, the pure-JAX path "
                        "elsewhere; force blockwise as the escape hatch")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize encoder blocks (less activation "
                        "memory, ~1/3 more backward FLOPs)")
    p.add_argument("--zero1", action="store_true",
                   help="shard optimizer moments over the 'data' axis "
                        "(ZeRO-1); params stay replicated")
    p.add_argument("--fsdp", action="store_true",
                   help="fully-sharded data parallelism (ZeRO-3): shard "
                        "params and optimizer moments over 'data'; "
                        "weights are all-gathered just-in-time")
    p.add_argument("--grad-accum", type=int, default=None,
                   help="microbatches accumulated per optimizer step "
                        "(the global batch is split in time; 1/N the "
                        "activation memory; full-batch gradient math "
                        "except per-microbatch BN stats/augment RNG)")
    p.add_argument("--moe-experts", type=int, default=None,
                   help="experts per MoE block (vit/lm/lm_pp); "
                        "0 = dense MLPs")
    p.add_argument("--moe-top-k", type=int, default=None)
    p.add_argument("--moe-every", type=int, default=None)
    p.add_argument("--moe-capacity-factor", type=float, default=None)
    p.add_argument("--moe-aux-weight", type=float, default=None)
    p.add_argument("--moe-dispatch", default=None,
                   choices=["auto", "alltoall", "replicated"],
                   help="expert-parallel token dispatch: GShard "
                        "all_to_all capacity buffers vs replicated "
                        "routing + psum (auto prefers alltoall when "
                        "shapes divide)")
    p.add_argument("--vocab-ce", default=None,
                   choices=["auto", "sharded", "full"],
                   help="LM loss lowering: vocab-sharded logits + CE "
                        "over the mesh 'model' axis (full [B,T,V] "
                        "logits never materialize) vs the full-logits "
                        "path (auto shards when the axis divides the "
                        "vocab)")
    p.add_argument("--dropout-rate", type=float, default=None,
                   help="dropout rate for every model family (default "
                        "0.2, torchvision MobileNetV2's classifier "
                        "dropout; LMs inherit it unless overridden)")
    p.add_argument("--vit-patch", type=int, default=None)
    p.add_argument("--vit-hidden", type=int, default=None)
    p.add_argument("--vit-depth", type=int, default=None)
    p.add_argument("--vit-heads", type=int, default=None)
    p.add_argument("--vit-mlp-ratio", type=float, default=None,
                   help="ViT MLP hidden width as a multiple of the "
                        "embedding width (default 4.0)")
    p.add_argument("--param-dtype", default=None,
                   choices=["float32", "bfloat16"],
                   help="parameter/optimizer-state storage dtype "
                        "(default float32 master params; --dtype "
                        "stays the compute dtype)")
    p.add_argument("--width-mult", type=float, default=None)
    p.add_argument("--synthetic-size", type=int, default=None,
                   help="train-set size when --dataset synthetic")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--eval-only", action="store_true",
                   help="evaluate the saved checkpoint (best params if "
                        "present, else the last full state) and exit")
    p.add_argument("--mesh-data", type=int, default=None)
    p.add_argument("--mesh-seq", type=int, default=None,
                   help="sequence-parallel axis size (ring/ulysses "
                        "attention)")
    p.add_argument("--mesh-pipe", type=int, default=None,
                   help="pipeline-parallel axis size (vit_pp model)")
    p.add_argument("--mesh-model", type=int, default=None,
                   help="tensor-parallel axis size")
    p.add_argument("--dtype", default=None, choices=["bfloat16", "float32"])
    p.add_argument("--profile-dir", default=None,
                   help="jax profiler trace output directory; combine "
                        "with --profile-start-step/--profile-num-steps "
                        "to capture a step window instead of the run")
    p.add_argument("--profile-start-step", type=int, default=None,
                   help="global step at which the profiler trace "
                        "starts (alone: traces to the end of the run, "
                        "under <checkpoint-dir>/profile unless "
                        "--profile-dir is set)")
    p.add_argument("--profile-num-steps", type=int, default=None,
                   help="steps to trace from --profile-start-step "
                        "(0 = until the end of the run); without "
                        "--profile-dir the trace lands under "
                        "<checkpoint-dir>/profile")
    p.add_argument("--no-obs", action="store_true",
                   help="disable the observability subsystem (no "
                        "obs_* records, spans, or step timing)")
    p.add_argument("--obs-step-every", type=int, default=None,
                   help="emit a per-step obs_step record every N "
                        "steps (0 = per-epoch obs records only)")
    p.add_argument("--flightrec", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="black-box flight recorder (default on): "
                        "crash-durable event ring + crash handlers "
                        "that leave <checkpoint-dir>/flightrec/"
                        "crash_report.json (ring tail, per-thread "
                        "stacks, native batcher journal) when the "
                        "process dies; render with "
                        "scripts/obs_crash_report.py")
    p.add_argument("--flightrec-events", type=int, default=None,
                   help="flight-recorder event-ring capacity (slots)")
    p.add_argument("--obs-hbm-attrib", action="store_true",
                   help="decompose the compiled train step's HBM "
                        "bytes by op category into the "
                        "hbm_bytes_per_image_* gauges once at the "
                        "first step (one extra AOT lowering)")
    p.add_argument("--statsd", default=None, metavar="HOST:PORT",
                   help="stream obs records as statsd/UDP gauges to "
                        "this endpoint (non-blocking: bounded queue + "
                        "background sender; drops are counted)")
    p.add_argument("--obs-http", default=None, metavar="URL",
                   help="POST obs records as line-JSON to this URL "
                        "(same non-blocking queue; pair with "
                        "'scripts/obs_dashboard.py --listen PORT')")
    p.add_argument("--obs-webhook", default=None, metavar="URL",
                   help="POST one templated JSON payload per alert "
                        "record (obs_alert/obs_crash/obs_regression) "
                        "to this URL — retried with backoff, "
                        "dead-lettered after webhook_max_retries "
                        "(wire format in docs/metrics_schema.md)")
    p.add_argument("--obs-queue-size", type=int, default=None,
                   help="bounded export queue depth (overflow drops "
                        "records and counts them, never blocks a step)")
    p.add_argument("--obs-hist-samples", type=int, default=None,
                   help="histogram reservoir bound "
                        "(histogram_max_samples): windows beyond this "
                        "many observations switch from exact "
                        "percentiles to seeded reservoir sampling")
    p.add_argument("--alert-cooldown-steps", type=int, default=None,
                   help="suppress same-reason obs_alerts within this "
                        "many steps (counted in obs_alerts_suppressed) "
                        "so a stall pages once")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic fault injection "
                        "(docs/elasticity.md): e.g. 'kill@step=5', "
                        "'kill@ckpt=2', 'sigterm@step=8:again=1', "
                        "'slow@step=10:delay=1:steps=3', "
                        "'ioerr@save=1:fails=2'; ';'-separated, "
                        "host=H scopes one process")
    p.add_argument("--preempt-grace-s", type=float, default=None,
                   help="SIGTERM grace window the platform grants: "
                        "the preemption save's durability wait is "
                        "bounded by what remains of it, and a second "
                        "SIGTERM escalates to immediate "
                        "checkpoint-abandon exit (0 = unbounded)")
    p.add_argument("--evict-on-straggler", action="store_true",
                   help="straggler-shaped watchdog alerts (step_stall"
                        "/thread_stalled) on this replica trigger "
                        "checkpoint-now-then-evict through the agreed "
                        "stop — the elastic agent re-meshes the pod "
                        "without the slow host (docs/elasticity.md)")
    p.add_argument("--halt-on-unhealthy", action="store_true",
                   help="abort the run (RunUnhealthyError) on a fatal "
                        "obs_alert: step stall, NaN/spiking loss, or "
                        "missing processes — after the alert record "
                        "is written")
    p.add_argument("--stall-factor", type=float, default=None,
                   help="step_stall alert threshold: a step slower "
                        "than FACTOR x the rolling median (and at "
                        "least --stall-min-s); 0 disables")
    p.add_argument("--stall-min-s", type=float, default=None,
                   help="absolute floor (seconds) a step must exceed "
                        "to count as stalled")
    p.add_argument("--loss-spike-factor", type=float, default=None,
                   help="loss_spike alert threshold: loss above "
                        "FACTOR x its EMA; 0 disables")
    p.add_argument("--heartbeat-timeout", type=float, default=None,
                   metavar="SECONDS",
                   help="stale_heartbeat alert when no epoch "
                        "heartbeat lands for this long (0 = off)")
    p.add_argument("--run-id", default=None,
                   help="explicit run identity stamped on every obs "
                        "record (default: generated and persisted "
                        "under <checkpoint-dir>/run_id; --resume "
                        "reuses it)")
    p.add_argument("--obs-rule", action="append", default=None,
                   metavar="RULE",
                   help="GaugePredicate alert rule over any registry "
                        "snapshot key, e.g. 'mfu < 0.3', "
                        "'step_time_s_p99 > 2', "
                        "'mem_peak_bytes_in_use + 1e6/s' (growth per "
                        "second); repeatable, checked each epoch")
    p.add_argument("--log-every-steps", type=int, default=None,
                   help="emit a step/loss/lr line every N steps (0 = "
                        "per-epoch only, like the reference)")
    p.add_argument("--no-native-loader", action="store_true",
                   help="force the pure-numpy host batch path")
    p.add_argument("--mixup", type=float, default=None, metavar="ALPHA",
                   help="mixup Beta(a,a) strength for image models; "
                        "0 = off")
    p.add_argument("--cutmix", type=float, default=None, metavar="ALPHA",
                   help="CutMix Beta(a,a) strength; with --mixup, each "
                        "step picks one at random")
    p.add_argument("--pallas-depthwise", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="route 3x3 depthwise convs through the Pallas "
                        "kernel (default off: slower than XLA's conv "
                        "emitter on v5e, kept for experimentation)")
    p.add_argument("--fused-bn", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="MobileNetV2: conv->BN->ReLU6 epilogue as one "
                        "fusable region (default on; --no-fused-bn "
                        "restores the nn.BatchNorm + separate clamp "
                        "path, same parameters)")
    p.add_argument("--fused-ir", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="MobileNetV2: fused 1x1-conv + BN-stats Pallas "
                        "kernel pair for the inverted-residual expand/"
                        "project convs (default on; TPU-only and "
                        "per-shape — elsewhere numerically identical "
                        "to --fused-bn; --no-fused-ir restores the "
                        "XLA path, same parameters)")
    p.add_argument("--block-remat", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="MobileNetV2: recompute inverted-residual "
                        "elementwise epilogues in backward, saving "
                        "only conv outputs + BN stats as residuals "
                        "(default off: measured as MORE bytes accessed "
                        "on the CPU backend; compare per backend via "
                        "bench.py's bytes_per_image_breakdown)")
    return p


def config_from_args(argv=None) -> TrainConfig:
    args = build_argparser().parse_args(argv)
    cfg = preset(args.preset)
    data, model, optim, mesh, ckpt = cfg.data, cfg.model, cfg.optim, cfg.mesh, cfg.checkpoint
    obs = cfg.obs
    if args.no_obs:
        obs = dataclasses.replace(obs, enabled=False)
    if args.obs_step_every is not None:
        obs = dataclasses.replace(obs, step_records_every=args.obs_step_every)
    if args.obs_hbm_attrib:
        obs = dataclasses.replace(obs, hbm_attrib=True)
    if args.flightrec is not None:
        obs = dataclasses.replace(obs, flightrec=args.flightrec)
    if args.flightrec_events is not None:
        obs = dataclasses.replace(obs,
                                  flightrec_events=args.flightrec_events)
    if args.profile_start_step is not None:
        obs = dataclasses.replace(obs,
                                  profile_start_step=args.profile_start_step)
    if args.profile_num_steps is not None:
        obs = dataclasses.replace(obs,
                                  profile_num_steps=args.profile_num_steps)
    export = obs.export
    if args.statsd is not None:
        export = dataclasses.replace(export, statsd=args.statsd)
    if args.obs_http is not None:
        export = dataclasses.replace(export, http=args.obs_http)
    if args.obs_webhook is not None:
        export = dataclasses.replace(export, webhook=args.obs_webhook)
    if args.obs_queue_size is not None:
        export = dataclasses.replace(export,
                                     queue_size=args.obs_queue_size)
    if export is not obs.export:
        obs = dataclasses.replace(obs, export=export)
    if args.halt_on_unhealthy:
        obs = dataclasses.replace(obs, halt_on_unhealthy=True)
    if args.evict_on_straggler:
        obs = dataclasses.replace(obs, evict_on_straggler=True)
    if args.run_id is not None:
        obs = dataclasses.replace(obs, run_id=args.run_id)
    if args.obs_rule:
        obs = dataclasses.replace(obs, gauge_rules=tuple(args.obs_rule))
    for obs_field, arg in (("stall_factor", args.stall_factor),
                           ("stall_min_s", args.stall_min_s),
                           ("loss_spike_factor", args.loss_spike_factor),
                           ("heartbeat_timeout_s",
                            args.heartbeat_timeout),
                           ("histogram_max_samples",
                            args.obs_hist_samples),
                           ("alert_cooldown_steps",
                            args.alert_cooldown_steps)):
        if arg is not None:
            obs = dataclasses.replace(obs, **{obs_field: arg})
    if args.batch_size is not None:
        data = dataclasses.replace(data, batch_size=args.batch_size)
    if args.image_size is not None:
        data = dataclasses.replace(data, image_size=args.image_size)
    if args.data_dir is not None:
        data = dataclasses.replace(data, data_dir=args.data_dir)
    if args.dataset is not None:
        data = dataclasses.replace(data, dataset=args.dataset)
    if args.no_native_loader:
        data = dataclasses.replace(data, native_loader=False)
    if args.no_download:
        data = dataclasses.replace(data, download=False)
    if args.text_file is not None:
        data = dataclasses.replace(data, text_path=args.text_file)
    if args.pack_docs:
        data = dataclasses.replace(data, pack_docs=True)
    if args.mixup is not None:
        data = dataclasses.replace(data, mixup_alpha=args.mixup)
    if args.cutmix is not None:
        data = dataclasses.replace(data, cutmix_alpha=args.cutmix)
    if args.seq_len is not None:
        data = dataclasses.replace(data, seq_len=args.seq_len)
    if args.max_seq_len is not None:
        model = dataclasses.replace(model, max_seq_len=args.max_seq_len)
    if data.seq_len > model.max_seq_len:
        # Long-context runs shouldn't require editing source: grow the
        # position table to cover the requested sequence length.
        model = dataclasses.replace(model, max_seq_len=data.seq_len)
    if args.vocab_size is not None:
        data = dataclasses.replace(data, vocab_size=args.vocab_size)
        model = dataclasses.replace(model, vocab_size=args.vocab_size)
    if args.synthetic_size is not None:
        data = dataclasses.replace(
            data, synthetic_train_size=args.synthetic_size,
            synthetic_test_size=max(1, args.synthetic_size // 4))
    if args.pretrained is not None:
        model = dataclasses.replace(model, pretrained_path=args.pretrained)
    if args.model is not None:
        model = dataclasses.replace(model, name=args.model)
    if args.attention is not None:
        model = dataclasses.replace(model, attention=args.attention)
    if args.attention_block is not None:
        model = dataclasses.replace(model, attention_block=args.attention_block)
    if args.attention_core is not None:
        model = dataclasses.replace(model, attention_core=args.attention_core)
    if args.remat:
        model = dataclasses.replace(model, remat=True)
    if args.zero1:
        mesh = dataclasses.replace(mesh, zero1=True)
    if args.fsdp:
        mesh = dataclasses.replace(mesh, fsdp=True)
    if args.grad_accum is not None:
        optim = dataclasses.replace(optim, grad_accum=args.grad_accum)
    for name in ("vit_patch", "vit_hidden", "vit_depth", "vit_heads",
                 "vit_mlp_ratio", "param_dtype",
                 "moe_experts", "moe_top_k", "moe_every",
                 "moe_capacity_factor", "moe_aux_weight", "moe_dispatch",
                 "vocab_ce", "pp_microbatches", "pp_schedule",
                 "pp_virtual", "dropout_rate"):
        val = getattr(args, name)
        if val is not None:
            model = dataclasses.replace(model, **{name: val})
    if args.width_mult is not None:
        model = dataclasses.replace(model, width_mult=args.width_mult)
    if args.pallas_depthwise is not None:
        model = dataclasses.replace(model,
                                    use_pallas_depthwise=args.pallas_depthwise)
    if args.fused_bn is not None:
        model = dataclasses.replace(model, fused_bn=args.fused_bn)
    if args.fused_ir is not None:
        model = dataclasses.replace(model, fused_ir=args.fused_ir)
    if args.block_remat is not None:
        model = dataclasses.replace(model, block_remat=args.block_remat)
    if args.dtype is not None:
        model = dataclasses.replace(model, dtype=args.dtype)
    if args.lr is not None:
        optim = dataclasses.replace(optim, learning_rate=args.lr)
    if args.optimizer is not None:
        optim = dataclasses.replace(optim, name=args.optimizer)
    if args.weight_decay is not None:
        optim = dataclasses.replace(optim, weight_decay=args.weight_decay)
    if args.label_smoothing is not None:
        optim = dataclasses.replace(optim,
                                    label_smoothing=args.label_smoothing)
    if args.eval_batch_size is not None:
        data = dataclasses.replace(data,
                                   eval_batch_size=args.eval_batch_size)
    if args.lr_schedule is not None:
        optim = dataclasses.replace(optim, schedule=args.lr_schedule)
    if args.warmup_epochs is not None:
        optim = dataclasses.replace(optim, warmup_epochs=args.warmup_epochs)
    if args.clip_norm is not None:
        optim = dataclasses.replace(optim, clip_norm=args.clip_norm)
    if args.ema_decay is not None:
        optim = dataclasses.replace(optim, ema_decay=args.ema_decay)
    if args.mesh_data is not None:
        mesh = dataclasses.replace(mesh, data=args.mesh_data)
    if args.mesh_seq is not None:
        mesh = dataclasses.replace(mesh, seq=args.mesh_seq)
    if args.mesh_pipe is not None:
        mesh = dataclasses.replace(mesh, pipe=args.mesh_pipe)
    if args.mesh_model is not None:
        mesh = dataclasses.replace(mesh, model=args.mesh_model)
    if args.checkpoint_dir is not None:
        ckpt = dataclasses.replace(ckpt, directory=args.checkpoint_dir)
    if args.resume:
        ckpt = dataclasses.replace(ckpt, resume=True)
    cfg = cfg.replace(data=data, model=model, optim=optim, mesh=mesh,
                      checkpoint=ckpt, obs=obs)
    if args.epochs is not None:
        cfg = cfg.replace(epochs=args.epochs)
    if args.seed is not None:
        cfg = cfg.replace(seed=args.seed)
    if args.chaos is not None:
        cfg = cfg.replace(chaos=args.chaos)
    if args.preempt_grace_s is not None:
        cfg = cfg.replace(preempt_grace_s=args.preempt_grace_s)
    if args.profile_dir is not None:
        cfg = cfg.replace(profile_dir=args.profile_dir)
    if args.log_every_steps is not None:
        cfg = cfg.replace(log_every_steps=args.log_every_steps)
    if args.eval_only:
        cfg = cfg.replace(eval_only=True)
    return cfg
