from tpunet.data.cifar10 import get_dataset, load_cifar10, synthetic_cifar10  # noqa: F401
from tpunet.data.augment import make_train_augment, make_eval_preprocess  # noqa: F401
from tpunet.data.pipeline import (train_batches, eval_batches,  # noqa: F401
                                  steps_per_epoch, timed_batches)
