"""Fully on-device data augmentation (jit/vmap, MXU-friendly).

TPU-native replacement for the reference's torchvision CPU transform
stack (cifar10_mpi_mobilenet_224.py:72-89):

    train: Resize(224) -> RandomResizedCrop(224, scale=(0.7, 1.0)) ->
           RandomHorizontalFlip -> ColorJitter(0.3, 0.3, 0.3, 0.1) ->
           RandomRotation(15) -> ToTensor -> Normalize(ImageNet stats)
    test:  Resize(224) -> ToTensor -> Normalize

Design: the host ships raw 32x32 uint8 batches (3 KB/image instead of the
~588 KB/image a host-side 224px float pipeline would transfer), and the
whole augmentation runs inside the jitted train step:

  hflip -> rotate(+-15 deg, bilinear, edge fill, at 32x32 where the
  gather is tiny) -> fused random-resized-crop + resize-to-224 expressed
  as two separable per-image bilinear matrices (a (224,32) row matrix
  and column matrix), i.e. batched matmuls that map straight onto the
  MXU -> color jitter (elementwise) -> torchvision's rotate-last black
  BORDER geometry as a closed-form coverage mask at 224 (elementwise;
  no output-resolution gather) -> normalize.

Documented deviations from torchvision semantics (distribution-level
equivalent — quantified in tests/test_augment_stats.py against a PIL
reference): ColorJitter sub-ops apply in fixed order (brightness,
contrast, saturation, hue) rather than a random permutation;
RandomResizedCrop clamps the sampled box instead of torchvision's
10-attempt rejection loop; hflip runs first (commutes with the crop
distribution); CONTENT rotation still happens before the crop, at the
32px source (so it composes with the crop's anisotropic scaling as a
slight shear vs torchvision's post-resize rotation, and edge-fill can
smear frame borders into view) — but the rotation BORDER geometry is
torchvision's exactly: the black corners a rotate-last pipeline leaves
on the full output frame are applied as a closed-form coverage mask at
output resolution (round 1's zero-fill rotate-before-crop shed most of
that border mass — 0.5% dark-pixel mass vs ~2.5%, +0.03 channel-mean
shift, measured in test_augment_stats; a literal rotate-at-224 gather
measured ~11x slower end-to-end on the v5e). Crop-box sampling, jitter
strengths, rotation range, and normalization stats match the reference
exactly.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from tpunet.config import DataConfig

SRC = 32  # CIFAR-10 native resolution


# ---------------------------------------------------------------------------
# Bilinear resampling as separable matrices (MXU path)
# ---------------------------------------------------------------------------

def _hat_weights(s, src_size: int):
    """[..., out] continuous source coords -> [..., out, src] bilinear
    hat weights, coords clamped to the frame. The ONE home of the
    clamped-tap convention: after the clamp both adjacent taps exist
    and their weights sum to exactly (1-f) + f = 1, so no normalizing
    reduction is needed (it showed up at ~3% of the train step in the
    round-5 per-op profile, runs/bench-roofline/)."""
    s = jnp.clip(s, 0.0, src_size - 1.0)
    j = jnp.arange(src_size, dtype=jnp.float32)
    return jnp.maximum(0.0, 1.0 - jnp.abs(s[..., None] - j))


def _bilinear_matrix(start, size, out_size: int, src_size: int):
    """(out_size, src_size) bilinear sampling matrix for a 1-D crop+resize.

    Output index i samples continuous source coordinate
    ``start + (i + 0.5) * size / out_size - 0.5`` (half-pixel centers).
    ``start``/``size`` may be traced scalars — the matrix shape is static.
    """
    i = jnp.arange(out_size, dtype=jnp.float32)
    return _hat_weights(start + (i + 0.5) * size / out_size - 0.5,
                        src_size)


def resize_matrix_np(out_size: int, src_size: int) -> np.ndarray:
    """Static full-image resize matrix (eval path), as a numpy constant."""
    i = np.arange(out_size, dtype=np.float32)
    s = np.clip((i + 0.5) * src_size / out_size - 0.5, 0.0, src_size - 1.0)
    j = np.arange(src_size, dtype=np.float32)
    w = np.maximum(0.0, 1.0 - np.abs(s[:, None] - j[None, :]))
    return w / w.sum(axis=1, keepdims=True)


def _apply_separable(img, row_m, col_m):
    """img (H, W, C), row_m (Ho, H), col_m (Wo, W) -> (Ho, Wo, C)."""
    img = jnp.einsum("oh,hwc->owc", row_m, img)
    return jnp.einsum("pw,owc->opc", col_m, img)


# ---------------------------------------------------------------------------
# Rotation (gather at source resolution)
# ---------------------------------------------------------------------------

def _inverse_rot_coords(h: int, w: int, angle):
    """(sy, sx) source coordinates of each output pixel under the
    inverse rotation about the image center — the ONE copy of the
    center convention and rotation direction, shared by the content
    gather and the border mask so they can never misalign."""
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    yy, xx = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    cos, sin = jnp.cos(angle), jnp.sin(angle)
    sy = cos * (yy - cy) + sin * (xx - cx) + cy
    sx = -sin * (yy - cy) + cos * (xx - cx) + cx
    return sy, sx


def _rotate_bilinear(img, angle, fill: str = "zero"):
    """Rotate (H, W, C) float image by ``angle`` radians.

    ``fill="zero"`` zeroes out-of-frame taps (PIL semantics);
    ``fill="edge"`` clamps to the border pixel — used by the train
    pipeline, whose torchvision-matching black borders are applied
    separately by the ANALYTIC mask below (no gather at the output
    resolution, where a per-pixel gather measured an ~11x train-step
    slowdown on the v5e)."""
    h, w = img.shape[0], img.shape[1]
    sy, sx = _inverse_rot_coords(h, w, angle)
    y0, x0 = jnp.floor(sy), jnp.floor(sx)
    wy, wx = (sy - y0)[..., None], (sx - x0)[..., None]
    zero_fill = fill == "zero"

    def gather(yi, xi):
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        out = img[yc, xc]
        if zero_fill:
            valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
            out = out * valid[..., None]
        return out

    top = gather(y0, x0) * (1 - wx) + gather(y0, x0 + 1) * wx
    bot = gather(y0 + 1, x0) * (1 - wx) + gather(y0 + 1, x0 + 1) * wx
    return top * (1 - wy) + bot * wy


def _shear_mats(shifts, size: int):
    """[L] per-line shifts -> [L, size, size] bank of 1-D bilinear
    shift-with-edge-clamp matrices (line l's resample is ``M[l] @
    line``); weights/clamp via the shared ``_hat_weights``, whose
    clamp doubles as the edge fill."""
    i = jnp.arange(size, dtype=jnp.float32)
    return _hat_weights(i[None, :] + shifts[:, None], size)


def _rotate_shear(img, angle):
    """Rotate (H, W, C) by ``angle`` radians via the 3-shear (Paeth)
    decomposition, edge fill — the TPU-native replacement for the
    4-tap gather rotation on the train path.

    rotate(a) = shear_x(t) . shear_y(s) . shear_x(t) with
    t = -tan(a/2), s = sin(a) (all about the image center, matching
    ``_inverse_rot_coords``'s convention — verified against
    ``_rotate_bilinear`` in tests/test_data.py). Each shear is a bank
    of per-line 32x32 resample matrices applied as batched matmuls:
    under vmap the whole rotation is 3 einsums on the MXU, replacing
    the 4 vmapped gathers that ran at 3-4 GiB/s and cost 15% of the
    train step (runs/bench-roofline/ATTRIB_r05.json). Three successive
    1-D interpolations blur marginally more than one 2-D bilinear —
    distribution-level equivalent (test_augment_stats holds), and the
    torchvision border geometry is untouched (the analytic coverage
    mask below is angle-only)."""
    h, w = img.shape[0], img.shape[1]
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    t = -jnp.tan(angle / 2.0)
    s = jnp.sin(angle)
    mx = _shear_mats(t * (jnp.arange(h, dtype=jnp.float32) - cy), w)
    my = _shear_mats(s * (jnp.arange(w, dtype=jnp.float32) - cx), h)
    img = jnp.einsum("hij,hjc->hic", mx, img)   # x-shear
    img = jnp.einsum("wij,jwc->iwc", my, img)   # y-shear
    return jnp.einsum("hij,hjc->hic", mx, img)  # x-shear (same bank)


def _rotation_border_mask(size: int, angle):
    """The bilinear COVERAGE of a ``size``-square frame rotated by
    ``angle`` — i.e. exactly the alpha PIL's rotate gives a ones-image
    (soft 1px edge included) — computed in closed form per pixel:
    separable validity-weighted tap fractions of the inverse-rotated
    coordinates. Pure elementwise math, so applying torchvision's
    post-rotation black corners costs nothing on TPU."""
    sy, sx = _inverse_rot_coords(size, size, angle)

    def cov(s):
        i0 = jnp.floor(s)
        f = s - i0
        v0 = ((i0 >= 0) & (i0 <= size - 1)).astype(jnp.float32)
        v1 = ((i0 + 1 >= 0) & (i0 + 1 <= size - 1)).astype(jnp.float32)
        return (1.0 - f) * v0 + f * v1

    return cov(sy) * cov(sx)


# ---------------------------------------------------------------------------
# Color jitter (torchvision-strength ops, fixed order)
# ---------------------------------------------------------------------------

# Plain numpy: a module-level jnp constant would initialize the XLA
# backend at import time, breaking jax.distributed.initialize ordering.
_GRAY = np.asarray([0.299, 0.587, 0.114], np.float32)


def _rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    maxc = jnp.max(x, axis=-1)
    minc = jnp.min(x, axis=-1)
    v = maxc
    d = maxc - minc
    safe_d = jnp.where(d == 0, 1.0, d)
    s = jnp.where(maxc == 0, 0.0, d / jnp.where(maxc == 0, 1.0, maxc))
    rc = (maxc - r) / safe_d
    gc = (maxc - g) / safe_d
    bc = (maxc - b) / safe_d
    h = jnp.where(maxc == r, bc - gc,
                  jnp.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = jnp.where(d == 0, 0.0, (h / 6.0) % 1.0)
    return h, s, v


def _hsv_to_rgb(h, s, v):
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    i = i.astype(jnp.int32) % 6
    p = v * (1.0 - s)
    q = v * (1.0 - f * s)
    t = v * (1.0 - (1.0 - f) * s)
    rs = jnp.stack([v, q, p, p, t, v], axis=-1)
    gs = jnp.stack([t, v, v, q, p, p], axis=-1)
    bs = jnp.stack([p, p, t, v, v, q], axis=-1)
    one_hot = jax.nn.one_hot(i, 6, dtype=v.dtype)
    return jnp.stack([(rs * one_hot).sum(-1), (gs * one_hot).sum(-1),
                      (bs * one_hot).sum(-1)], axis=-1)


def _color_jitter(key, x, cfg: DataConfig):
    kb, kc, ks, kh = jax.random.split(key, 4)
    if cfg.jitter_brightness > 0:
        b = jax.random.uniform(kb, (), minval=1 - cfg.jitter_brightness,
                               maxval=1 + cfg.jitter_brightness)
        x = jnp.clip(x * b, 0.0, 1.0)
    if cfg.jitter_contrast > 0:
        c = jax.random.uniform(kc, (), minval=1 - cfg.jitter_contrast,
                               maxval=1 + cfg.jitter_contrast)
        mean = jnp.mean(x @ _GRAY)
        x = jnp.clip(c * x + (1 - c) * mean, 0.0, 1.0)
    if cfg.jitter_saturation > 0:
        s = jax.random.uniform(ks, (), minval=1 - cfg.jitter_saturation,
                               maxval=1 + cfg.jitter_saturation)
        gray = (x @ _GRAY)[..., None]
        x = jnp.clip(s * x + (1 - s) * gray, 0.0, 1.0)
    if cfg.jitter_hue > 0:
        dh = jax.random.uniform(kh, (), minval=-cfg.jitter_hue,
                                maxval=cfg.jitter_hue)
        h, s_, v = _rgb_to_hsv(x)
        x = _hsv_to_rgb((h + dh) % 1.0, s_, v)
    return x


# ---------------------------------------------------------------------------
# Random resized crop parameters (torchvision sampling, clamped)
# ---------------------------------------------------------------------------

def _rrc_params(key, cfg: DataConfig):
    ka, kr, ky, kx = jax.random.split(key, 4)
    area = float(SRC * SRC)
    target = jax.random.uniform(ka, (), minval=cfg.rrc_scale[0],
                                maxval=cfg.rrc_scale[1]) * area
    log_ratio = jax.random.uniform(
        kr, (), minval=math.log(cfg.rrc_ratio[0]),
        maxval=math.log(cfg.rrc_ratio[1]))
    ratio = jnp.exp(log_ratio)
    w = jnp.clip(jnp.sqrt(target * ratio), 1.0, SRC)
    h = jnp.clip(jnp.sqrt(target / ratio), 1.0, SRC)
    top = jax.random.uniform(ky, (), minval=0.0, maxval=SRC - h)
    left = jax.random.uniform(kx, (), minval=0.0, maxval=SRC - w)
    return top, left, h, w


# ---------------------------------------------------------------------------
# Public pipelines
# ---------------------------------------------------------------------------

def _augment_one(key, img_u8, cfg: DataConfig):
    kf, kr, kc, kj = jax.random.split(key, 4)
    x = img_u8.astype(jnp.float32) / 255.0
    flip = jax.random.bernoulli(kf)
    x = jnp.where(flip, x[:, ::-1, :], x)
    if cfg.rotation_degrees > 0:
        angle = jax.random.uniform(
            kr, (), minval=-cfg.rotation_degrees,
            maxval=cfg.rotation_degrees) * (math.pi / 180.0)
        # Content rotation at the 32px SOURCE, edge fill. The 3-shear
        # matmul path (gather-free, see _rotate_shear) is exact only
        # while the intermediate shears stay inside the frame; beyond
        # ~30 degrees their edge clamps start smearing content, so
        # larger configured ranges keep the direct 4-tap gather
        # (rotation_degrees is static — the choice is made at trace
        # time, not per angle).
        if cfg.rotation_degrees <= 30.0:
            x = _rotate_shear(x, angle)
        else:
            x = _rotate_bilinear(x, angle, fill="edge")
    # Color jitter at the 32px SOURCE, before the crop+resize: every
    # jitter pass (and its clips/reductions) touches a 49x smaller
    # tensor than at 224 (measured ~5% of the train step there,
    # runs/bench-roofline/ATTRIB_r05.json). Jitter is per-pixel and
    # bilinear resampling is a convex combination, so brightness/
    # saturation commute with the resize exactly up to the clip;
    # contrast's gray-mean is now over the full source rather than the
    # crop, and hue's nonlinearity interpolates slightly differently —
    # distribution-level equivalent (test_augment_stats' PIL bands
    # hold), and the jitter-vs-crop order was already a documented
    # deviation from torchvision.
    x = _color_jitter(kj, x, cfg)
    top, left, h, w = _rrc_params(kc, cfg)
    row_m = _bilinear_matrix(top, h, cfg.image_size, SRC)
    col_m = _bilinear_matrix(left, w, cfg.image_size, SRC)
    x = _apply_separable(x, row_m, col_m)
    if cfg.rotation_degrees > 0:
        # torchvision rotates LAST, leaving black corners on the full
        # output frame — reproduced here as the closed-form coverage
        # mask at 224 (a round-1-style zero-fill rotate-before-crop
        # shed most of that border mass: measured 0.5% dark pixels vs
        # torchvision's ~2.5% and a +0.03 channel-mean shift; a literal
        # rotate-at-224 gather measured ~11x slower end-to-end).
        x = x * _rotation_border_mask(cfg.image_size, angle)[..., None]
    mean = jnp.asarray(cfg.mean)
    std = jnp.asarray(cfg.std)
    return (x - mean) / std


def make_train_augment(cfg: DataConfig) -> Callable:
    """Returns fn(key, images_u8[B,32,32,3]) -> float32 [B,S,S,3].

    Pure and jit-safe; call it inside the jitted train step so XLA fuses
    augmentation with the forward pass.
    """
    def augment(key, images):
        keys = jax.random.split(key, images.shape[0])
        return jax.vmap(partial(_augment_one, cfg=cfg))(keys, images)
    return augment


def make_eval_preprocess(cfg: DataConfig) -> Callable:
    """Returns fn(images_u8[B,32,32,3]) -> float32 [B,S,S,3].

    Resize(image_size) + Normalize (reference test transform, :84-89) as
    two batched matmuls with a static resize matrix.
    """
    rm = jnp.asarray(resize_matrix_np(cfg.image_size, SRC))
    mean = jnp.asarray(cfg.mean)
    std = jnp.asarray(cfg.std)

    def preprocess(images):
        x = images.astype(jnp.float32) / 255.0
        x = jnp.einsum("oh,bhwc->bowc", rm, x)
        x = jnp.einsum("pw,bowc->bopc", rm, x)
        return (x - mean) / std
    return preprocess


# ---------------------------------------------------------------------------
# Mixup / CutMix (beyond-parity; absent from the reference's transform
# stack at :72-82). Both run on-device inside the jitted train step,
# pairing each example with a random OTHER example of the same global
# batch (one permutation gather — XLA turns it into collective traffic
# under the data sharding, amortized over the whole step).
# ---------------------------------------------------------------------------


def mixup_cutmix(key, images, labels, mixup_alpha: float,
                 cutmix_alpha: float):
    """-> (mixed_images, labels_b, lam): train with
    lam * CE(logits, labels) + (1 - lam) * CE(logits, labels_b).

    One lam ~ Beta(alpha, alpha) per batch (the standard formulation).
    With both alphas > 0 each step picks mixup or CutMix with equal
    probability. CutMix pastes a random box from the paired example and
    sets lam to the surviving-area fraction.
    """
    if mixup_alpha <= 0 and cutmix_alpha <= 0:
        return images, labels, jnp.float32(1.0)
    b, h, w = images.shape[:3]
    kperm, kchoice, kmix, kcut, kbox = jax.random.split(key, 5)
    perm = jax.random.permutation(kperm, b)
    images_b, labels_b = images[perm], labels[perm]

    def do_mixup(_):
        lam = jax.random.beta(kmix, mixup_alpha, mixup_alpha)
        lam = lam.astype(jnp.float32)
        out = lam * images + (1.0 - lam) * images_b
        return out.astype(images.dtype), lam

    def do_cutmix(_):
        lam0 = jax.random.beta(kcut, cutmix_alpha,
                               cutmix_alpha).astype(jnp.float32)
        # box covering (1 - lam0) of the area, clipped at the borders
        rh = jnp.sqrt(1.0 - lam0) * h
        rw = jnp.sqrt(1.0 - lam0) * w
        cy = jax.random.uniform(kbox, (), minval=0.0, maxval=1.0) * h
        cx = jax.random.uniform(jax.random.fold_in(kbox, 1), (),
                                minval=0.0, maxval=1.0) * w
        y0, y1 = jnp.clip(cy - rh / 2, 0, h), jnp.clip(cy + rh / 2, 0, h)
        x0, x1 = jnp.clip(cx - rw / 2, 0, w), jnp.clip(cx + rw / 2, 0, w)
        yy = jnp.arange(h, dtype=jnp.float32)
        xx = jnp.arange(w, dtype=jnp.float32)
        box = ((yy[:, None] >= y0) & (yy[:, None] < y1)
               & (xx[None, :] >= x0) & (xx[None, :] < x1))
        out = jnp.where(box[None, :, :, None], images_b, images)
        # lam = surviving fraction of the ORIGINAL image (exact, after
        # border clipping)
        lam = 1.0 - jnp.mean(box.astype(jnp.float32))
        return out.astype(images.dtype), lam

    if mixup_alpha > 0 and cutmix_alpha > 0:
        use_mix = jax.random.bernoulli(kchoice)
        out, lam = jax.lax.cond(use_mix, do_mixup, do_cutmix, None)
    elif mixup_alpha > 0:
        out, lam = do_mixup(None)
    else:
        out, lam = do_cutmix(None)
    return out, labels_b, lam
