"""CIFAR-10 loading.

The reference downloads CIFAR-10 through torchvision with a rank-0 +
barrier dance (cifar10_mpi_mobilenet_224.py:93-102). Here the dataset is
read directly from the standard ``cifar-10-batches-py`` pickle layout
(what torchvision's download produces), kept fully in host memory
(50k x 32x32x3 uint8 = 150 MB), and sharded per host by the pipeline.
A deterministic synthetic dataset stands in when the real data is absent
(hermetic tests / benchmarks in no-egress environments).
"""

from __future__ import annotations

import os
import pickle
import tarfile
from typing import Tuple

import numpy as np

from tpunet.config import DataConfig
from tpunet.data.download import BATCH_DIR as _BATCH_DIR
from tpunet.data.download import TARBALL as _TARBALL

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]


def _read_batch(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NHWC
    labels = np.asarray(d[b"labels"], dtype=np.int32)
    return np.ascontiguousarray(data), labels


def load_cifar10(data_dir: str, download: bool = True) -> Arrays:
    """Load CIFAR-10 from ``data_dir``, downloading (checksum-verified)
    and/or extracting the tarball when needed — the reference's
    ``download=True`` dataset path (cifar10_mpi_mobilenet_224.py:93-102).

    Returns (train_x[50000,32,32,3] u8, train_y, test_x[10000,...], test_y).
    """
    from tpunet.data.download import ensure_cifar10

    data_dir = ensure_cifar10(os.path.expanduser(data_dir),
                              download=download)
    batch_dir = os.path.join(data_dir, _BATCH_DIR)
    tarball = os.path.join(data_dir, _TARBALL)
    if not os.path.isdir(batch_dir) and os.path.exists(tarball):
        with tarfile.open(tarball, "r:gz") as tf:
            tf.extractall(data_dir)
    if not os.path.isdir(batch_dir):
        raise FileNotFoundError(
            f"CIFAR-10 not found under {data_dir!r} (expected "
            f"{_BATCH_DIR}/ or {_TARBALL}).")
    xs, ys = [], []
    for i in range(1, 6):
        x, y = _read_batch(os.path.join(batch_dir, f"data_batch_{i}"))
        xs.append(x)
        ys.append(y)
    train_x = np.concatenate(xs)
    train_y = np.concatenate(ys)
    test_x, test_y = _read_batch(os.path.join(batch_dir, "test_batch"))
    return train_x, train_y, test_x, test_y


def synthetic_cifar10(n_train: int = 50_000, n_test: int = 10_000,
                      num_classes: int = 10, seed: int = 0) -> Arrays:
    """Deterministic class-separable stand-in with CIFAR-10 shapes.

    Each class is a fixed low-frequency color pattern plus noise, so a
    model can actually fit it (used by convergence smoke tests).
    """
    rng = np.random.default_rng(seed)
    protos = rng.uniform(40, 215, size=(num_classes, 8, 8, 3))

    def make(n, salt):
        r = np.random.default_rng(seed + salt)
        y = r.integers(0, num_classes, size=n).astype(np.int32)
        base = protos[y]                                   # (n, 8, 8, 3)
        img = np.repeat(np.repeat(base, 4, axis=1), 4, axis=2)
        img = img + r.normal(0, 24, size=img.shape)
        return np.clip(img, 0, 255).astype(np.uint8), y

    train_x, train_y = make(n_train, 1)
    test_x, test_y = make(n_test, 2)
    return train_x, train_y, test_x, test_y


def get_dataset(cfg: DataConfig) -> Arrays:
    if cfg.dataset == "synthetic":
        return synthetic_cifar10(n_train=cfg.synthetic_train_size,
                                 n_test=cfg.synthetic_test_size)
    if cfg.dataset == "cifar10":
        return load_cifar10(cfg.data_dir, download=cfg.download)
    if cfg.dataset in ("synthetic_lm", "text_lm"):
        from tpunet.data.lm import get_lm_dataset
        return get_lm_dataset(cfg)
    raise ValueError(f"unknown dataset {cfg.dataset!r}")
