"""Dataset and pretrained-weight acquisition.

The reference auto-downloads CIFAR-10 through torchvision with a rank-0 +
barrier gate (cifar10_mpi_mobilenet_224.py:93-102, ``download=True`` at
:97) and pulls ImageNet-pretrained MobileNetV2 weights through the torch
hub cache (``models.mobilenet_v2(pretrained=True)``, :137). This module
is the tpunet equivalent: checksum-verified HTTP fetch of the same two
artifacts, invoked lazily by the data/model layers. Multi-host gating
reuses the existing process-0 gate in tpunet/main.py (process 0 builds
the Trainer — and therefore downloads — first; the other hosts wait on
``sync_hosts`` and find the files already present).

In a no-egress environment the fetch fails fast with the exact drop-in
procedure (file name, destination, checksum), so a user can stage the
artifacts out-of-band and rerun — nothing else in the stack changes.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import urllib.error
import urllib.request

# Canonical CIFAR-10 python tarball (the file torchvision's download
# produces and pins by md5).
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"

# torchvision's ImageNet-pretrained MobileNetV2 (the exact weights the
# reference fine-tunes from). torch.hub names checkpoint files with the
# first 8 hex digits of their sha256 and verifies that prefix on
# download; we check the same invariant.
MOBILENET_V2_URL = "https://download.pytorch.org/models/mobilenet_v2-b0353104.pth"
MOBILENET_V2_SHA256_PREFIX = "b0353104"

_DEFAULT_WEIGHTS_CACHE = os.path.join("~", ".cache", "tpunet")

# Extracted/tarball names of the standard CIFAR-10 python layout —
# shared with tpunet/data/cifar10.py (single source of truth).
BATCH_DIR = "cifar-10-batches-py"
TARBALL = "cifar-10-python.tar.gz"


class DownloadError(RuntimeError):
    """Fetch failed (no egress / checksum mismatch); carries drop-in help."""


def _checksums(path: str):
    md5, sha = hashlib.md5(), hashlib.sha256()
    with open(path, "rb") as f:
        while chunk := f.read(1 << 20):
            md5.update(chunk)
            sha.update(chunk)
    return md5.hexdigest(), sha.hexdigest()


def fetch(url: str, dest: str, *, md5: str | None = None,
          sha256_prefix: str | None = None, timeout: float = 60.0) -> str:
    """Download ``url`` to ``dest`` atomically (tempfile + rename) and
    verify checksums. Returns ``dest``. Raises :class:`DownloadError` on
    network failure or checksum mismatch (partial/corrupt files are
    removed, never left at ``dest``)."""
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    fd, part = tempfile.mkstemp(dir=os.path.dirname(dest) or ".",
                                suffix=".part")
    try:
        with os.fdopen(fd, "wb") as out:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                while chunk := r.read(1 << 20):
                    out.write(chunk)
        got_md5, got_sha = _checksums(part)
        if md5 and got_md5 != md5:
            raise DownloadError(f"{url}: md5 {got_md5} != expected {md5}")
        if sha256_prefix and not got_sha.startswith(sha256_prefix):
            raise DownloadError(f"{url}: sha256 {got_sha[:8]}... != "
                                f"expected prefix {sha256_prefix}")
        os.replace(part, dest)
        return dest
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        raise DownloadError(f"fetching {url} failed: {e}") from e
    finally:
        if os.path.exists(part):
            os.unlink(part)


def ensure_cifar10(data_dir: str, download: bool = True) -> str:
    """Make sure the CIFAR-10 tarball (or extracted batches) exists under
    ``data_dir``, downloading it when permitted. Returns ``data_dir``.

    Mirrors the reference's ``download=True`` dataset construction
    (cifar10_mpi_mobilenet_224.py:93-102); call only from process 0
    (tpunet/main.py's gate does this).
    """
    data_dir = os.path.expanduser(data_dir)
    tarball = os.path.join(data_dir, TARBALL)
    if os.path.isdir(os.path.join(data_dir, BATCH_DIR)):
        return data_dir
    if os.path.exists(tarball):
        # Verify staged (drop-in) tarballs too — torchvision's
        # check_integrity does the same for pre-existing files; a
        # truncated copy would otherwise die later in tarfile/pickle
        # with no actionable message.
        got_md5, _ = _checksums(tarball)
        if got_md5 != CIFAR10_MD5:
            raise DownloadError(
                f"{tarball!r} is corrupt (md5 {got_md5} != expected "
                f"{CIFAR10_MD5}); delete it and re-stage "
                f"cifar-10-python.tar.gz from {CIFAR10_URL}")
        return data_dir
    help_text = (
        f"CIFAR-10 is not present under {data_dir!r}. "
        f"Drop-in procedure for offline environments: obtain "
        f"{TARBALL} (md5 {CIFAR10_MD5}) from "
        f"{CIFAR10_URL} and place it at {tarball!r}; it is extracted "
        f"automatically on the next run. Or use --dataset synthetic.")
    if not download:
        raise DownloadError("downloads disabled (--no-download). " + help_text)
    try:
        print(f"Downloading CIFAR-10 -> {tarball}")
        fetch(CIFAR10_URL, tarball, md5=CIFAR10_MD5)
    except DownloadError as e:
        raise DownloadError(f"{e}. {help_text}") from e
    return data_dir


def ensure_mobilenet_v2_weights(path: str | None = None,
                                download: bool = True) -> str:
    """Resolve the ImageNet-pretrained MobileNetV2 ``.pth`` used for
    transfer learning (``--pretrained auto``), downloading torchvision's
    checkpoint into ``~/.cache/tpunet`` when absent. Returns the path.
    """
    if path is None:
        path = os.path.join(os.path.expanduser(_DEFAULT_WEIGHTS_CACHE),
                            os.path.basename(MOBILENET_V2_URL))
    if os.path.exists(path):
        return path
    help_text = (
        f"Drop-in procedure for offline environments: obtain "
        f"{os.path.basename(MOBILENET_V2_URL)} (sha256 starting "
        f"{MOBILENET_V2_SHA256_PREFIX}) from {MOBILENET_V2_URL} and "
        f"place it at {path!r}, or pass --pretrained <your/path.pth>.")
    if not download:
        raise DownloadError("downloads disabled (--no-download). " + help_text)
    try:
        print(f"Downloading pretrained MobileNetV2 -> {path}")
        fetch(MOBILENET_V2_URL, path,
              sha256_prefix=MOBILENET_V2_SHA256_PREFIX)
    except DownloadError as e:
        raise DownloadError(f"{e}. {help_text}") from e
    return path
