"""Synthetic language-modeling data.

The reference has no sequence workload at all (SURVEY.md section 5);
this generator backs the LM model family's tests and demos in
no-egress environments. Sequences follow a seeded random bigram
process: each token has one preferred successor taken with probability
0.8 (uniform otherwise), so a causal LM has real, learnable structure
(a perfect model reaches ~0.8 next-token accuracy; a uniform guesser
1/vocab) while the data stays hermetic and deterministic.

Returned in the Trainer's (train_x, train_y, test_x, test_y)
convention; for token data the y arrays are per-sequence dummy labels
(the LM steps derive targets by shifting x — tpunet/train/steps.py).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from tpunet.config import DataConfig

Arrays = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]


def synthetic_lm(n_train: int, n_test: int, seq_len: int = 128,
                 vocab: int = 256, seed: int = 0) -> Arrays:
    rng = np.random.default_rng(seed)
    preferred = rng.integers(0, vocab, vocab)

    def gen(n: int) -> np.ndarray:
        toks = np.empty((n, seq_len), np.int32)
        toks[:, 0] = rng.integers(0, vocab, n)
        for t in range(1, seq_len):
            follow = rng.random(n) < 0.8
            toks[:, t] = np.where(follow, preferred[toks[:, t - 1]],
                                  rng.integers(0, vocab, n))
        return toks

    train_x, test_x = gen(n_train), gen(n_test)
    return (train_x, np.zeros(n_train, np.int32),
            test_x, np.zeros(n_test, np.int32))


def text_lm(path: str, seq_len: int, train_frac: float = 0.9) -> Arrays:
    """Byte-level LM dataset from a local file: the raw bytes ARE the
    tokens (vocab 256, no tokenizer, no downloads — works in no-egress
    environments on any text/corpus file). The stream is chunked into
    non-overlapping seq_len windows; the TAIL fraction is the test split
    (contiguous, so train/test measure held-out text, not shuffled
    leakage from the same passages)."""
    with open(path, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    n_seq = len(data) // seq_len
    if n_seq < 2:
        raise ValueError(
            f"{path!r} has {len(data)} bytes; need at least "
            f"2*seq_len = {2 * seq_len} for a train/test split")
    toks = data[:n_seq * seq_len].reshape(n_seq, seq_len).astype(np.int32)
    n_train = min(n_seq - 1, max(1, int(round(n_seq * train_frac))))
    train_x, test_x = toks[:n_train], toks[n_train:]
    return (train_x, np.zeros(len(train_x), np.int32),
            test_x, np.zeros(len(test_x), np.int32))


def text_lm_packed(path: str, seq_len: int,
                   train_frac: float = 0.9) -> Arrays:
    """Byte-level PACKED LM dataset: the file is split into documents
    on newlines, documents are greedily packed into ``seq_len`` rows
    (no document straddles a row boundary; over-long documents are
    split), and each row carries per-token SEGMENT IDS in the y slot —
    1..k for the row's documents, 0 for tail padding. Trained with the
    segment-masked attention (tpunet/ops/flash.py segment_ids) and the
    packed LM step, tokens never attend — and the loss never predicts —
    across document boundaries or into padding.
    """
    with open(path, "rb") as f:
        raw = f.read()
    docs = [d for d in raw.split(b"\n") if d]
    if not docs:
        raise ValueError(f"{path!r} has no non-empty lines to pack")
    rows, segs = [], []
    cur = np.zeros(seq_len, np.int32)
    cur_seg = np.zeros(seq_len, np.int32)
    pos, seg_id = 0, 0

    def flush():
        nonlocal cur, cur_seg, pos, seg_id
        if pos:
            rows.append(cur)
            segs.append(cur_seg)
            cur = np.zeros(seq_len, np.int32)
            cur_seg = np.zeros(seq_len, np.int32)
            pos, seg_id = 0, 0

    for doc in docs:
        toks = np.frombuffer(doc, np.uint8).astype(np.int32)
        for start in range(0, len(toks), seq_len):   # split long docs
            piece = toks[start:start + seq_len]
            if pos + len(piece) > seq_len:
                flush()
            seg_id += 1
            cur[pos:pos + len(piece)] = piece
            cur_seg[pos:pos + len(piece)] = seg_id
            pos += len(piece)
    flush()
    if len(rows) < 2:
        raise ValueError(
            f"{path!r} packs into {len(rows)} row(s); need at least 2 "
            f"for a train/test split (more text or smaller --seq-len)")
    x = np.stack(rows)
    y = np.stack(segs)
    n_train = min(len(x) - 1, max(1, int(round(len(x) * train_frac))))
    return x[:n_train], y[:n_train], x[n_train:], y[n_train:]


def get_lm_dataset(cfg: DataConfig) -> Arrays:
    if cfg.dataset == "synthetic_lm":
        return synthetic_lm(cfg.synthetic_train_size,
                            cfg.synthetic_test_size,
                            seq_len=cfg.seq_len, vocab=cfg.vocab_size)
    if cfg.dataset == "text_lm":
        if not cfg.text_path:
            raise ValueError("dataset 'text_lm' needs a file: --text-file")
        if cfg.vocab_size < 256:
            raise ValueError(
                f"text_lm is byte-level: vocab_size must be >= 256, got "
                f"{cfg.vocab_size}")
        if cfg.pack_docs:
            return text_lm_packed(cfg.text_path, cfg.seq_len)
        return text_lm(cfg.text_path, cfg.seq_len)
    raise ValueError(f"unknown LM dataset {cfg.dataset!r}")
