"""ctypes bindings for the native host-side batch assembler (cxx/batcher.cc).

The reference's host data path is DataLoader worker *processes*
(cifar10_mpi_mobilenet_224.py:126-133); tpunet's device-side augmentation
leaves only a permutation gather on the host, which this C++ library does
with threads in-process and prefetches ahead of the device. Everything
degrades gracefully: if the shared library is missing and no C++
toolchain is available, callers fall back to numpy fancy indexing.

Build: ``make -C cxx`` (or automatic on first import when g++ exists).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SRC = os.path.join(_REPO, "cxx", "batcher.cc")
_LIB_DIR = os.path.join(_HERE, "_lib")
# TPUNET_NATIVE_LIB points the bindings at an alternative build of the
# same source — the sanitizer variants (``make -C cxx asan|tsan|ubsan``,
# driven by scripts/check_sanitizers.py with the matching runtime
# LD_PRELOADed). An override is used as-is: never auto-(re)built, and
# required to exist (a sanitizer gate that silently fell back to the
# plain library would pass without testing anything).
_LIB_OVERRIDE = os.environ.get("TPUNET_NATIVE_LIB", "")
_LIB = _LIB_OVERRIDE or os.path.join(_LIB_DIR, "libtnbatcher.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


# Must match tn_abi_version() in cxx/batcher.cc; bump both together.
# v2: flight-recorder surface (tn_journal_read / tn_crash_install).
_ABI_VERSION = 2


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    os.makedirs(_LIB_DIR, exist_ok=True)
    # Compile to a private temp file and rename into place: atomic under
    # POSIX, so concurrent processes (multi-controller tests) never dlopen
    # a partially written library. One source of truth for flags: $CXX
    # like the Makefile, defaulting to g++.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-std=c++17", "-Wall", "-Werror=return-type",
           "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _stale() -> bool:
    try:
        return os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
    except OSError:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if _LIB_OVERRIDE:
            if not os.path.exists(_LIB):
                _load_failed = True
                return None
        elif (not os.path.exists(_LIB) or _stale()) and not _build():
            if not os.path.exists(_LIB):
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        # Refuse a library whose C ABI doesn't match these bindings
        # (e.g. a stale .so left behind when a rebuild failed).
        try:
            lib.tn_abi_version.restype = ctypes.c_int
            abi = lib.tn_abi_version()
        except AttributeError:
            abi = -1
        if abi != _ABI_VERSION:
            _load_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.tn_gather_rows.argtypes = [u8p, i64p, ctypes.c_int64,
                                       ctypes.c_int64, u8p, ctypes.c_int]
        lib.tn_gather_rows.restype = None
        lib.tn_prefetcher_create.argtypes = [u8p, i32p, ctypes.c_int64,
                                             ctypes.c_int64, ctypes.c_int64,
                                             ctypes.c_int, ctypes.c_int]
        lib.tn_prefetcher_create.restype = ctypes.c_void_p
        lib.tn_prefetcher_start_epoch.argtypes = [ctypes.c_void_p, i64p,
                                                  ctypes.c_int64]
        lib.tn_prefetcher_start_epoch.restype = ctypes.c_int
        lib.tn_prefetcher_next.argtypes = [ctypes.c_void_p, u8p, i32p]
        lib.tn_prefetcher_next.restype = ctypes.c_int
        lib.tn_prefetcher_destroy.argtypes = [ctypes.c_void_p]
        lib.tn_prefetcher_destroy.restype = None
        lib.tn_journal_read.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tn_journal_read.restype = ctypes.c_int
        lib.tn_crash_install.argtypes = [ctypes.c_char_p]
        lib.tn_crash_install.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# Flight-recorder surface (tpunet/obs/flightrec/): the native op
# journal and the C-level crash spill.
# ---------------------------------------------------------------------------


class _JournalEntry(ctypes.Structure):
    # Mirrors JournalEntry in cxx/batcher.cc (packed 32 bytes).
    _fields_ = [("seq", ctypes.c_uint64), ("op", ctypes.c_uint32),
                ("tid", ctypes.c_uint32), ("a", ctypes.c_int64),
                ("b", ctypes.c_int64)]


def journal_entries(max_entries: int = 256) -> list:
    """Live snapshot of the native op journal (oldest-first dicts with
    op names), or [] when the library is unavailable."""
    lib = _load()
    if lib is None:
        return []
    buf = (_JournalEntry * max_entries)()
    n = lib.tn_journal_read(buf, max_entries)
    try:
        # Op-id -> name table from the flight recorder. Optional: this
        # module (and the jax-free sanitizer stress driver that loads
        # it by file path) must work without the obs stack — raw
        # ``opN`` names then.
        from tpunet.obs.flightrec.report import NATIVE_OPS
    except Exception:
        NATIVE_OPS = {}
    return [{"seq": int(e.seq),
             "op": NATIVE_OPS.get(int(e.op), f"op{int(e.op)}"),
             "tid": int(e.tid), "a": int(e.a), "b": int(e.b)}
            for e in buf[:max(0, n)]]


def crash_install(path: str) -> bool:
    """Arm the C crash handler: on SIGSEGV/SIGABRT/SIGBUS it spills
    the op journal to ``path`` and chains to the previously installed
    handler (call AFTER faulthandler.enable so Python stacks still
    dump). False when the library is unavailable or sigaction
    failed."""
    lib = _load()
    if lib is None:
        return False
    return lib.tn_crash_install(os.fsencode(path)) == 0


def _as_u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _as_i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _as_i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def gather_rows(src: np.ndarray, idx: np.ndarray,
                n_threads: int = 4) -> np.ndarray:
    """out[i] = src[idx[i]] over the leading axis, for ANY fixed-size
    dtype — the C++ gather is a raw byte memcpy per row (row_bytes =
    trailing-shape elements x itemsize), so uint8 images and int32
    token sequences ride the same path.

    Multithreaded native memcpy when the library is available, else numpy
    fancy indexing — bit-identical either way.
    """
    lib = _load()
    src = np.ascontiguousarray(src)
    if lib is None:
        return src[idx]
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.itemsize
    lib.tn_gather_rows(_as_u8p(src), _as_i64p(idx), len(idx), row_bytes,
                       _as_u8p(out), n_threads)
    return out


class NativePrefetcher:
    """Background-thread batch assembly over an in-RAM dataset of any
    fixed-size dtype (uint8 image rows, int32 token rows — the C++ side
    moves raw bytes either way).

    Owns references to ``rows``/``labels`` for its lifetime (the C++
    side reads their buffers directly, zero-copy).
    """

    def __init__(self, rows: np.ndarray, labels: np.ndarray,
                 local_batch: int, depth: int = 4, n_threads: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native batcher unavailable")
        self._lib = lib
        self.rows = np.ascontiguousarray(rows)
        self.labels = np.ascontiguousarray(labels, dtype=np.int32)
        self.local_batch = int(local_batch)
        self.row_shape = self.rows.shape[1:]
        self.row_dtype = self.rows.dtype
        row_bytes = (int(np.prod(self.row_shape, dtype=np.int64))
                     * self.rows.itemsize)
        self._handle = lib.tn_prefetcher_create(
            _as_u8p(self.rows), _as_i32p(self.labels), len(self.rows),
            row_bytes, self.local_batch, depth, n_threads)
        self._idx: Optional[np.ndarray] = None   # keep alive for C++ reads
        # Host-thread registry (tpunet/obs/flightrec/): the C++ worker
        # cannot beat from its own thread, so the consumer side beats
        # for it — a beat marks "about to block in next()" (busy), and
        # a consumer stuck there past the budget is exactly the hang
        # the thread_stalled alert should page (the C journal then
        # says what the worker was doing). Lazy import: this module
        # must stay importable without the obs stack.
        try:
            from tpunet.obs import flightrec
            self._fr = flightrec
            self._thread = flightrec.register_thread(
                "native-prefetcher", stall_after_s=120.0)
        except Exception:
            self._fr = self._thread = None

    def iter_epoch(self, idx: np.ndarray) -> Iterator[
            Tuple[np.ndarray, np.ndarray]]:
        """Yield (rows[local_batch, ...], labels) following ``idx``."""
        self._idx = np.ascontiguousarray(idx, dtype=np.int64)
        if self._fr is not None:
            self._fr.record("prefetch", f"epoch start n={len(idx)}")
        if self._lib.tn_prefetcher_start_epoch(
                self._handle, _as_i64p(self._idx), len(self._idx)):
            raise IndexError("prefetcher index out of range for dataset")
        while True:
            x = np.empty((self.local_batch,) + self.row_shape,
                         self.row_dtype)
            y = np.empty((self.local_batch,), np.int32)
            if self._thread is not None:
                self._thread.beat("busy")    # about to block in next()
            if self._lib.tn_prefetcher_next(self._handle, _as_u8p(x),
                                            _as_i32p(y)):
                if self._thread is not None:
                    self._thread.beat("idle")
                if self._fr is not None:
                    self._fr.record("prefetch", "epoch exhausted")
                return
            if self._thread is not None:
                self._thread.beat("idle")
            yield x, y

    def close(self) -> None:
        if self._handle:
            if self._fr is not None:
                self._fr.record("prefetch", "destroy")
            self._lib.tn_prefetcher_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
