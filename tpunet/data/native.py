"""ctypes bindings for the native host-side batch assembler (cxx/batcher.cc).

The reference's host data path is DataLoader worker *processes*
(cifar10_mpi_mobilenet_224.py:126-133); tpunet's device-side augmentation
leaves only a permutation gather on the host, which this C++ library does
with threads in-process and prefetches ahead of the device. Everything
degrades gracefully: if the shared library is missing and no C++
toolchain is available, callers fall back to numpy fancy indexing.

Build: ``make -C cxx`` (or automatic on first import when g++ exists).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SRC = os.path.join(_REPO, "cxx", "batcher.cc")
_LIB_DIR = os.path.join(_HERE, "_lib")
_LIB = os.path.join(_LIB_DIR, "libtnbatcher.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


# Must match tn_abi_version() in cxx/batcher.cc; bump both together.
_ABI_VERSION = 1


def _build() -> bool:
    if not os.path.exists(_SRC):
        return False
    os.makedirs(_LIB_DIR, exist_ok=True)
    # Compile to a private temp file and rename into place: atomic under
    # POSIX, so concurrent processes (multi-controller tests) never dlopen
    # a partially written library. One source of truth for flags: $CXX
    # like the Makefile, defaulting to g++.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-std=c++17", "-Wall", "-Werror=return-type",
           "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _stale() -> bool:
    try:
        return os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
    except OSError:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if (not os.path.exists(_LIB) or _stale()) and not _build():
            if not os.path.exists(_LIB):
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _load_failed = True
            return None
        # Refuse a library whose C ABI doesn't match these bindings
        # (e.g. a stale .so left behind when a rebuild failed).
        try:
            lib.tn_abi_version.restype = ctypes.c_int
            abi = lib.tn_abi_version()
        except AttributeError:
            abi = -1
        if abi != _ABI_VERSION:
            _load_failed = True
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.tn_gather_rows.argtypes = [u8p, i64p, ctypes.c_int64,
                                       ctypes.c_int64, u8p, ctypes.c_int]
        lib.tn_gather_rows.restype = None
        lib.tn_prefetcher_create.argtypes = [u8p, i32p, ctypes.c_int64,
                                             ctypes.c_int64, ctypes.c_int64,
                                             ctypes.c_int, ctypes.c_int]
        lib.tn_prefetcher_create.restype = ctypes.c_void_p
        lib.tn_prefetcher_start_epoch.argtypes = [ctypes.c_void_p, i64p,
                                                  ctypes.c_int64]
        lib.tn_prefetcher_start_epoch.restype = ctypes.c_int
        lib.tn_prefetcher_next.argtypes = [ctypes.c_void_p, u8p, i32p]
        lib.tn_prefetcher_next.restype = ctypes.c_int
        lib.tn_prefetcher_destroy.argtypes = [ctypes.c_void_p]
        lib.tn_prefetcher_destroy.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _as_u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _as_i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _as_i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def gather_rows(src: np.ndarray, idx: np.ndarray,
                n_threads: int = 4) -> np.ndarray:
    """out[i] = src[idx[i]] over the leading axis, for ANY fixed-size
    dtype — the C++ gather is a raw byte memcpy per row (row_bytes =
    trailing-shape elements x itemsize), so uint8 images and int32
    token sequences ride the same path.

    Multithreaded native memcpy when the library is available, else numpy
    fancy indexing — bit-identical either way.
    """
    lib = _load()
    src = np.ascontiguousarray(src)
    if lib is None:
        return src[idx]
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    out = np.empty((len(idx),) + src.shape[1:], dtype=src.dtype)
    row_bytes = int(np.prod(src.shape[1:], dtype=np.int64)) * src.itemsize
    lib.tn_gather_rows(_as_u8p(src), _as_i64p(idx), len(idx), row_bytes,
                       _as_u8p(out), n_threads)
    return out


class NativePrefetcher:
    """Background-thread batch assembly over an in-RAM dataset of any
    fixed-size dtype (uint8 image rows, int32 token rows — the C++ side
    moves raw bytes either way).

    Owns references to ``rows``/``labels`` for its lifetime (the C++
    side reads their buffers directly, zero-copy).
    """

    def __init__(self, rows: np.ndarray, labels: np.ndarray,
                 local_batch: int, depth: int = 4, n_threads: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError("native batcher unavailable")
        self._lib = lib
        self.rows = np.ascontiguousarray(rows)
        self.labels = np.ascontiguousarray(labels, dtype=np.int32)
        self.local_batch = int(local_batch)
        self.row_shape = self.rows.shape[1:]
        self.row_dtype = self.rows.dtype
        row_bytes = (int(np.prod(self.row_shape, dtype=np.int64))
                     * self.rows.itemsize)
        self._handle = lib.tn_prefetcher_create(
            _as_u8p(self.rows), _as_i32p(self.labels), len(self.rows),
            row_bytes, self.local_batch, depth, n_threads)
        self._idx: Optional[np.ndarray] = None   # keep alive for C++ reads

    def iter_epoch(self, idx: np.ndarray) -> Iterator[
            Tuple[np.ndarray, np.ndarray]]:
        """Yield (rows[local_batch, ...], labels) following ``idx``."""
        self._idx = np.ascontiguousarray(idx, dtype=np.int64)
        if self._lib.tn_prefetcher_start_epoch(
                self._handle, _as_i64p(self._idx), len(self._idx)):
            raise IndexError("prefetcher index out of range for dataset")
        while True:
            x = np.empty((self.local_batch,) + self.row_shape,
                         self.row_dtype)
            y = np.empty((self.local_batch,), np.int32)
            if self._lib.tn_prefetcher_next(self._handle, _as_u8p(x),
                                            _as_i32p(y)):
                return
            yield x, y

    def close(self) -> None:
        if self._handle:
            self._lib.tn_prefetcher_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
