"""Per-host sharded batch iteration (DistributedSampler replacement).

The reference shards with torch's DistributedSampler (num_replicas =
world_size, per-epoch reshuffle via set_epoch; cifar10_mpi_mobilenet_224.py
:119-124,165). Here each host holds the full dataset in RAM (CIFAR-10 is
150 MB) and slices its contiguous shard of a *deterministic global
permutation* seeded by (seed, epoch) — every host computes the same
permutation, so shards are disjoint and exactly cover the data with no
inter-host communication.

Deviations (documented, SURVEY.md section 7 hard-part 4): the train
remainder is dropped instead of padded with duplicates, and evaluation
pads the final batch with *masked* examples so test metrics are exact —
which also fixes the reference's rank-local accuracy wart (:196,224).
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Iterator, Optional, Tuple

import numpy as np


def timed_batches(batches: Iterable, on_wait: Callable[[float], None],
                  wait_ctx: Optional[Callable] = None) -> Iterator:
    """Wrap a batch iterator, reporting host time blocked per fetch.

    ``on_wait(seconds)`` receives the ``perf_counter`` lap spent inside
    each ``next()`` — with the numpy iterators that is fancy-indexing
    cost, with the native prefetcher it is genuine queue-wait — i.e.
    the input-stall side of the stall-vs-compute split the obs epoch
    record reports. ``wait_ctx()`` (optional) supplies a context
    manager entered around the fetch, so the wait shows up as a
    labeled span in profiler traces. Works with any iterable; the
    trainer points it at train_batches or the native prefetcher alike.
    """
    it = iter(batches)
    while True:
        t0 = time.perf_counter()
        try:
            if wait_ctx is not None:
                with wait_ctx():
                    batch = next(it)
            else:
                batch = next(it)
        except StopIteration:
            return
        on_wait(time.perf_counter() - t0)
        yield batch


def _epoch_permutation(n: int, seed: int, epoch: int) -> np.ndarray:
    """Same permutation on every host (counter-based PRNG keyed on inputs)."""
    bits = np.random.Generator(np.random.Philox(key=[seed, epoch]))
    return bits.permutation(n)


def steps_per_epoch(n: int, global_batch: int) -> int:
    return n // global_batch


def host_index_sequence(n: int, *, global_batch: int, seed: int, epoch: int,
                        process_index: int = 0,
                        process_count: int = 1) -> np.ndarray:
    """This host's full index order for an epoch (concatenated per-step
    slices of the global permutation) — the feed for the native prefetcher."""
    if global_batch % process_count:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{process_count} processes")
    local = global_batch // process_count
    perm = _epoch_permutation(n, seed, epoch)
    n_steps = steps_per_epoch(n, global_batch)
    # Step s gives this host rows [s*gb + pi*local, s*gb + (pi+1)*local):
    # i.e. column `process_index` of the (steps, processes, local) view.
    return (perm[:n_steps * global_batch]
            .reshape(n_steps, process_count, local)[:, process_index]
            .reshape(-1))


def train_batches(
    images: np.ndarray,
    labels: np.ndarray,
    *,
    global_batch: int,
    seed: int,
    epoch: int,
    process_index: int = 0,
    process_count: int = 1,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield this host's (images_u8, labels) slices of each global batch.

    Each yielded array has ``global_batch // process_count`` rows; the
    concatenation over hosts in process order is exactly the global batch.
    """
    if global_batch % process_count:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{process_count} processes")
    local = global_batch // process_count
    perm = _epoch_permutation(len(images), seed, epoch)
    n_steps = steps_per_epoch(len(images), global_batch)
    for s in range(n_steps):
        start = s * global_batch + process_index * local
        idx = perm[start:start + local]
        yield images[idx], labels[idx]


def eval_batches(
    images: np.ndarray,
    labels: np.ndarray,
    *,
    global_batch: int,
    process_index: int = 0,
    process_count: int = 1,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Yield (images, labels, mask) covering the eval set exactly once.

    The final batch is zero-padded; ``mask`` is 1.0 for real examples and
    0.0 for padding, so reductions weighted by mask give exact global
    metrics (unlike the reference's padded DistributedSampler eval).
    """
    if global_batch % process_count:
        raise ValueError("global eval batch not divisible by process count")
    local = global_batch // process_count
    n = len(images)
    n_steps = (n + global_batch - 1) // global_batch
    for s in range(n_steps):
        start = s * global_batch + process_index * local
        stop = min(start + local, n) if start < n else start
        count = max(0, stop - start)
        x = np.zeros((local,) + images.shape[1:], dtype=images.dtype)
        # labels may be per-example scalars OR per-token rows (packed
        # LM segment ids) — pad with whatever trailing shape they have.
        y = np.zeros((local,) + labels.shape[1:], dtype=labels.dtype)
        m = np.zeros((local,), dtype=np.float32)
        if count:
            x[:count] = images[start:stop]
            y[:count] = labels[start:stop]
            m[:count] = 1.0
        yield x, y, m
