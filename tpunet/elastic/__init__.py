"""Elastic grow/shrink training: chaos, rendezvous, and the agent.

The paper's MPI/DDP world has no failure story: one lost rank kills
the whole job and the only recovery is an epoch-0 restart (SURVEY.md
section 5). tpunet already had every piece of a better story built
separately — preemption agreement in the trainer, multi-controller
checkpoint roundtrip, step-aligned straggler alerts, crash forensics
that survive SIGKILL, the run-history store that makes a restarted
run judgeable — yet a lost host still ended the run. This package
wires them into one closed loop:

- ``chaos``      — deterministic fault injection (``--chaos`` on the
  train CLI): SIGKILL mid-step and mid-checkpoint-write, SIGTERM
  preemption with escalation, slow-host delay, transient checkpoint
  IO errors. Seeded and step-addressed, so every failure scenario is
  a reproducible test, not a war story (docs/elasticity.md grammar).
- ``rendezvous`` — filesystem rendezvous for surviving hosts:
  generation-numbered, epoch/step-stamped announcements, timeout-
  bounded gather with a clean "cannot form quorum" degradation path,
  departure markers and join requests (grow).
- ``agent``      — the per-host supervisor (``python -m
  tpunet.elastic``): launches the trainer as a child process, detects
  child death / peer loss / preemption stops, re-rendezvous with the
  survivors, and relaunches the child against the resized world with
  ``--resume`` — the mesh's data axis follows the world, FSDP state
  re-shards onto the new mesh at restore, and the run keeps its
  ``run_id`` so the metrics stream (and the PR-9 history store)
  continues across generations.
- ``events``     — the ``obs_elastic`` record kind (shrink / grow /
  restart / evict / quorum_failed / remesh / recovered) appended into
  the run's ``metrics.jsonl`` and routed through the fleet dashboard
  and the alert webhook (docs/metrics_schema.md).
"""

from __future__ import annotations

from tpunet.elastic.chaos import Chaos, ChaosSpecError
from tpunet.elastic.events import (ELASTIC_KIND, append_elastic_record,
                                   build_elastic_record)
from tpunet.elastic.rendezvous import QuorumError, Rendezvous

__all__ = [
    "Chaos", "ChaosSpecError", "ELASTIC_KIND", "QuorumError",
    "Rendezvous", "append_elastic_record", "build_elastic_record",
]
