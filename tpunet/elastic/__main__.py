"""Elastic agent CLI: ``python -m tpunet.elastic``.

One agent per host, all pointed at the same shared run/rendezvous
directories, each wrapping the SAME trainer command::

    python -m tpunet.elastic \\
        --run-dir /ckpt/run1 --rdzv-dir /ckpt/run1/rdzv \\
        --host-id $(hostname) --max-restarts 2 -- \\
        python -m tpunet.main --dataset cifar10 --epochs 20 \\
            --checkpoint-dir /ckpt/run1

The agent injects the per-generation world (``JAX_COORDINATOR_ADDRESS``
/ ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` + ``TPUNET_ELASTIC_*``)
and appends ``--resume`` from the second incarnation on; see
docs/elasticity.md for the full protocol and exit codes.
"""

from __future__ import annotations

import argparse
import socket
import sys
from typing import List, Optional

from tpunet.elastic.agent import AgentConfig, ElasticAgent


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpunet.elastic",
        description="per-host elastic training agent (supervise, "
                    "rendezvous, relaunch)")
    p.add_argument("--run-dir", required=True,
                   help="shared checkpoint/metrics directory (the "
                        "child's --checkpoint-dir)")
    p.add_argument("--rdzv-dir", required=True,
                   help="shared rendezvous directory (all hosts)")
    p.add_argument("--host-id", default=socket.gethostname(),
                   help="unique host identity (default: hostname)")
    p.add_argument("--addr", default="127.0.0.1",
                   help="this host's address for coordinator duty")
    p.add_argument("--min-hosts", type=int, default=1,
                   help="quorum floor: fewer announced hosts than "
                        "this is a QuorumError, not a smaller pod")
    p.add_argument("--max-restarts", type=int, default=1,
                   help="child failures this host absorbs before "
                        "marking itself gone (0 = any failure is "
                        "host death)")
    p.add_argument("--settle-s", type=float, default=0.5,
                   help="rendezvous stability window")
    p.add_argument("--timeout-s", type=float, default=60.0,
                   help="rendezvous gather budget before the quorum "
                        "verdict")
    p.add_argument("--dead-after-s", type=float, default=3.0,
                   help="peer heartbeat staleness => host lost")
    p.add_argument("--grace-s", type=float, default=5.0,
                   help="SIGTERM->SIGKILL grace when stopping a "
                        "wedged child")
    p.add_argument("--max-generations", type=int, default=32,
                   help="relaunch budget (runaway guard)")
    p.add_argument("--join", action="store_true",
                   help="ask a running pod to re-rendezvous and grow "
                        "onto this host before the first gather")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="-- followed by the trainer command")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_argparser().parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("usage error: trainer command required after '--'",
              file=sys.stderr)
        return 2
    agent = ElasticAgent(AgentConfig(
        run_dir=args.run_dir, rdzv_dir=args.rdzv_dir,
        host_id=args.host_id, command=command, addr=args.addr,
        min_hosts=args.min_hosts, max_restarts=args.max_restarts,
        settle_s=args.settle_s, timeout_s=args.timeout_s,
        dead_after_s=args.dead_after_s, grace_s=args.grace_s,
        max_generations=args.max_generations))
    if args.join:
        agent.rdzv.request_join()
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
