"""The per-host elastic agent: supervise, rendezvous, relaunch.

One agent runs on every host of an elastic pod (``python -m
tpunet.elastic``). It owns no jax runtime — it is a pure-stdlib
supervisor, so it survives everything the trainer can die of — and it
closes the loop the subsystems left open:

    launch trainer child against generation G's membership
      └─ child dies (SIGKILL / crash)      ──┐
      └─ a peer's agent marks gone / goes   ├─> stop wedged child,
         silent / announces G+1             │   re-rendezvous G+1,
      └─ child stops for preemption/evict ──┘   relaunch with --resume

The re-mesh itself needs no mesh surgery: generation G+1's child
boots a fresh jax world of the surviving hosts (``JAX_*`` rendezvous
env vars), the mesh's ``data`` axis follows the device count
(``MeshConfig.data = -1``), and the trainer's normal ``--resume``
path restores the last intact checkpoint onto the new mesh — FSDP
leaves re-shard to the new data axis via the restore target's
shardings, and the restored arrays are re-materialized (``jnp.copy``)
before the donated first step, which is what keeps tpucheck R1 clean
across the elastic/ -> ckpt/ -> train/ path.

Child-exit classification (markers from ``tpunet/elastic/events.py``):

- ``elastic/done``       -> every epoch finished: agent exits 0;
- ``elastic/evict.json`` -> agreed evict: the named host leaves
  (marks ``gone``, exits 0), survivors re-rendezvous;
- exit 0, no marker      -> clean preemption stop: restart;
- nonzero / signal       -> failure: restart while the per-host
  ``max_restarts`` budget lasts, else the host marks ``gone`` and
  exits 2 (host death from the pod's point of view).

Membership changes are appended to the run's ``metrics.jsonl`` as
``obs_elastic`` records by generation G+1's rank-0 agent (shrink /
grow / restart, with ``recovery_s`` = detection -> relaunch), under
the run's original ``run_id``.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpunet.elastic import events
from tpunet.elastic.rendezvous import QuorumError, Rendezvous

# Exit codes (docs/elasticity.md "Agent exit codes").
EXIT_DONE = 0          # training completed (or this host was evicted)
EXIT_RESTARTS = 2      # per-host restart budget exhausted
EXIT_QUORUM = 3        # rendezvous could not form a quorum
EXIT_GENERATIONS = 4   # generation budget exhausted (runaway guard)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class AgentConfig:
    run_dir: str               # shared checkpoint/metrics directory
    rdzv_dir: str              # shared rendezvous directory
    host_id: str
    command: List[str]         # child argv (without --resume)
    addr: str = "127.0.0.1"    # this host's address for the coordinator
    min_hosts: int = 1
    max_restarts: int = 1      # child failures this host absorbs
    settle_s: float = 0.5
    timeout_s: float = 60.0
    beat_s: float = 0.2        # heartbeat/poll period while supervising
    dead_after_s: float = 3.0  # peer heartbeat staleness => host lost
    grace_s: float = 5.0       # SIGTERM -> SIGKILL when stopping a child
    max_generations: int = 32
    env: Dict[str, Optional[str]] = field(default_factory=dict)
    # None value = remove the variable from the child environment.


class ElasticAgent:
    def __init__(self, cfg: AgentConfig):
        self.cfg = cfg
        self.rdzv = Rendezvous(
            cfg.rdzv_dir, cfg.host_id, min_hosts=cfg.min_hosts,
            settle_s=cfg.settle_s, timeout_s=cfg.timeout_s)
        self._log = print

    # -- child lifecycle -----------------------------------------------

    def _child_env(self, generation: int, world: int, rank: int,
                   coordinator: str) -> Dict[str, str]:
        env = dict(os.environ)
        for key, val in self.cfg.env.items():
            if val is None:
                env.pop(key, None)
            else:
                env[key] = val
        env["TPUNET_ELASTIC_GENERATION"] = str(generation)
        env["TPUNET_ELASTIC_WORLD"] = str(world)
        env["TPUNET_ELASTIC_RANK"] = str(rank)
        env["TPUNET_ELASTIC_HOST"] = self.cfg.host_id
        if world > 1:
            env["JAX_COORDINATOR_ADDRESS"] = coordinator
            env["JAX_NUM_PROCESSES"] = str(world)
            env["JAX_PROCESS_ID"] = str(rank)
        else:
            # A shrunk-to-one world must boot single-controller: stale
            # rendezvous vars would make jax wait for dead peers.
            for key in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                        "JAX_PROCESS_ID"):
                env.pop(key, None)
        return env

    def _launch(self, generation: int, world: int, rank: int,
                coordinator: str) -> subprocess.Popen:
        argv = list(self.cfg.command)
        if generation > 0 or events.read_run_id(self.cfg.run_dir):
            # Any prior incarnation left state: resume (keeps run_id,
            # keeps metrics.jsonl, restores the last intact
            # checkpoint; a checkpoint-less resume degrades to a
            # fresh start on the same stream).
            argv.append("--resume")
        log_dir = os.path.join(self.cfg.run_dir, "elastic", "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(
            log_dir, f"gen{generation:03d}-{self.cfg.host_id}.log")
        # Child output goes to a FILE, never a pipe: the agent drains
        # nothing, so a chatty child can never fill a pipe and wedge
        # mid-collective (the tests/_gang.py lesson).
        log_file = open(log_path, "ab")
        try:
            # The agent supervises this child for its whole life (the
            # loop below is its registry); flightrec's THREADS
            # registry does not exist in this jax-free process.
            child = subprocess.Popen(
                argv, stdout=log_file, stderr=subprocess.STDOUT,
                env=self._child_env(generation, world, rank,
                                    coordinator))
        finally:
            log_file.close()
        self._log(f"[elastic {self.cfg.host_id}] gen {generation}: "
                  f"launched pid {child.pid} rank {rank}/{world} "
                  f"(log: {log_path})")
        return child

    def _stop_child(self, child: subprocess.Popen) -> None:
        """SIGTERM (a wedged child may still flush a checkpoint from
        its writer thread), bounded grace, then SIGKILL."""
        if child.poll() is not None:
            return
        try:
            child.send_signal(signal.SIGTERM)
        except OSError:
            return
        deadline = time.monotonic() + self.cfg.grace_s
        while child.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if child.poll() is None:
            child.kill()
            child.wait()

    # -- supervision ---------------------------------------------------

    def _supervise(self, child: subprocess.Popen, generation: int,
                   hosts: List[str]) -> Tuple[str, object]:
        """Wait for the child or for a membership-change signal.
        Returns ``("exit", returncode)`` or ``("peer", why)``."""
        started = time.monotonic()
        while True:
            rc = child.poll()
            if rc is not None:
                return ("exit", rc)
            self.rdzv.heartbeat()
            if self.rdzv.latest_generation() > generation:
                return ("peer", "new_generation")
            gone = (self.rdzv.gone() & set(hosts)) - {self.cfg.host_id}
            if gone:
                return ("peer", f"host_left:{','.join(sorted(gone))}")
            if time.monotonic() - started > self.cfg.dead_after_s:
                stale = self.rdzv.stale_peers(hosts,
                                              self.cfg.dead_after_s)
                if stale:
                    # A silent peer: its agent died with its host (no
                    # gone marker) — declare it lost.
                    for host in stale:
                        self.rdzv.mark_gone(host)
                    return ("peer",
                            f"host_lost:{','.join(sorted(stale))}")
            if self.rdzv.join_requests():
                return ("peer", "join")
            time.sleep(self.cfg.beat_s)

    # -- membership records --------------------------------------------

    def _emit_change(self, *, generation: int, hosts: List[str],
                     prev_hosts: List[str], cause: str,
                     detect_t: float) -> None:
        old_w, new_w = len(prev_hosts), len(hosts)
        event = ("shrink" if new_w < old_w
                 else "grow" if new_w > old_w else "restart")
        lost = sorted(set(prev_hosts) - set(hosts))
        record = events.build_elastic_record(
            event, cause=cause, generation=generation,
            old_world=old_w, new_world=new_w, hosts=hosts,
            lost=lost or None,
            step=self._latest_ckpt_step(),
            recovery_s=time.monotonic() - detect_t)
        events.append_elastic_record(self.cfg.run_dir, record)
        self._log(f"[elastic {self.cfg.host_id}] {event}: world "
                  f"{old_w}->{new_w} gen {generation} cause={cause}")

    def _latest_ckpt_step(self) -> Optional[int]:
        """Best-effort committed-checkpoint stamp for announcements
        and records (orbax layout: ``state/<step>`` dirs; in-progress
        writes carry orbax's tmp suffix and are excluded)."""
        state = os.path.join(self.cfg.run_dir, "state")
        best = None
        try:
            names = os.listdir(state)
        except OSError:
            return None
        for name in names:
            if name.isdigit():
                best = max(best or 0, int(name))
        return best

    # -- main loop -----------------------------------------------------

    def run(self) -> int:
        cfg = self.cfg
        generation = max(self.rdzv.latest_generation(), 0)
        restarts_left = cfg.max_restarts
        prev_hosts: Optional[List[str]] = None
        cause = ""
        detect_t = time.monotonic()
        for _ in range(cfg.max_generations):
            info = {"addr": cfg.addr, "port": _free_port(),
                    "ckpt_step": self._latest_ckpt_step()}
            self.rdzv.announce(generation, info)
            self.rdzv.heartbeat()
            try:
                members = self.rdzv.gather(generation)
            except QuorumError as e:
                events.append_elastic_record(
                    cfg.run_dir, events.build_elastic_record(
                        "quorum_failed", cause=str(e),
                        generation=generation,
                        old_world=(len(prev_hosts)
                                   if prev_hosts else None)))
                self._log(f"[elastic {cfg.host_id}] {e}")
                return EXIT_QUORUM
            hosts = [h for h, _ in members]
            rank = hosts.index(cfg.host_id)
            world = len(members)
            coordinator = (f"{members[0][1].get('addr', cfg.addr)}:"
                           f"{members[0][1].get('port', 0)}")
            if rank == 0:
                if prev_hosts is not None:
                    self._emit_change(generation=generation,
                                      hosts=hosts,
                                      prev_hosts=prev_hosts,
                                      cause=cause, detect_t=detect_t)
                    events.clear_evict_marker(cfg.run_dir)
                # Clear ALL outstanding join requests, not just the
                # ones that made it into this membership: a joiner
                # that died between request_join() and announcing
                # would otherwise leave a stale request that trips
                # every generation's supervise loop into an immediate
                # re-rendezvous and churns a healthy pod to the
                # generation budget. A live-but-slow joiner self-
                # heals: its own child fails to rendezvous, its agent
                # announces the next generation, and the pod grows
                # then.
                for joiner in self.rdzv.join_requests():
                    self.rdzv.clear_join(joiner)
                events.write_agent_state(cfg.run_dir, {
                    "generation": generation, "world": world,
                    "hosts": hosts, "time": time.time()})
            child = self._launch(generation, world, rank, coordinator)
            verdict, payload = self._supervise(child, generation, hosts)
            if verdict == "peer":
                self._stop_child(child)
                why = str(payload)
                cause = why.split(":")[0]
                detect_t = time.monotonic()
                prev_hosts = hosts
                generation = max(generation + 1,
                                 self.rdzv.latest_generation())
                continue
            rc = int(payload)  # verdict == "exit"
            detect_t = time.monotonic()
            if events.is_done(cfg.run_dir):
                self._log(f"[elastic {cfg.host_id}] training complete "
                          f"(gen {generation})")
                return EXIT_DONE
            evict = events.read_evict_marker(cfg.run_dir)
            if evict is not None:
                if evict.get("host") == cfg.host_id:
                    events.append_elastic_record(
                        cfg.run_dir, events.build_elastic_record(
                            "evict",
                            cause=str(evict.get("reason", "evicted")),
                            generation=generation,
                            old_world=world, new_world=world - 1,
                            lost=[cfg.host_id],
                            detail=evict.get("detail") or None))
                    self.rdzv.mark_gone()
                    self._log(f"[elastic {cfg.host_id}] evicted "
                              f"({evict.get('reason')}); leaving pod")
                    return EXIT_DONE
                cause = "evict"
            elif rc == 0:
                cause = "preempted"
            else:
                restarts_left -= 1
                if restarts_left < 0:
                    self.rdzv.mark_gone()
                    self._log(f"[elastic {cfg.host_id}] child failed "
                              f"(rc {rc}) with no restart budget "
                              "left; leaving pod")
                    return EXIT_RESTARTS
                cause = "failed"
            prev_hosts = hosts
            generation = max(generation + 1,
                             self.rdzv.latest_generation())
        self._log(f"[elastic {cfg.host_id}] generation budget "
                  f"({cfg.max_generations}) exhausted")
        return EXIT_GENERATIONS
