"""Deterministic fault injection for elastic-training tests.

A failure story is only as good as its reproductions. This module
turns "a host died mid-epoch" / "the checkpoint write was interrupted"
/ "one replica went slow" from war stories into step-addressed,
seeded, CLI-expressible scenarios: ``--chaos SPEC`` on the train CLI
installs an injector whose hooks the trainer and the checkpointer
call at the exact points real faults strike.

Spec grammar (full reference in docs/elasticity.md)::

    spec    := event (';' event)*
    event   := kind '@' where ('=' N)? (':' key '=' value)*

    kill@step=N[:host=H]            SIGKILL entering global step N
    kill@ckpt=K[:host=H]            SIGKILL during the K-th state save,
                                    after the orbax write is in flight
                                    (a torn, uncommitted checkpoint)
    sigterm@step=N[:host=H][:again=S]
                                    SIGTERM entering step N (the spot
                                    preemption shape); again=S delivers
                                    a SECOND SIGTERM S seconds later
                                    (the escalation path)
    slow@step=N:delay=S[:steps=M][:host=H]
                                    sleep S seconds per step for M
                                    steps (default 1) starting at N —
                                    the straggler shape
    slow@prob=P:delay=S:seed=X[:host=H]
                                    seeded Bernoulli(P) per-step delay
                                    (same seed => same afflicted steps)
    ioerr@save=K[:fails=F][:host=H] the K-th state save's first F
                                    write attempts raise OSError
                                    (default 1) — drives the
                                    checkpointer's retry/backoff
    ioerr@restore=K[:fails=F][:host=H]
                                    likewise for the K-th restore

``host=H`` scopes an event to one process index (default: every
process) — a 2-process gang can lose exactly one host. Events are
one-shot except ``slow``/``ioerr`` whose counts are part of the spec.
Save/restore ordinals are 1-based and count *dispatches*, not retry
attempts, so ``ioerr@save=2:fails=2`` deterministically means "the
second checkpoint's first two attempts fail, the third succeeds".

Everything here is host-side (never traced into jit — tpucheck R3);
kills are real ``SIGKILL``s: no atexit, no flush, no checkpoint
rescue — exactly what the flight recorder's watcher must survive.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tpunet.obs import flightrec


class ChaosSpecError(ValueError):
    """A ``--chaos`` spec that does not parse; the message quotes the
    offending event and the grammar form it missed."""


_KINDS = ("kill", "sigterm", "slow", "ioerr")
_WHERES = {
    "kill": ("step", "ckpt"),
    "sigterm": ("step",),
    "slow": ("step", "prob"),
    "ioerr": ("save", "restore"),
}
_FLOAT_KEYS = ("delay", "again", "prob")
_INT_KEYS = ("host", "steps", "fails", "seed", "step", "ckpt", "save",
             "restore", "gen")


@dataclass
class _Event:
    kind: str
    where: str                     # step | ckpt | save | restore | prob
    at: Optional[float]            # step/ordinal number, or probability
    params: Dict[str, float] = field(default_factory=dict)
    fired: int = 0

    def param(self, key: str, default: float = 0.0) -> float:
        return self.params.get(key, default)

    def render(self) -> str:
        kv = "".join(f":{k}={v:g}" for k, v in sorted(self.params.items()))
        at = "" if self.at is None else f"={self.at:g}"
        return f"{self.kind}@{self.where}{at}{kv}"


def _parse_event(text: str) -> _Event:
    def bad(why: str) -> ChaosSpecError:
        return ChaosSpecError(
            f"bad chaos event {text!r}: {why} (grammar: "
            f"kind@where=N[:key=value]*, kinds {'/'.join(_KINDS)} — "
            "see docs/elasticity.md)")

    head, _, tail = text.partition(":")
    if "@" not in head:
        raise bad("missing '@'")
    kind, _, where_part = head.partition("@")
    kind = kind.strip()
    if kind not in _KINDS:
        raise bad(f"unknown kind {kind!r}")
    where, _, at_text = where_part.partition("=")
    where = where.strip()
    if where not in _WHERES[kind]:
        raise bad(f"kind {kind!r} takes @{'/@'.join(_WHERES[kind])}, "
                  f"not @{where!r}")
    at: Optional[float] = None
    if at_text:
        try:
            at = float(at_text)
        except ValueError:
            raise bad(f"non-numeric position {at_text!r}") from None
    elif where != "restore":
        raise bad(f"@{where} needs a position (e.g. @{where}=3)")
    params: Dict[str, float] = {}
    if tail:
        for pair in tail.split(":"):
            key, eq, val = pair.partition("=")
            key = key.strip()
            if not eq or key not in _FLOAT_KEYS + _INT_KEYS:
                raise bad(f"unknown or malformed key {pair!r}")
            try:
                params[key] = float(val)
            except ValueError:
                raise bad(f"non-numeric value in {pair!r}") from None
    if kind == "slow" and "delay" not in params:
        raise bad("slow needs :delay=SECONDS")
    if where == "prob":
        if at is None or not 0.0 < at <= 1.0:
            raise bad("prob must be in (0, 1]")
        if "seed" not in params:
            raise bad("slow@prob needs :seed=N (seeded => reproducible)")
    return _Event(kind=kind, where=where, at=at, params=params)


class Chaos:
    """The installed injector: parsed events + the hooks the trainer
    and checkpointer call. Injection is synchronous on the calling
    thread except the ``sigterm :again`` escalation timer, which runs
    on a registered background thread (flightrec host-thread
    registry) so the second signal lands while the trainer is busy
    with its grace-window work — the exact race it exists to test."""

    def __init__(self, events: List[_Event], *, process_index: int = 0,
                 generation: int = 0,
                 kill: Callable[[int, int], None] = os.kill,
                 sleep: Callable[[float], None] = time.sleep):
        self.events = events
        self.process_index = process_index
        self.generation = generation
        self._kill = kill
        self._sleep = sleep
        self._rngs: Dict[int, random.Random] = {}

    @classmethod
    def parse(cls, spec: str, *, process_index: int = 0,
              generation: int = 0,
              kill: Callable[[int, int], None] = os.kill,
              sleep: Callable[[float], None] = time.sleep) -> "Chaos":
        events = [_parse_event(part.strip())
                  for part in spec.split(";") if part.strip()]
        if not events:
            raise ChaosSpecError(f"empty chaos spec {spec!r}")
        return cls(events, process_index=process_index,
                   generation=generation, kill=kill, sleep=sleep)

    # -- matching ------------------------------------------------------

    def _mine(self, ev: _Event) -> bool:
        """host=H scopes to one process index; gen=G to one elastic
        generation (so a relaunched incarnation does not replay its
        predecessor's death — the same spec rides the same argv
        across generations)."""
        host = ev.params.get("host")
        if host is not None and int(host) != self.process_index:
            return False
        gen = ev.params.get("gen")
        return gen is None or int(gen) == self.generation

    def _fire_kill(self, ev: _Event, what: str) -> None:
        ev.fired += 1
        # The breadcrumb goes into the crash-durable ring FIRST: the
        # post-mortem report then says the death was injected, not
        # organic.
        flightrec.record("chaos", f"SIGKILL injected ({what})")
        self._kill(os.getpid(), signal.SIGKILL)

    def _fire_sigterm(self, ev: _Event, step: int) -> None:
        ev.fired += 1
        flightrec.record("chaos", f"SIGTERM injected step={step}")
        self._kill(os.getpid(), signal.SIGTERM)
        again = ev.param("again")
        if again > 0:
            handle = flightrec.register_thread("chaos-sigterm")

            def escalate() -> None:
                handle.beat("busy")
                self._sleep(again)
                flightrec.record("chaos", "second SIGTERM injected")
                self._kill(os.getpid(), signal.SIGTERM)
                handle.beat("idle")

            threading.Thread(target=escalate, name="chaos-sigterm",
                             daemon=True).start()

    # -- hooks ---------------------------------------------------------

    def step(self, global_step: int) -> None:
        """Called at the top of every train step (host-side)."""
        for i, ev in enumerate(self.events):
            if not self._mine(ev):
                continue
            if ev.kind == "slow" and ev.where == "prob":
                rng = self._rngs.setdefault(
                    i, random.Random(int(ev.param("seed"))))
                # One draw per step keeps the sequence step-addressed:
                # the same seed afflicts the same steps in every run.
                if rng.random() < float(ev.at or 0.0):
                    ev.fired += 1
                    flightrec.record(
                        "chaos", f"slow step={global_step}")
                    self._sleep(ev.param("delay"))
                continue
            if ev.at is None or int(ev.at) > global_step:
                continue
            if ev.kind == "slow" and ev.where == "step":
                span = int(ev.param("steps", 1.0))
                if global_step < int(ev.at) + span:
                    ev.fired += 1
                    flightrec.record(
                        "chaos", f"slow step={global_step}")
                    self._sleep(ev.param("delay"))
                continue
            if int(ev.at) != global_step or ev.fired:
                continue
            if ev.kind == "kill" and ev.where == "step":
                self._fire_kill(ev, f"step={global_step}")
            elif ev.kind == "sigterm":
                self._fire_sigterm(ev, global_step)

    def save_attempt(self, save_index: int, attempt: int) -> None:
        """Called before each state-save write attempt (``save_index``
        is the 1-based dispatch ordinal, ``attempt`` the 0-based retry
        count). Raises the injected transient ``OSError``."""
        self._io_attempt("save", save_index, attempt)

    def restore_attempt(self, restore_index: int, attempt: int) -> None:
        self._io_attempt("restore", restore_index, attempt)

    def _io_attempt(self, where: str, index: int, attempt: int) -> None:
        for ev in self.events:
            if ev.kind != "ioerr" or ev.where != where \
                    or not self._mine(ev):
                continue
            if ev.at is not None and int(ev.at) != index:
                continue
            if attempt < int(ev.param("fails", 1.0)):
                ev.fired += 1
                flightrec.record(
                    "chaos", f"ioerr {where} index={index} "
                             f"attempt={attempt}")
                raise OSError(
                    f"chaos: injected transient {where} IO error "
                    f"(index={index}, attempt={attempt})")

    def save_in_flight(self, save_index: int) -> None:
        """Called once per state save after the orbax write has been
        dispatched but before it is awaited/committed — the
        mid-checkpoint-write kill point (the checkpoint on disk is
        torn: written but never finalized)."""
        for ev in self.events:
            if ev.kind == "kill" and ev.where == "ckpt" \
                    and self._mine(ev) and not ev.fired \
                    and ev.at is not None and int(ev.at) == save_index:
                self._fire_kill(ev, f"ckpt={save_index}")

    def render(self) -> str:
        return ";".join(ev.render() for ev in self.events)


# -- process-global install (the checkpointer reaches the injector
# -- without threading it through every constructor) -------------------

_CURRENT: Optional[Chaos] = None


def install(spec: str, *, process_index: int = 0) -> Chaos:
    """Parse and arm the process-global injector (``--chaos``). The
    elastic generation is read from the agent-exported env var, so
    ``gen=G`` events address one incarnation of the run."""
    global _CURRENT
    try:
        generation = int(os.environ.get("TPUNET_ELASTIC_GENERATION",
                                        "0"))
    except ValueError:
        generation = 0
    _CURRENT = Chaos.parse(spec, process_index=process_index,
                           generation=generation)
    flightrec.record("chaos", f"armed {_CURRENT.render()} "
                              f"host={process_index} "
                              f"gen={generation}")
    return _CURRENT


def current() -> Optional[Chaos]:
    return _CURRENT


def clear() -> None:
    global _CURRENT
    _CURRENT = None
