"""``obs_elastic`` records and the agent/trainer marker files.

Elasticity events are part of the run's observable history: a shrink
that silently drops half the pod's throughput would poison every
cross-run comparison the PR-9 history store makes. So every
membership change is one ``obs_elastic`` record (schema:
docs/metrics_schema.md) carrying the cause, the old/new world and
mesh, the restore stamp, and the recovery wall-clock — appended to
the SAME ``metrics.jsonl`` under the SAME ``run_id`` as the training
records it interrupts, so the stream stays one judgeable run.

Two writers exist on purpose:

- the **agent** (no jax, no registry) appends via
  ``append_elastic_record`` — identity-stamped from the persisted
  ``<run_dir>/run_id`` file, one atomic appended line;
- the **trainer** emits through ``Registry.emit`` (identity stamp,
  jsonl sink, live exporters, webhook) for the events it witnesses
  from inside: ``evict_requested`` when the watchdog hands it a
  straggler verdict, ``recovered`` once it has restored onto the new
  mesh.

Marker files are the agent/trainer contract (all under the shared run
directory, all single atomic writes):

- ``elastic/done``          — the trainer completed every epoch;
- ``elastic/evict.json``    — an agreed evict: names the process
  index/host being evicted so each agent knows whether it is the one
  leaving;
- ``elastic/state.json``    — the agent's generation bookkeeping
  (informational, refreshed per generation).
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Dict, List, Optional

ELASTIC_KIND = "obs_elastic"

#: Event vocabulary (docs/metrics_schema.md `obs_elastic`).
EVENTS = ("shrink", "grow", "restart", "evict", "evict_requested",
          "quorum_failed", "recovered")

_MARKER_DIR = "elastic"
_DONE = "done"
_EVICT = "evict.json"


def build_elastic_record(event: str, *, cause: str = "",
                         generation: Optional[int] = None,
                         old_world: Optional[int] = None,
                         new_world: Optional[int] = None,
                         old_mesh: Optional[Dict[str, int]] = None,
                         new_mesh: Optional[Dict[str, int]] = None,
                         hosts: Optional[List[str]] = None,
                         lost: Optional[List[str]] = None,
                         epoch: Optional[int] = None,
                         step: Optional[int] = None,
                         recovery_s: Optional[float] = None,
                         detail: Optional[dict] = None) -> dict:
    """One ``obs_elastic`` record body (no ``kind``/identity — the
    emitter stamps those)."""
    if event not in EVENTS:
        raise ValueError(f"unknown elastic event {event!r} "
                         f"(expected one of {EVENTS})")
    record: dict = {
        "event": event,
        # quorum failure is the one elastic event that means the run
        # is STOPPED, not reshaped — page it accordingly.
        "severity": "fatal" if event == "quorum_failed" else "warn",
    }
    if cause:
        record["cause"] = cause
    for key, val in (("generation", generation),
                     ("old_world", old_world), ("new_world", new_world),
                     ("old_mesh", old_mesh), ("new_mesh", new_mesh),
                     ("hosts", hosts), ("lost", lost),
                     ("epoch", epoch), ("step", step)):
        if val is not None:
            record[key] = val
    if recovery_s is not None:
        record["recovery_s"] = round(float(recovery_s), 3)
    if detail:
        record["detail"] = detail
    return record


def read_run_id(run_dir: str) -> str:
    """The persisted run identity (``<run_dir>/run_id``), or '' before
    the first trainer incarnation has written it."""
    path = os.path.join(run_dir, "run_id")
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return ""


def append_elastic_record(run_dir: str, record: dict) -> dict:
    """Agent-side emission: stamp kind + identity and append one line
    to the run's ``metrics.jsonl``. Safe while no trainer runs (the
    agent only writes between generations) and append-atomic like
    ``MetricsLogger.log``."""
    stamped = {
        "kind": ELASTIC_KIND,
        "run_id": read_run_id(run_dir),
        "process_index": 0,
        "host": socket.gethostname(),
        "time": round(time.time(), 3),
    }
    stamped.update(record)
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, "metrics.jsonl")
    with open(path, "a") as f:
        f.write(json.dumps(stamped) + "\n")
    return stamped


# -- marker files ------------------------------------------------------


def _marker_path(run_dir: str, name: str) -> str:
    return os.path.join(run_dir, _MARKER_DIR, name)


def _write_marker(run_dir: str, name: str, payload: dict) -> None:
    os.makedirs(os.path.join(run_dir, _MARKER_DIR), exist_ok=True)
    path = _marker_path(run_dir, name)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def mark_done(run_dir: str) -> None:
    """Trainer: every configured epoch completed — agents stop
    relaunching."""
    _write_marker(run_dir, _DONE, {"time": time.time()})


def is_done(run_dir: str) -> bool:
    return os.path.isfile(_marker_path(run_dir, _DONE))


def write_evict_marker(run_dir: str, *, process_index: int, host: str,
                       reason: str, detail: Optional[dict] = None
                       ) -> bool:
    """Claim the evict slot for this replica — FIRST claim wins.

    In lockstep data parallelism a straggler slows every replica's
    measured step time, so several hosts' watchdogs may fire
    near-simultaneously; the marker is therefore an exclusive claim
    (atomic link-into-place): the first claimer is the replica the
    pod evicts, later claimers defer (returns False). The true
    straggler usually claims first — its delay is measured directly,
    the others' only after dispatch backpressure — but the guarantee
    is liveness (exactly one replica leaves), not perfect
    attribution (docs/elasticity.md)."""
    os.makedirs(os.path.join(run_dir, _MARKER_DIR), exist_ok=True)
    path = _marker_path(run_dir, _EVICT)
    tmp = f"{path}.claim.{host}.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"process_index": int(process_index), "host": host,
                   "reason": reason, "detail": detail or {},
                   "time": time.time()}, f)
    try:
        os.link(tmp, path)   # atomic: fails iff a claim already won
        return True
    except FileExistsError:
        return False
    finally:
        os.unlink(tmp)


def read_evict_marker(run_dir: str) -> Optional[dict]:
    try:
        with open(_marker_path(run_dir, _EVICT)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def clear_evict_marker(run_dir: str) -> None:
    try:
        os.unlink(_marker_path(run_dir, _EVICT))
    except OSError:
        pass


def write_agent_state(run_dir: str, payload: dict) -> None:
    """Informational generation bookkeeping (rendered by humans and
    read back by the resumed trainer for its elastic gauges)."""
    _write_marker(run_dir, "state.json", payload)


def read_agent_state(run_dir: str) -> Optional[dict]:
    try:
        with open(_marker_path(run_dir, "state.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def write_mesh(run_dir: str, mesh: Dict[str, int]) -> None:
    """Trainer (coordinator): persist this incarnation's mesh shape so
    the NEXT incarnation's ``recovered`` record can report
    ``old_mesh`` -> ``new_mesh`` across the re-mesh."""
    _write_marker(run_dir, "mesh.json", dict(mesh))


def read_mesh(run_dir: str) -> Optional[Dict[str, int]]:
    try:
        with open(_marker_path(run_dir, "mesh.json")) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def agent_host() -> str:
    """This process's elastic host identity (the agent exports it);
    hostname when not under an agent."""
    return os.environ.get("TPUNET_ELASTIC_HOST", socket.gethostname())


def agent_env() -> Optional[dict]:
    """The elastic environment the agent exports to its child, parsed
    from this process's env (None when not running under an agent):
    ``{"generation": int, "world": int, "rank": int}``."""
    gen = os.environ.get("TPUNET_ELASTIC_GENERATION")
    if gen is None:
        return None
    try:
        return {"generation": int(gen),
                "world": int(os.environ.get("TPUNET_ELASTIC_WORLD", "1")),
                "rank": int(os.environ.get("TPUNET_ELASTIC_RANK", "0"))}
    except ValueError:
        return None
