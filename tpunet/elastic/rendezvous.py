"""Filesystem rendezvous for elastic host membership.

Surviving hosts of a shrinking (or growing) pod need to agree on the
next generation's membership without any surviving coordinator — the
coordinator may be the host that died. The agreement medium here is a
shared directory (tests: a tmpdir; a real pod: NFS/GCS-fuse — the
same place the checkpoints already live), because it is the one
dependency the checkpoint path already requires and it survives any
subset of hosts dying.

Protocol (docs/elasticity.md "Rendezvous protocol"):

- membership is **generation-numbered**: generation ``G``'s
  announcements live under ``gen-<G>/<host>.json``, each stamped with
  the host's latest known checkpoint ``epoch``/``step``, its pid, a
  coordinator-candidate ``addr:port``, and a wall-clock time;
- ``gather(G)`` waits until the announced set has been **stable for
  ``settle_s``** (no arrivals), then returns it sorted by host id —
  rank and coordinator assignment are therefore deterministic across
  hosts with no messages exchanged;
- the gather is **timeout-bounded**: past ``timeout_s``, fewer than
  ``min_hosts`` announcements is a ``QuorumError`` (the clean
  "cannot form quorum" degradation — the agent reports it and exits
  nonzero instead of spinning);
- departure is a ``gone/<host>`` marker (evicted or restart-budget-
  exhausted hosts write it; gone hosts are excluded from every later
  generation) — a host that dies *without* marking (SIGKILL takes the
  agent too) is detected by its **heartbeat file** going stale
  (``hb/<host>``, touched by the agent's supervise loop);
- a new host joins by writing ``join/<host>`` (grow): running agents
  poll ``join_requests()`` and trigger the next generation, where the
  joiner announces like everyone else.

Everything is write-once-per-path or atomic-rename, so torn reads are
impossible and retries are idempotent.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, List, Optional, Set, Tuple


class QuorumError(RuntimeError):
    """Rendezvous timed out below ``min_hosts`` — the pod cannot form
    a quorum and the caller must degrade cleanly, not spin."""


class Rendezvous:
    POLL_S = 0.05

    def __init__(self, directory: str, host_id: str, *,
                 min_hosts: int = 1, settle_s: float = 1.0,
                 timeout_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if not host_id or "/" in host_id:
            raise ValueError(f"bad host id {host_id!r}")
        if min_hosts < 1:
            raise ValueError(f"min_hosts must be >= 1, got {min_hosts}")
        self.directory = os.path.abspath(directory)
        self.host_id = host_id
        self.min_hosts = min_hosts
        self.settle_s = settle_s
        self.timeout_s = timeout_s
        self._clock = clock
        self._sleep = sleep
        for sub in ("gone", "hb", "join"):
            os.makedirs(os.path.join(self.directory, sub), exist_ok=True)

    # -- paths ---------------------------------------------------------

    def _gen_dir(self, generation: int) -> str:
        return os.path.join(self.directory, f"gen-{generation:06d}")

    def _write_json(self, path: str, payload: dict) -> None:
        tmp = f"{path}.tmp.{self.host_id}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)

    # -- announcements -------------------------------------------------

    def announce(self, generation: int, info: Optional[dict] = None
                 ) -> None:
        """Publish this host's membership in ``generation`` (idempotent
        — re-announcing overwrites with fresher stamps)."""
        gen_dir = self._gen_dir(generation)
        os.makedirs(gen_dir, exist_ok=True)
        payload = {"host": self.host_id, "pid": os.getpid(),
                   "time": time.time()}
        payload.update(info or {})
        self._write_json(os.path.join(gen_dir, f"{self.host_id}.json"),
                         payload)

    def members(self, generation: int) -> Dict[str, dict]:
        """Announced (and not departed) hosts of ``generation``."""
        gen_dir = self._gen_dir(generation)
        out: Dict[str, dict] = {}
        gone = self.gone()
        try:
            names = os.listdir(gen_dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            host = name[:-len(".json")]
            if host in gone:
                continue
            try:
                with open(os.path.join(gen_dir, name)) as f:
                    out[host] = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # torn concurrent write: next poll sees it
        return out

    def latest_generation(self) -> int:
        """Highest generation any host has announced into (-1: none).
        The trigger signal: an agent seeing a generation beyond its
        own knows a peer has declared a membership change."""
        latest = -1
        try:
            names = os.listdir(self.directory)
        except OSError:
            return latest
        for name in names:
            if name.startswith("gen-"):
                try:
                    latest = max(latest, int(name[4:]))
                except ValueError:
                    continue
        return latest

    # -- gather --------------------------------------------------------

    def gather(self, generation: int) -> List[Tuple[str, dict]]:
        """Wait for generation ``G``'s membership to stabilize and
        return it sorted by host id (rank order). Raises
        ``QuorumError`` on timeout below ``min_hosts``."""
        deadline = self._clock() + self.timeout_s
        seen: Set[str] = set()
        stable_since = self._clock()
        while True:
            members = self.members(generation)
            hosts = set(members)
            now = self._clock()
            if hosts != seen:
                seen = hosts
                stable_since = now
            if (self.host_id in hosts
                    and len(hosts) >= self.min_hosts
                    and now - stable_since >= self.settle_s):
                return sorted(members.items())
            if now >= deadline:
                if self.host_id in hosts and len(hosts) >= self.min_hosts:
                    return sorted(members.items())
                raise QuorumError(
                    f"rendezvous generation {generation}: "
                    f"{len(hosts)} host(s) announced "
                    f"({sorted(hosts)}) after {self.timeout_s:.1f}s, "
                    f"need >= {self.min_hosts} — cannot form quorum")
            self._sleep(self.POLL_S)

    # -- departure / liveness ------------------------------------------

    def mark_gone(self, host: Optional[str] = None) -> None:
        """Record a departed host (self by default): excluded from
        every current and future generation's membership."""
        path = os.path.join(self.directory, "gone", host or self.host_id)
        with open(path, "w") as f:
            f.write(f"{time.time()}\n")

    def gone(self) -> Set[str]:
        try:
            return set(os.listdir(os.path.join(self.directory, "gone")))
        except OSError:
            return set()

    def heartbeat(self) -> None:
        """Touch this host's liveness file (agent supervise loop)."""
        path = os.path.join(self.directory, "hb", self.host_id)
        with open(path, "w") as f:
            f.write(f"{time.time()}\n")
        # mtime is the signal; the wall-clock content is for humans.

    def stale_peers(self, peers: List[str], dead_after_s: float
                    ) -> Set[str]:
        """Peers (excluding self) whose heartbeat file is absent or
        older than ``dead_after_s`` — the SIGKILLed-agent detection
        path (a gracefully leaving host marks ``gone`` instead and is
        detected faster)."""
        stale: Set[str] = set()
        now = time.time()
        for host in peers:
            if host == self.host_id:
                continue
            path = os.path.join(self.directory, "hb", host)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                stale.add(host)
                continue
            if age > dead_after_s:
                stale.add(host)
        return stale

    # -- grow ----------------------------------------------------------

    def request_join(self) -> None:
        """A new host asks the running pod to re-rendezvous (grow)."""
        path = os.path.join(self.directory, "join", self.host_id)
        with open(path, "w") as f:
            f.write(f"{time.time()}\n")

    def join_requests(self) -> Set[str]:
        try:
            joins = set(os.listdir(os.path.join(self.directory, "join")))
        except OSError:
            return set()
        return joins - self.gone()

    def clear_join(self, host: str) -> None:
        try:
            os.unlink(os.path.join(self.directory, "join", host))
        except OSError:
            pass
