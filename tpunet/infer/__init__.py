from tpunet.infer.predict import Predictor, PredictionResult  # noqa: F401
from tpunet.infer.generate import generate_text, load_lm  # noqa: F401
