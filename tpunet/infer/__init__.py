from tpunet.infer.predict import Predictor, PredictionResult  # noqa: F401
