"""Web serving app (Gradio) for the trained classifier.

Parity with the reference's Gradio app (source in GROUP03.pdf pp.22-23,
not a repo file): Image input -> top-3 label output, served on
0.0.0.0:7861. Differences by design: the forward pass is a jitted XLA
program on TPU (no CUDA), and preprocessing reuses the training
normalization stats — the reference app normalized with CIFAR-10 stats
while training used ImageNet stats, a train/serve skew bug we do not
replicate.

Gradio is an optional dependency; import is gated so the rest of the
framework never requires it.

This app is the REFERENCE-PARITY demo: single-request, one forward per
call. Production traffic goes through ``python -m tpunet.serve``
(tpunet/serve/, docs/serving.md) — continuous batching, backpressure,
SLO metrics.
"""

from __future__ import annotations

from typing import Optional

from tpunet.infer.predict import Predictor


def make_classify(predictor: Predictor):
    """The serving function the web UI calls: PIL image (or None) ->
    {class name: probability} dict, the input format of gr.Label (which
    renders the top-3 — reference GROUP03.pdf pp.22-23). Module-level so
    it is testable without gradio installed."""

    def classify(img):
        if img is None:
            return {}
        probs = predictor.predict_probs(img)
        return {name: float(p)
                for name, p in zip(predictor.class_names, probs)}

    return classify


def build_interface(predictor: Optional[Predictor] = None,
                    checkpoint_dir: str = "checkpoints"):
    try:
        import gradio as gr
    except ImportError as e:
        raise ImportError(
            "gradio is not installed; `pip install gradio` to serve the "
            "web app, or use tpunet.infer.Predictor directly") from e

    predictor = predictor or Predictor(checkpoint_dir=checkpoint_dir)
    classify = make_classify(predictor)

    return gr.Interface(
        fn=classify,
        inputs=gr.Image(type="pil", label="Input image"),
        outputs=gr.Label(num_top_classes=3, label="Prediction"),
        title="tpunet CIFAR-10 classifier (MobileNetV2 on TPU)",
        description="Top-3 classes with confidences; TPU-jitted forward.",
    )


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(description="tpunet web serving app")
    p.add_argument("--checkpoint-dir", default="checkpoints")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7861)  # reference port
    args = p.parse_args(argv)
    demo = build_interface(checkpoint_dir=args.checkpoint_dir)
    demo.launch(server_name=args.host, server_port=args.port)


if __name__ == "__main__":
    main()
