"""Text generation CLI for the LM family.

The reference serves its vision model through a predict helper and a
Gradio app (cifar10_serial_mobilenet_224.py:159-188, GROUP03.pdf
pp.22-23); this is the LM family's serving analogue: load the best
checkpoint, prefill the prompt, and decode autoregressively through the
KV-cache incremental path (tpunet.models.lm.generate — one compiled
single-token program, O(L) per token). Byte-level checkpoints
(--dataset text_lm training) round-trip UTF-8 text; other vocabs print
token ids.

    python -m tpunet.infer.generate --checkpoint-dir ckpt \
        --prompt "The " --tokens 256 --temperature 0.8
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import numpy as np

from tpunet.ckpt import Checkpointer
from tpunet.config import CheckpointConfig, ModelConfig
from tpunet.models import create_model, init_variables
from tpunet.models.lm import generate


def load_lm(model_cfg: ModelConfig,
            checkpoint_dir: Optional[str] = None,
            variables: Optional[dict] = None,
            mesh=None, train_pipe: int = 0) -> Tuple[object, dict]:
    """Build the LM and load its best-checkpoint params (sequence-
    parallel attention configs swap to dense, same function — mirrors
    infer.Predictor). Pipeline-trained checkpoints (name 'lm_pp')
    restore in their stacked layout and are unstacked into the
    TransformerLM tree, which owns the KV-cache decode path — train
    pipelined, serve incrementally.

    ``mesh`` enables TENSOR-PARALLEL serving (a model too big for one
    chip's HBM serves from a mesh 'model' axis): the unstacked params
    are placed with the Megatron path-rule shardings
    (tpunet/parallel/tp.py — qkv/fc1 column-, out/fc2 row-parallel;
    embed/LN replicated), so each device holds 1/N of every block
    weight and GSPMD inserts the decode collectives. 'lm' checkpoints
    restore DIRECTLY into the shardings (the Orbax template is built
    sharded from eval_shape — no single-device materialization, so a
    model that only fits sharded loads); 'lm_pp' checkpoints restore
    in their stacked layout and pass through a transient full-size
    unstacking before sharding (their training shard axis is 'pipe',
    not 'model' — a stacked-sharded restore is future work). Pass the
    same mesh to ``generate(..., mesh=...)`` so the KV cache shards
    its head dim to match."""
    if model_cfg.name not in ("lm", "lm_pp"):
        raise ValueError(f"generation needs the 'lm' (or 'lm_pp') "
                         f"model, got {model_cfg.name!r}")
    if model_cfg.attention in ("ring", "ulysses"):
        model_cfg = dataclasses.replace(model_cfg, attention="dense")
    is_pp = model_cfg.name == "lm_pp"
    restore_cfg = model_cfg
    model_cfg = dataclasses.replace(model_cfg, name="lm")
    tp = mesh is not None and mesh.shape.get("model", 1) > 1
    if tp:
        from tpunet.parallel.tp import rules_for, tree_shardings
        if model_cfg.vit_heads % mesh.shape["model"]:
            raise ValueError(
                f"--vit-heads {model_cfg.vit_heads} not divisible by "
                f"the mesh 'model' axis ({mesh.shape['model']}) — "
                "TP serving shards attention by head")
    model = create_model(model_cfg)
    sharded = False
    if variables is None:
        if tp and not is_pp and checkpoint_dir:
            # Sharded restore: template zeros laid out per the TP rules
            # from eval_shape alone, so the full tree never lands on
            # one device.
            import jax.numpy as jnp
            dummy = jnp.zeros((1, min(16, model_cfg.max_seq_len)),
                              jnp.int32)
            shapes = jax.eval_shape(
                lambda: model.init({"params": jax.random.PRNGKey(0)},
                                   dummy, train=False))
            sh = tree_shardings(shapes["params"], mesh,
                                rules_for(model_cfg, mesh))
            template = jax.tree_util.tree_map(
                lambda s, d: jnp.zeros(s.shape, s.dtype, device=d),
                shapes["params"], sh)
            variables = {"params": template}
            sharded = True
        else:
            restore_model = (create_model(restore_cfg) if is_pp
                             else model)
            variables = init_variables(
                restore_model, jax.random.PRNGKey(0),
                seq_len=min(16, model_cfg.max_seq_len))
        if checkpoint_dir:
            ckpt = Checkpointer(CheckpointConfig(directory=checkpoint_dir))
            best = ckpt.restore_best({"params": variables["params"],
                                      "batch_stats": {}})
            if best is None:
                raise FileNotFoundError(
                    f"no best checkpoint under {checkpoint_dir!r}")
            variables = {"params": best["params"]}
    if is_pp and "blocks_qkv_k" in variables["params"]:
        # Stacked pipeline layout (restored above, or passed in directly
        # by an in-process caller): unstack into the TransformerLM tree.
        # ``train_pipe`` > 0 marks an INTERLEAVED-schedule checkpoint,
        # whose stacks are chunk-permuted: pass the training run's
        # pipe-axis size (the checkpoint's cfg.pp_virtual gives v).
        # When the checkpoint carries the best_meta.json sidecar
        # (tpunet/ckpt/orbax_io.py save_best), the layout comes from
        # THERE — no operator-remembered flags needed; an explicit
        # --train-pipe still overrides.
        from tpunet.models.lm_pp import to_transformer_lm_params
        virtual = restore_cfg.pp_virtual
        if not train_pipe and checkpoint_dir:
            meta = Checkpointer(
                CheckpointConfig(directory=checkpoint_dir)).best_meta()
            if meta and meta.get("pp_layout_pipe", 0):
                train_pipe = int(meta["pp_layout_pipe"])
                virtual = int(meta["pp_layout_virtual"])
        kw = ({"pipe": train_pipe, "virtual": virtual}
              if train_pipe else {})
        variables = {"params":
                     to_transformer_lm_params(variables["params"], **kw)}
    params = variables["params"]
    if tp and not sharded:
        params = jax.device_put(
            params, tree_shardings(params, mesh,
                                   rules_for(model_cfg, mesh)))
    return model, {"params": params}


def generate_text(model, variables, prompt: str, n_new: int,
                  temperature: float = 0.0, top_k: int = 0,
                  top_p: float = 0.0, seed: int = 0, mesh=None) -> str:
    """Byte-level helper: UTF-8 prompt in, UTF-8 continuation out."""
    toks = np.frombuffer(prompt.encode("utf-8"), np.uint8)
    if toks.size == 0:
        raise ValueError("prompt must be non-empty")
    out = generate(model, variables, toks[None].astype(np.int32), n_new,
                   temperature=temperature, top_k=top_k, top_p=top_p,
                   rng=jax.random.PRNGKey(seed), mesh=mesh)
    new = np.asarray(out)[0, toks.size:]
    return bytes(np.clip(new, 0, 255).astype(np.uint8)).decode(
        "utf-8", errors="replace")


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="tpunet LM text generation")
    p.add_argument("--checkpoint-dir", default="checkpoints")
    p.add_argument("--prompt", default="The ")
    p.add_argument("--tokens", type=int, default=128,
                   help="number of new tokens to generate")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 samples softmax(logits/T)")
    p.add_argument("--top-k", type=int, default=0,
                   help="truncate sampling to the k most-likely tokens "
                        "(0 = off)")
    p.add_argument("--top-p", type=float, default=0.0,
                   help="nucleus sampling: smallest cumulative-"
                        "probability mass to sample from (0 = off)")
    p.add_argument("--seed", type=int, default=0)
    # Architecture of the trained checkpoint (must match training).
    p.add_argument("--model", choices=("lm", "lm_pp"), default="lm",
                   help="lm_pp: a pipeline-trained checkpoint, unstacked "
                        "into the incremental-decode model at load")
    p.add_argument("--vit-hidden", type=int, default=192)
    p.add_argument("--vit-depth", type=int, default=6)
    p.add_argument("--vit-heads", type=int, default=3)
    p.add_argument("--vocab-size", type=int, default=256)
    p.add_argument("--max-seq-len", type=int, default=1024)
    p.add_argument("--moe-experts", type=int, default=0,
                   help="experts per MoE block of the trained "
                        "checkpoint (0 = dense MLPs)")
    p.add_argument("--moe-every", type=int, default=2)
    p.add_argument("--moe-top-k", type=int, default=2)
    p.add_argument("--moe-capacity-factor", type=float, default=1.25)
    p.add_argument("--mesh-model", type=int, default=0,
                   help="tensor-parallel serving: shard block weights "
                        "(and the KV cache's head dim) over N devices "
                        "via the Megatron path rules — for checkpoints "
                        "too big for one chip's HBM (0 = single-chip)")
    p.add_argument("--train-pipe", type=int, default=0,
                   help="for --model lm_pp checkpoints trained with "
                        "--pp-schedule interleaved: the training "
                        "run's --mesh-pipe (the stacks are chunk-"
                        "permuted; 0 = gpipe/1f1b checkpoint)")
    p.add_argument("--pp-virtual", type=int, default=2,
                   help="--pp-virtual of the interleaved training run "
                        "(ignored unless --train-pipe > 0)")
    p.add_argument("--prompt-format", choices=("auto", "bytes", "ids"),
                   default="auto",
                   help="how to read --prompt: 'bytes' = UTF-8 text "
                        "(byte-level --dataset text_lm checkpoints), "
                        "'ids' = space-separated token ids; 'auto' "
                        "picks bytes iff --vocab-size is 256")
    args = p.parse_args(argv)
    byte_prompt = (args.vocab_size == 256
                   if args.prompt_format == "auto"
                   else args.prompt_format == "bytes")
    if byte_prompt and args.vocab_size != 256:
        # generate_text round-trips tokens as raw bytes; any other vocab
        # would silently clip sampled ids into [0, 255].
        raise SystemExit(f"--prompt-format bytes needs vocab-size 256 "
                         f"(got {args.vocab_size})")

    if (args.top_k or args.top_p) and args.temperature <= 0:
        raise SystemExit("--top-k/--top-p filter SAMPLING; set "
                         "--temperature > 0 (temperature 0 is greedy "
                         "decoding and would silently ignore them)")

    cfg = ModelConfig(name=args.model, vit_hidden=args.vit_hidden,
                      vit_depth=args.vit_depth, vit_heads=args.vit_heads,
                      vocab_size=args.vocab_size,
                      max_seq_len=args.max_seq_len, dropout_rate=0.0,
                      moe_experts=args.moe_experts,
                      moe_every=args.moe_every,
                      moe_top_k=args.moe_top_k,
                      moe_capacity_factor=args.moe_capacity_factor,
                      pp_virtual=args.pp_virtual)
    if byte_prompt:
        # Byte-level checkpoint (--dataset text_lm): the prompt IS text.
        prompt_len = len(args.prompt.encode("utf-8"))
        if prompt_len == 0:
            raise SystemExit("--prompt must be non-empty")
    else:
        # The prompt is space-separated token ids.
        try:
            prompt_toks = [int(t) for t in args.prompt.split()]
        except ValueError:
            raise SystemExit(
                f"--prompt-format ids takes the prompt as space-"
                f"separated token ids, e.g. --prompt '5 7 3'; got "
                f"{args.prompt!r} (use --prompt-format bytes for text)")
        if not prompt_toks:
            raise SystemExit("--prompt must contain at least one token id")
        bad = [t for t in prompt_toks if not 0 <= t < args.vocab_size]
        if bad:
            raise SystemExit(f"prompt token(s) {bad} outside "
                             f"[0, {args.vocab_size})")
        prompt_len = len(prompt_toks)
    if prompt_len + args.tokens > cfg.max_seq_len:
        raise SystemExit(f"prompt+tokens = {prompt_len + args.tokens} "
                         f"exceeds --max-seq-len {cfg.max_seq_len}")
    mesh = None
    if args.mesh_model > 1:
        from tpunet.config import MeshConfig
        from tpunet.parallel import make_mesh
        mesh = make_mesh(MeshConfig(data=1, model=args.mesh_model))
    model, variables = load_lm(cfg, checkpoint_dir=args.checkpoint_dir,
                               mesh=mesh, train_pipe=args.train_pipe)
    if byte_prompt:
        text = generate_text(model, variables, args.prompt, args.tokens,
                             temperature=args.temperature,
                             top_k=args.top_k, top_p=args.top_p,
                             seed=args.seed, mesh=mesh)
        print(args.prompt + text)
    else:
        toks = np.asarray(prompt_toks, np.int32)[None]
        out = generate(model, variables, toks, args.tokens,
                       temperature=args.temperature, top_k=args.top_k,
                       top_p=args.top_p,
                       rng=jax.random.PRNGKey(args.seed), mesh=mesh)
        print(" ".join(str(t) for t in np.asarray(out)[0]))


if __name__ == "__main__":
    main()
