"""Top-k inference with a confidence threshold.

Parity with the reference's predict_cifar10_image()
(cifar10_serial_mobilenet_224.py:159-188): image -> test transform
(Resize(image_size) + ImageNet normalize) -> softmax -> top-k (default
k=3) -> if the best probability is below conf_threshold (default 0.5) the
prediction is flagged "uncertain". The forward pass is jitted once and
reused across requests.

The reference's Gradio app normalized with CIFAR-10 stats while training
used ImageNet stats (train/serve skew, GROUP03.pdf p.22); here inference
always reuses the training DataConfig stats, fixing that bug by
construction.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpunet.ckpt import Checkpointer
from tpunet.config import (CIFAR10_CLASSES, CheckpointConfig, DataConfig,
                           ModelConfig)
from tpunet.models import create_model, init_variables


@dataclasses.dataclass
class PredictionResult:
    predicted: str               # class name, or "uncertain"
    confidence: float
    uncertain: bool
    topk: List[Tuple[str, float]]


class Predictor:
    """Loads (or receives) trained variables and serves top-k predictions."""

    def __init__(self,
                 model_cfg: Optional[ModelConfig] = None,
                 data_cfg: Optional[DataConfig] = None,
                 variables: Optional[dict] = None,
                 checkpoint_dir: Optional[str] = None,
                 class_names: Sequence[str] = CIFAR10_CLASSES):
        self.model_cfg = model_cfg or ModelConfig()
        if self.model_cfg.attention in ("ring", "ulysses"):
            # Serving is single-chip; the sequence-parallel cores need a
            # seq mesh but compute the same function as dense — swap.
            self.model_cfg = dataclasses.replace(self.model_cfg,
                                                 attention="dense")
        self.data_cfg = data_cfg or DataConfig()
        self.class_names = tuple(class_names)
        self.model = create_model(self.model_cfg)
        if variables is None:
            variables = init_variables(self.model, jax.random.PRNGKey(0),
                                       image_size=self.data_cfg.image_size)
            if checkpoint_dir:
                ckpt = Checkpointer(CheckpointConfig(directory=checkpoint_dir))
                best = ckpt.restore_best({
                    "params": variables["params"],
                    "batch_stats": variables.get("batch_stats", {})})
                if best is None:
                    raise FileNotFoundError(
                        f"no best checkpoint under {checkpoint_dir!r}")
                variables = best
        self.variables = {"params": variables["params"],
                          "batch_stats": variables.get("batch_stats", {})}
        size = self.data_cfg.image_size
        mean = jnp.asarray(self.data_cfg.mean)
        std = jnp.asarray(self.data_cfg.std)

        def forward(variables, image_u8):
            x = image_u8.astype(jnp.float32) / 255.0
            x = jax.image.resize(x, (size, size, 3), method="bilinear")
            x = (x - mean) / std
            logits = self.model.apply(variables, x[None], train=False)
            return jax.nn.softmax(logits[0])

        self._forward = jax.jit(forward)

    def predict_probs(self, image) -> np.ndarray:
        """image: (H, W, 3) uint8 array or PIL.Image; returns class probs."""
        if hasattr(image, "convert"):      # PIL image
            image = np.asarray(image.convert("RGB"))
        image = np.asarray(image)
        if image.dtype != np.uint8:
            image = np.clip(image * 255 if image.max() <= 1.0 else image,
                            0, 255).astype(np.uint8)
        return np.asarray(self._forward(self.variables, jnp.asarray(image)))

    def predict(self, image, topk: int = 3,
                conf_threshold: float = 0.5) -> PredictionResult:
        probs = self.predict_probs(image)
        order = np.argsort(probs)[::-1][:topk]
        top = [(self.class_names[i], float(probs[i])) for i in order]
        best_name, best_conf = top[0]
        uncertain = best_conf < conf_threshold
        return PredictionResult(
            predicted="uncertain" if uncertain else best_name,
            confidence=best_conf,
            uncertain=uncertain,
            topk=top,
        )


def main(argv=None):
    import argparse

    from PIL import Image

    p = argparse.ArgumentParser(description="tpunet top-k inference")
    p.add_argument("image", help="path to an image file")
    p.add_argument("--checkpoint-dir", default="checkpoints")
    p.add_argument("--topk", type=int, default=3)
    p.add_argument("--conf-threshold", type=float, default=0.5)
    args = p.parse_args(argv)
    pred = Predictor(checkpoint_dir=args.checkpoint_dir)
    result = pred.predict(Image.open(args.image), topk=args.topk,
                          conf_threshold=args.conf_threshold)
    print(f"Predicted: {result.predicted} "
          f"(confidence {result.confidence:.4f})")
    for name, prob in result.topk:
        print(f"  {name}: {prob:.4f}")


if __name__ == "__main__":
    main()
