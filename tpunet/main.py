#!/usr/bin/env python
"""tpunet training entry point.

Replaces all three reference training scripts with one CLI over presets
(SURVEY.md section 0):

  python train.py --preset serial       # cifar10_serial_mobilenet_224.py
  python train.py --preset single       # cifar10_128batch.py
  python train.py --preset distributed  # cifar10_mpi_mobilenet_224.py

Distributed runs need no mpirun/rank plumbing: launch the same command on
every TPU-VM worker (see launch/run_pod.sh); process topology comes from
the platform via jax.distributed.initialize.
"""

from __future__ import annotations

import dataclasses

import jax

from tpunet.config import config_from_args
from tpunet.obs import RunUnhealthyError
from tpunet.parallel import initialize_distributed, sync_hosts
from tpunet.train.loop import Trainer
from tpunet.utils import log0


def main(argv=None) -> int:
    initialize_distributed()
    cfg = config_from_args(argv)
    # Profiling is owned by the obs subsystem now (tpunet/obs/spans.py
    # WindowedProfiler): --profile-dir alone still traces the whole
    # run, but the trace starts/stops at step boundaries inside the
    # trainer so --profile-start-step/--profile-num-steps can scope it.

    n_proc = jax.process_count()
    if n_proc > 1:
        # Reference semantics: per-rank batch of 128 => global scales with
        # world size (cifar10_mpi_mobilenet_224.py:117 + mpirun -np N).
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, batch_size=cfg.data.batch_size * n_proc))
    log0(f"JAX devices: {jax.device_count()} "
         f"({jax.local_device_count()} local), processes: {n_proc}")

    # Dataset fetch gate (reference rank-0 download + barrier, :93-102):
    # process 0 materializes the data first, other hosts wait — and
    # ONLY the dataset. Trainer construction issues device-layout
    # computations (the sharded state device_put / jit-identity), and
    # cross-process collectives must run in the SAME order on every
    # process (gloo on CPU gangs pairs them strictly by sequence; the
    # old p0-builds-Trainer-before-the-barrier shape interleaved p0's
    # layout computations with the others' barrier psum and died in
    # gloo's preamble check) — so construction is symmetric, after the
    # barrier.
    if n_proc > 1:
        if jax.process_index() == 0:
            from tpunet.data import get_dataset
            get_dataset(cfg.data)
        sync_hosts("dataset-ready")
    trainer = Trainer(cfg)

    try:
        if cfg.eval_only:
            m = trainer.evaluate_checkpoint()
            log0(f"Eval: Test Loss: {m['loss']:.4f} "
                 f"Test Acc: {m['accuracy']:.4f}")
        else:
            trainer.train()
    except RunUnhealthyError as e:
        # --halt-on-unhealthy tripped: the obs_alert record is already
        # in metrics.jsonl (and the live exporters) — exit nonzero
        # without a traceback, like a failed health check should.
        log0(f"ABORT: {e}")
        return 2
    finally:
        # Runs on the NaN-guard/preemption-raise paths too; close()
        # flushes checkpoints AND any still-open profiler trace, each
        # independently (Trainer.close's own try/finally).
        trainer.close()
    return 0


if __name__ == "__main__":  # python -m tpunet.main
    import sys

    sys.exit(main())


