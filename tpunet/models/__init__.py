from tpunet.models.mobilenetv2 import MobileNetV2, create_model  # noqa: F401
from tpunet.models.convert import convert_torch_state_dict, load_pretrained  # noqa: F401
