"""Model registry.

``create_model(cfg, mesh=None)`` dispatches on ``ModelConfig.name``:
the reference's one model (MobileNetV2, cifar10_mpi_mobilenet_224.py:
137-139) plus tpunet's attention-based ViT family. ``init_variables``
is model-agnostic — some models carry BatchNorm statistics (MobileNetV2)
and some do not (ViT); callers use ``variables.get("batch_stats", {})``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tpunet.config import ModelConfig
from tpunet.models import mobilenetv2, vit
from tpunet.models.convert import convert_torch_state_dict, load_pretrained  # noqa: F401
from tpunet.models.mobilenetv2 import MobileNetV2  # noqa: F401
from tpunet.models.vit import ViT, VIT_PRESETS  # noqa: F401


def create_model(cfg: ModelConfig, mesh=None):
    """Build the configured model. ``mesh`` is needed only by models
    that run shard_map internally (ring attention, pipeline)."""
    if cfg.name == "mobilenet_v2":
        return mobilenetv2.create_model(cfg)
    if cfg.name == "vit_pp":
        from tpunet.models import vit_pp
        return vit_pp.create_model(cfg, mesh=mesh)
    if cfg.name == "lm":
        from tpunet.models import lm
        return lm.create_model(cfg, mesh=mesh)
    if cfg.name == "lm_pp":
        from tpunet.models import lm_pp
        return lm_pp.create_model(cfg, mesh=mesh)
    if cfg.name == "vit" or cfg.name in VIT_PRESETS:
        return vit.create_model(cfg, mesh=mesh)
    raise ValueError(f"unknown model {cfg.name!r}")


def init_variables(model, rng: jax.Array, image_size: int = 224,
                   batch_size: int = 1, seq_len: int = 16) -> dict:
    """Initialize model variables with a dummy batch — NHWC images, or
    int32 tokens for models declaring ``input_kind = "tokens"``.

    ``batch_size`` (and ``seq_len`` for token models) matters only for
    models whose attention runs under shard_map (ring): the init batch
    must divide the mesh's batch/seq axes.
    """
    if getattr(model, "input_kind", "image") == "tokens":
        dummy = jnp.zeros((batch_size, seq_len), jnp.int32)
    else:
        dummy = jnp.zeros((batch_size, image_size, image_size, 3),
                          jnp.float32)
    return model.init({"params": rng}, dummy, train=False)


def num_params(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))
