"""torchvision MobileNetV2 state_dict -> Flax variables converter.

Transfer learning from ImageNet-pretrained weights is load-bearing for the
reference's ~96% CIFAR-10 accuracy (reference README.md:24-26; model built
at cifar10_mpi_mobilenet_224.py:137-139). This module converts a torch
``state_dict`` (torchvision key layout) into this package's Flax
``{'params', 'batch_stats'}`` tree:

- conv weights: torch (O, I, kH, kW) -> flax (kH, kW, I, O)
- depthwise conv: torch (C, 1, kH, kW), groups=C -> flax (kH, kW, 1, C)
  (same transpose; flax ``feature_group_count`` handles grouping)
- linear: torch (out, in) -> flax (in, out)
- BatchNorm: weight->scale, bias->bias, running_mean/var -> batch_stats

torchvision key scheme handled (verified against torchvision 0.x
mobilenet_v2): ``features.0.{0,1}`` stem, ``features.{1..17}.conv.*``
inverted residuals (expand absent in block 1 where t=1),
``features.18.{0,1}`` head conv, ``classifier.1`` linear. ``module.``
prefixes (from DDP-wrapped saves, reference :249) are stripped. If the
checkpoint head has a different class count (e.g. 1000 ImageNet classes),
the head is left at its fresh random init — exactly the reference's
head-swap (:138-139).

No torch import is required unless loading a ``.pth`` via
:func:`load_pretrained`; :func:`convert_torch_state_dict` accepts any
mapping of numpy-convertible arrays.
"""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

import jax.numpy as jnp
import numpy as np

from tpunet.models.mobilenetv2 import INVERTED_RESIDUAL_SETTINGS


def _np(x) -> np.ndarray:
    """Coerce a torch tensor / array-like to a float32 numpy array."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x, dtype=np.float32)


def _conv(w) -> np.ndarray:
    return _np(w).transpose(2, 3, 1, 0)  # OIHW -> HWIO


def _block_specs() -> Tuple[Tuple[str, int, bool], ...]:
    """(flax block name, torch features index, has expand) per block."""
    specs = []
    idx = 0
    for t, _c, n, _s in INVERTED_RESIDUAL_SETTINGS:
        for _ in range(n):
            specs.append((f"block{idx:02d}", idx + 1, t != 1))
            idx += 1
    return tuple(specs)


def _layout():
    """The torchvision MobileNetV2 key layout, as (flax_path, conv_key,
    bn_key) triples — the single source of truth walked by BOTH the
    importer and the exporter, so the two can never silently diverge."""
    yield ("stem",), "features.0.0", "features.0.1"
    for name, fi, has_expand in _block_specs():
        base = f"features.{fi}.conv"
        if has_expand:
            yield (name, "expand"), f"{base}.0.0", f"{base}.0.1"
            yield (name, "depthwise"), f"{base}.1.0", f"{base}.1.1"
            yield (name, "project"), f"{base}.2", f"{base}.3"
        else:
            yield (name, "depthwise"), f"{base}.0.0", f"{base}.0.1"
            yield (name, "project"), f"{base}.1", f"{base}.2"
    yield ("head",), "features.18.0", "features.18.1"


def convert_torch_state_dict(
    state_dict: Mapping[str, object],
    num_classes: int = 10,
) -> Tuple[Dict, Dict, bool]:
    """Convert a torch state_dict to (params, batch_stats, head_converted).

    ``head_converted`` is False when the checkpoint's classifier has a
    different output dimension than ``num_classes`` (the caller keeps its
    randomly-initialized head — the transfer-learning head swap).
    """
    sd = {k.removeprefix("module."): v for k, v in state_dict.items()}

    params: Dict = {}
    stats: Dict = {}

    def convbn(flax_path: Tuple[str, ...], conv_key: str, bn_key: str):
        node = params
        for p in flax_path:
            node = node.setdefault(p, {})
        node["conv"] = {"kernel": jnp.asarray(_conv(sd[f"{conv_key}.weight"]))}
        node["bn"] = {
            "scale": jnp.asarray(_np(sd[f"{bn_key}.weight"])),
            "bias": jnp.asarray(_np(sd[f"{bn_key}.bias"])),
        }
        snode = stats
        for p in flax_path:
            snode = snode.setdefault(p, {})
        snode["bn"] = {
            "mean": jnp.asarray(_np(sd[f"{bn_key}.running_mean"])),
            "var": jnp.asarray(_np(sd[f"{bn_key}.running_var"])),
        }

    for flax_path, conv_key, bn_key in _layout():
        convbn(flax_path, conv_key, bn_key)

    head_converted = False
    w = _np(sd["classifier.1.weight"])
    if w.shape[0] == num_classes:
        params["classifier"] = {
            "kernel": jnp.asarray(w.T),
            "bias": jnp.asarray(_np(sd["classifier.1.bias"])),
        }
        head_converted = True
    return params, stats, head_converted


def merge_pretrained(variables: Dict, params: Dict, stats: Dict,
                     head_converted: bool) -> Dict:
    """Overlay converted weights onto freshly-initialized variables."""
    new_params = dict(variables["params"])
    for k, v in params.items():
        new_params[k] = v
    if not head_converted:
        new_params["classifier"] = variables["params"]["classifier"]
    new_stats = dict(variables["batch_stats"])
    for k, v in stats.items():
        if k in ("classifier",):
            continue
        merged = dict(new_stats.get(k, {}))
        merged.update(v)
        new_stats[k] = merged
    return {"params": new_params, "batch_stats": new_stats}


def export_torch_state_dict(params: Dict, batch_stats: Dict) -> Dict[str, np.ndarray]:
    """The inverse converter: Flax ``params``/``batch_stats`` -> a torch
    state_dict in the torchvision MobileNetV2 key layout (including the
    ``num_batches_tracked`` BN bookkeeping entries ``load_state_dict``
    checks under strict=True). Round-trips bit-exactly with
    :func:`convert_torch_state_dict`, so tpunet-trained weights load
    straight into torchvision/the reference's serving stack."""
    sd: Dict[str, np.ndarray] = {}

    def putconvbn(flax_path: Tuple[str, ...], conv_key: str, bn_key: str):
        node = params
        for p in flax_path:
            node = node[p]
        # HWIO -> OIHW (inverse of _conv)
        sd[f"{conv_key}.weight"] = np.asarray(
            node["conv"]["kernel"], np.float32).transpose(3, 2, 0, 1)
        sd[f"{bn_key}.weight"] = np.asarray(node["bn"]["scale"], np.float32)
        sd[f"{bn_key}.bias"] = np.asarray(node["bn"]["bias"], np.float32)
        snode = batch_stats
        for p in flax_path:
            snode = snode[p]
        sd[f"{bn_key}.running_mean"] = np.asarray(snode["bn"]["mean"],
                                                  np.float32)
        sd[f"{bn_key}.running_var"] = np.asarray(snode["bn"]["var"],
                                                 np.float32)
        sd[f"{bn_key}.num_batches_tracked"] = np.asarray(0, np.int64)

    for flax_path, conv_key, bn_key in _layout():
        putconvbn(flax_path, conv_key, bn_key)
    sd["classifier.1.weight"] = np.asarray(
        params["classifier"]["kernel"], np.float32).T
    sd["classifier.1.bias"] = np.asarray(params["classifier"]["bias"],
                                         np.float32)
    return sd


def main(argv=None):
    """Export a trained best-checkpoint to a torch ``.pth``:

        python -m tpunet.models.convert out.pth --checkpoint-dir ckpt
    """
    import argparse

    import jax

    from tpunet.ckpt import Checkpointer
    from tpunet.config import CheckpointConfig, ModelConfig
    from tpunet.models import create_model, init_variables

    p = argparse.ArgumentParser(
        description="export a tpunet MobileNetV2 checkpoint as a torch "
                    "state_dict (.pth)")
    p.add_argument("out", help="output .pth path")
    p.add_argument("--checkpoint-dir", default="checkpoints")
    p.add_argument("--width-mult", type=float, default=1.0)
    p.add_argument("--num-classes", type=int, default=10)
    args = p.parse_args(argv)

    import torch  # local import: only the writer needs torch

    model_cfg = ModelConfig(width_mult=args.width_mult,
                            num_classes=args.num_classes)
    model = create_model(model_cfg)
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=32)
    ckpt = Checkpointer(CheckpointConfig(directory=args.checkpoint_dir))
    best = ckpt.restore_best({"params": variables["params"],
                              "batch_stats": variables["batch_stats"]})
    if best is None:
        raise SystemExit(f"no best checkpoint under {args.checkpoint_dir!r}")
    sd = export_torch_state_dict(best["params"], best["batch_stats"])
    # torch.tensor COPIES — from_numpy would alias possibly-read-only
    # JAX-export buffers and torch warns/UB on those.
    torch.save({k: torch.tensor(np.asarray(v)) for k, v in sd.items()},
               args.out)
    print(f"wrote {len(sd)} tensors to {args.out}")


def load_pretrained(path: str, variables: Dict, num_classes: int = 10) -> Dict:
    """Load a torch ``.pth`` checkpoint and overlay it onto ``variables``.

    Accepts either a bare state_dict or a dict containing one under a
    conventional key ('state_dict' / 'model').
    """
    import torch  # local import: torch is optional at runtime

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and not any(hasattr(v, "shape") for v in obj.values()):
        for key in ("state_dict", "model", "params"):
            if key in obj:
                obj = obj[key]
                break
    params, stats, head_ok = convert_torch_state_dict(obj, num_classes)
    return merge_pretrained(variables, params, stats, head_ok)


if __name__ == "__main__":
    main()
