"""Decoder-only transformer LM — tpunet's long-context model family.

The reference is a fixed-224px vision CNN with no sequence axis at all
(SURVEY.md section 5, "long-context: absent entirely"); tpunet treats
long context as first-class, and this model is where it is exercised
end-to-end: causal attention over sequences whose length scales with
the mesh 'seq' axis (ring attention, exact causality under sharding via
global positions) or with bounded memory on one chip (blockwise).

Architecture: token embedding + learned positions -> the same pre-LN
encoder blocks as the ViT family (tpunet/models/vit.py, with a causal
attention core) -> final LN -> logits against the embedding transpose
(weight tying — halves the head params and is standard for small LMs).

Reuses the whole tpunet stack: Trainer epoch loop, psum metrics, Orbax
checkpointing, TP path rules (the block param names match the ViT
rules), MoE blocks, and the dense/blockwise/ring/ulysses attention
cores.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpunet.config import ModelConfig
from tpunet.models.vit import EncoderBlock, make_attn_fn


class TransformerLM(nn.Module):
    """tokens [B, T] int32 -> logits [B, T, vocab] float32."""

    vocab_size: int = 256
    hidden: int = 192
    depth: int = 6
    heads: int = 3
    mlp_ratio: float = 4.0
    max_len: int = 1024
    dropout_rate: float = 0.0
    attn_fn: Any = None
    moe_experts: int = 0
    moe_every: int = 2
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "auto"
    moe_mesh: Any = None
    remat: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    input_kind = "tokens"              # init_variables dispatch

    @nn.compact
    def __call__(self, tokens, train: bool = False, decode: bool = False,
                 pos_offset=0, segment_ids=None,
                 return_hidden: bool = False, decode_active=None,
                 paged_kv=None, page_table=None):
        """``decode=True``: incremental step against the KV cache (one
        token per call after cache init); ``pos_offset`` is the absolute
        position of ``tokens[:, 0]`` in the sequence — a scalar, or an
        int32 [B] array giving each batch row its OWN position (the
        tpunet/serve slot-pool engine: rows are independent requests at
        different depths; T > 1 then runs a chunked causal prefill that
        writes K/V for all T positions in one pass). ``decode_active``
        [B] bool gates per-row cache writes (inactive slots stay
        bit-frozen). ``segment_ids`` [B, T] enables packed-sequence
        training: attention is masked to same-segment tokens (composed
        with causality in the core). ``return_hidden=True`` returns the
        final-LN hidden states [B, T, C] float32 instead of logits —
        the vocab-sharded CE hook (tpunet/ops/vocab_ce.py): the caller
        computes the loss against the tied embedding without ever
        materializing the [B, T, V] logits. ``paged_kv`` (a
        ``models.vit.PagedKV``) + ``page_table`` [B, pages-per-row]
        int32 switch the decode KV cache to the shared page pool
        (tpunet/serve paged continuous batching; needs per-row
        ``pos_offset``)."""
        b, t = tokens.shape
        if t > self.max_len:
            raise ValueError(f"sequence {t} exceeds max_len {self.max_len}")
        embed = nn.Embed(self.vocab_size, self.hidden,
                         embedding_init=nn.initializers.normal(stddev=0.02),
                         param_dtype=self.param_dtype, name="embed")
        x = embed(tokens).astype(self.dtype)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, self.max_len, self.hidden), self.param_dtype)
        per_row = getattr(pos_offset, "ndim", 0) == 1
        if per_row:
            # Per-row positions (serve engine): gather each row's slice
            # of the position table; clip covers the padded tail of a
            # bucketed prefill (those K/V are overwritten before any
            # query can attend to them — engine invariant).
            idx = jnp.clip(pos_offset[:, None] + jnp.arange(t)[None, :],
                           0, self.max_len - 1)
            x = x + jnp.take(pos[0], idx, axis=0).astype(self.dtype)
        else:
            x = x + jax.lax.dynamic_slice_in_dim(
                pos, pos_offset, t, 1).astype(self.dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # remat only matters for training; never wrap the decode path.
        # (both flags are static: argnums count self as 0)
        Block = (nn.remat(EncoderBlock, static_argnums=(2, 3))
                 if self.remat and not decode else EncoderBlock)
        for i in range(self.depth):
            moe_here = (self.moe_experts > 0
                        and i % self.moe_every == self.moe_every - 1)
            x = Block(self.heads, int(self.hidden * self.mlp_ratio),
                             attn_fn=self.attn_fn,
                             moe_experts=self.moe_experts if moe_here else 0,
                             moe_top_k=self.moe_top_k,
                             moe_capacity_factor=self.moe_capacity_factor,
                             moe_dispatch=self.moe_dispatch,
                             moe_mesh=self.moe_mesh,
                             dropout_rate=self.dropout_rate,
                             dtype=self.dtype, param_dtype=self.param_dtype,
                             name=f"block{i:02d}")(
                                 x, train, decode, segment_ids,
                                 pos_offset if per_row else None,
                                 decode_active, paged_kv, page_table)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln")(x)
        if return_hidden:
            return x.astype(jnp.float32)
        # Tied output head: logits against the embedding matrix.
        logits = embed.attend(x.astype(self.param_dtype))
        return logits.astype(jnp.float32)


def create_model(cfg: ModelConfig, mesh=None) -> TransformerLM:
    return TransformerLM(
        vocab_size=cfg.vocab_size,
        hidden=cfg.vit_hidden,
        depth=cfg.vit_depth,
        heads=cfg.vit_heads,
        mlp_ratio=cfg.vit_mlp_ratio,
        max_len=cfg.max_seq_len,
        dropout_rate=cfg.dropout_rate,
        attn_fn=make_attn_fn(cfg, mesh, causal=True),
        moe_experts=cfg.moe_experts,
        moe_every=cfg.moe_every,
        moe_top_k=cfg.moe_top_k,
        moe_capacity_factor=cfg.moe_capacity_factor,
        moe_dispatch=cfg.moe_dispatch,
        moe_mesh=mesh,
        remat=cfg.remat,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )


def filter_logits(lg, *, top_k: int = 0, top_p: float = 0.0):
    """Truncate ``lg`` [..., V] for sampling: tokens outside the filters
    become -inf. Sequential HF-warper semantics: top-k first, then the
    nucleus over the RENORMALIZED post-top-k distribution (computing the
    nucleus on the raw distribution would admit a larger, more
    permissive nucleus whenever top-k removed tail mass)."""
    need_sort = (top_k > 0 and top_k < lg.shape[-1]) or 0.0 < top_p < 1.0
    if need_sort:
        srt = jnp.sort(lg, -1)[..., ::-1]  # one descending sort
    if top_k > 0 and top_k < lg.shape[-1]:
        lg = jnp.where(lg >= srt[..., top_k - 1:top_k], lg, -jnp.inf)
        srt = jnp.where(jnp.arange(srt.shape[-1]) < top_k, srt, -jnp.inf)
    if 0.0 < top_p < 1.0:
        # Keep the smallest prefix of the sorted distribution whose
        # mass reaches top_p (the top token always survives).
        probs = jax.nn.softmax(srt, -1)
        keep = jnp.cumsum(probs, -1) - probs < top_p
        cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), -1, keepdims=True)
        lg = jnp.where(lg >= cutoff, lg, -jnp.inf)
    return lg


def generate(model: TransformerLM, variables: dict, prompt, n_new: int,
             *, temperature: float = 0.0, top_k: int = 0,
             top_p: float = 0.0, rng=None,
             use_cache: bool = True, mesh=None):
    """Greedy (or sampled) autoregressive generation from ``prompt``
    [B, T0] int32. ``temperature`` 0 = greedy; > 0 samples
    softmax(logits/T), optionally truncated to the ``top_k``
    highest-probability tokens and/or the smallest ``top_p``
    cumulative-probability nucleus (both 0 = off).

    Default path: incremental decoding against the KV cache — O(L) work
    per token, one jitted single-token program compiled once, prompt
    prefilled through the same step. Works for every attention config
    (both cache init and decode steps bypass the injected core). For
    MoE models note the standard caveat: decode routes each step's
    tokens with per-step expert capacity, so when experts overflow, the
    drop set can differ from a full-prefix forward pass (exact equality
    holds whenever nothing is dropped, e.g. small batches).

    ``mesh`` (tensor-parallel serving): when the caller placed
    ``variables`` with TP shardings (tpunet/infer/generate.py load_lm
    --mesh-model), pass the mesh so the KV cache is created sharded to
    match — heads over 'model', the layout the attention's head-sharded
    Q/K/V writes produce. Without it GSPMD would reshard the cache
    every step. Same tokens out: sharding never changes the math
    (exactness test vs the unsharded path).

    ``use_cache=False`` falls back to full-prefix recompute: dense
    models reuse a fixed-size buffer (one compile; causality makes the
    unwritten tail irrelevant), MoE models grow the prefix because
    buffer padding would consume expert capacity."""
    prompt = jnp.asarray(prompt, jnp.int32)
    b, t0 = prompt.shape
    keys = jax.random.split(rng if rng is not None else jax.random.PRNGKey(0),
                            max(1, n_new))

    def pick(lg, key):
        if temperature <= 0:
            return jnp.argmax(lg, -1)
        lg = filter_logits(lg / temperature, top_k=top_k, top_p=top_p)
        return jax.random.categorical(key, lg, -1)

    if use_cache:
        total = t0 + n_new
        # Shapes only — no initializer FLOPs, no transient param copy.
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((b, total), jnp.int32),
                               decode=True))

        def cache_zeros(s):
            if mesh is not None:
                from jax.sharding import (NamedSharding,
                                          PartitionSpec as P)
                tp = mesh.shape.get("model", 1)
                spec = (P(None, None, "model", None)
                        if (s.ndim == 4 and tp > 1
                            and s.shape[2] % tp == 0) else P())
                return jnp.zeros(s.shape, s.dtype,
                                 device=NamedSharding(mesh, spec))
            return jnp.zeros(s.shape, s.dtype)

        cache = jax.tree_util.tree_map(cache_zeros, shapes["cache"])

        @jax.jit
        def step(cache, buf, i, key):
            tok = jax.lax.dynamic_slice(buf, (0, i), (b, 1))
            logits, mutated = model.apply(
                {**variables, "cache": cache}, tok, train=False,
                decode=True, pos_offset=i, mutable=["cache"])
            nxt = pick(logits[:, 0], key).astype(jnp.int32)
            # write the prediction at i+1 unless that slot holds prompt
            buf = jnp.where(
                jnp.arange(buf.shape[1])[None, :] == i + 1,
                jnp.where(i + 1 < t0, buf, nxt[:, None]), buf)
            return mutated["cache"], buf

        buf = jnp.zeros((b, total), jnp.int32)
        buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
        for i in range(total - 1):
            cache, buf = step(cache, buf, jnp.int32(i),
                              keys[max(0, i - t0 + 1) % len(keys)])
        return buf

    if model.moe_experts > 0:
        tokens = prompt
        for i in range(n_new):
            lg = model.apply(variables, tokens, train=False)[:, -1]
            nxt = pick(lg, keys[i]).astype(jnp.int32)
            tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
        return tokens

    buf = jnp.zeros((b, t0 + n_new), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))

    @jax.jit
    def write_next(buf, cur, key):
        logits = model.apply(variables, buf, train=False)
        lg = jax.lax.dynamic_index_in_dim(logits, cur - 1, axis=1,
                                          keepdims=False)
        nxt = pick(lg, key)
        return jax.lax.dynamic_update_slice(
            buf, nxt[:, None].astype(jnp.int32), (0, cur))

    for i in range(n_new):
        buf = write_next(buf, jnp.int32(t0 + i), keys[i])
    return buf
