"""Pipeline-parallel causal LM ("lm_pp").

The LM family is where pipeline parallelism earns its keep (depth grows
with model scale while the vision models stay shallow), so the decoder
gets the same treatment as tpunet/models/vit_pp.py: encoder blocks as
*stacked functional parameters* (leading ``depth`` dim, sharded over the
mesh 'pipe' axis by the path rule in tpunet/parallel/tp.py) streamed
through the GPipe executor (tpunet/parallel/pp.py) — one jitted SPMD
program, activations hopping stage-to-stage via ``lax.ppermute``.

Architecture matches tpunet/models/lm.py's TransformerLM: token
embedding + learned positions -> pre-LN causal blocks -> final LN ->
logits tied to the embedding transpose. Causality comes from the dense
attention mask inside block_apply (causal=True). With
``--attention ulysses`` or ``--attention ring`` the sequence is ALSO
sharded (SP x PP, dp x sp x pp meshes): the pipeline executor passes
the 'seq' axis through its shard_map and each stage runs its SP
collectives over that already-manual axis — Ulysses' all-to-all pair
around a locally-dense core (exact global causality: the core sees the
full sequence per head group), or the ring's per-step K/V ppermute
rotation (exact global causality via global positions,
tpunet/ops/attention.py ring_attention). Both ops are axis-name
shard_map-body functions, so no shard_map nesting is involved; pick
ulysses when the 'seq' axis size divides the head count (2
collectives/call), ring when it doesn't or when per-hop ICI traffic
must stay neighbor-only.

Dropout is fully supported: the train step's dropout rng threads
through gpipe, folded per (tick, stage, layer). Grad accumulation
composes too — the accumulation scan in steps.py wraps the whole
pipelined program (microbatching in TIME over microbatching in STAGES).

Packed sequences compose: ``segment_ids`` travel as the executors'
per-microbatch ``extra`` input (each stage indexes its current
microbatch's ids — batch metadata never hops), masking attention to
same-segment tokens inside every block; ``--pack-docs --model lm_pp``
works under both schedules, including packed x SP with Ulysses
(the seq-sharded id slice rides ``extra`` and the full-sequence local
core masks exactly after one [mb, T/sp] -> [mb, T] id all_gather —
tpunet/ops/attention.py ulysses_attention). Ring stays excluded: its
state-merging core has no segment operands (the __call__ error).

MoE composes as well (EP x PP): with ``--moe-experts`` the stacks are
organized as SUPER-layers — ``moe_every - 1`` dense blocks plus one
routed block per scan step — so the per-stage program stays one
uniform ``lax.scan`` despite heterogeneous layers (depth must divide
into whole super-layers, and super-layers across stages). The routed
block runs the same functional core as MoeMlp
(tpunet/models/moe.py moe_apply); the load-balance aux loss threads
through the executors' ``with_aux`` contract (sum over stages, mean
over microbatch-shards — the equal-weight semantics grad-accum uses,
tpunet/train/steps.py) and is sown into the standard 'losses'
collection. With pipe > 1 each microbatch-shard routes its tokens
independently with per-shard capacity (the standard shard_map MoE
scope; the unpipelined model under GSPMD routes globally — documented
deviation, exact parity at n_micro=1). With a mesh 'model' axis > 1
the expert stacks (and their Adam moments) shard over it INSIDE the
stages — true EP x PP: routing/dispatch replicated per shard (cheap,
O(n x E)), expert FFNs on the local expert slice, one psum per MoE
layer assembles the output (no token all-to-all: tokens are
replicated over 'model'). Grad parity vs the replicated run is exact
under both schedules; the 1F1B manual backward handles the
unreduced-cotangent convention the in-stage psum transposes imply
(tpunet/parallel/pp.py onef1b ep_axis).

With pipe == 1 the stacked params run as a plain lax.scan over layers —
the same math, which the parity tests assert. No KV-cache decode path
in this module: generation/serving unstacks lm_pp checkpoints into the
(architecturally identical) TransformerLM via to_transformer_lm_params
(tpunet/infer/generate.py --model lm_pp); the reference has no LM
serving at all (SURVEY.md section 0 — this whole family is beyond
parity).

Measured on the v5e chip (scripts/bench_lm.py --model lm_pp, T=2048
B=8 depth=4 hidden=512): 276-290k tok/s at pipe=1 with the flash core
(--attention flash/auto; inside the pipeline's shard_map the local
kernel variant runs, outside it the custom_partitioning-wrapped one —
resolve_block_cores) — 1.85x the
unrolled DENSE TransformerLM (157k) and within 19% of the unrolled
flash one (357k); that residual scan-over-layers overhead is the price
of being shardable over 'pipe', which pays only at real multi-stage
meshes (unmeasurable on this 1-chip environment; the dp x pp dryrun
leg validates the program, not its scaling). With the dense core this
was 132k tok/s.

Schedule note: three executors (``--pp-schedule``). "gpipe" (default)
lets reverse-mode AD through the scan+ppermute emit the standard
backward pipeline (all forwards, then all backwards — its residuals
stack every per-tick intermediate). "1f1b" is the hand-written VJP
(tpunet/parallel/pp.py onef1b): the backward replays forwards and runs
backwards interleaved per microbatch in 1F1B order, holding at most
min(S, M) stage inputs live — the 1F1B activation bound — at the cost
of one rematerialized stage forward per microbatch. Same grads
(parity-tested), same bubble fraction; pick 1f1b when activation
memory, not compute, is the binding constraint. "interleaved" adds
virtual pipeline stages (``--pp-virtual`` chunks per device on a full
activation ring, chunk-permuted 'pipe' storage): ~v-fold smaller
bubble at a 1F1B-style bounded memory cost (pp.py interleaved).
Composes with packed sequences and MoE/EP (chunks hold whole
super-layers); SP stays with gpipe/1f1b. Interleaved checkpoints
persist their layout (resume guard + the best_meta.json serving
sidecar) because the stacks are chunk-permuted.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpunet.config import ModelConfig
from tpunet.models.moe import moe_apply
from tpunet.models.vit_pp import (_dropout, _stacked_lecun_normal,
                                  attn_half_apply, block_apply,
                                  resolve_block_cores)
from tpunet.ops.attention import (ring_attention, ring_self_attention,
                                  ulysses_attention,
                                  ulysses_self_attention)
from tpunet.parallel.pp import (gpipe, interleaved,
                                interleaved_layer_order, onef1b)


def _stacked_expert_normal(key, shape, dtype=jnp.float32):
    """flax variance_scaling(2.0, fan_in, truncated_normal) for stacked
    [G, e, d_in, d_out] expert kernels, matching MoeMlp's UNSTACKED
    [e, d_in, d_out] fan exactly (flax treats leading dims as the
    receptive field: fan_in = e * d_in) — the stacked G dim must not
    fold into the fan."""
    fan_in = shape[-3] * shape[-2]
    std = (2.0 / fan_in) ** 0.5 / 0.87962566103423978
    return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


_ATTN_KEYS = ("ln1s", "ln1b", "qkv_k", "qkv_b", "out_k", "out_b",
              "ln2s", "ln2b")
_FC_KEYS = ("fc1_k", "fc1_b", "fc2_k", "fc2_b")
_MOE_KEYS = ("rk", "rb", "wi", "bi", "wo", "bo")


def _moe_block_apply(pa, pm, x, *, heads, top_k, capacity_factor,
                     dropout_rate=0.0, key=None, attn,
                     segment_ids=None, ep_axis=None,
                     ep_impl="replicated"):
    """One pre-LN block whose MLP is the routed MoE core: the shared
    attention half (vit_pp.attn_half_apply — same dropout placements
    and key split as dense blocks), then moe_apply
    (tpunet/models/moe.py) instead of the dense fc pair. Router math
    in float32 on the float32 router params (the stacked analogue of
    MoeMlp's float32 Dense). ``ep_axis`` (EP x PP): the expert params
    hold only this device's shard over that mesh axis; ``ep_impl``
    picks the lowering — "alltoall" (GShard capacity-buffer token
    exchange; each device routes its 1/ep token slice) or
    "replicated" (every device routes all tokens, one psum assembles
    the output). Returns (x, aux)."""
    mb, t, c = x.shape
    x, y, km = attn_half_apply(pa, x, heads=heads, causal=True,
                               dropout_rate=dropout_rate, key=key,
                               attn=attn, segment_ids=segment_ids)
    tokens = y.reshape(mb * t, c)
    logits = (tokens.astype(jnp.float32) @ pm["rk"].astype(jnp.float32)
              + pm["rb"].astype(jnp.float32))
    out, aux = moe_apply(tokens, logits, pm["wi"], pm["bi"], pm["wo"],
                         pm["bo"], top_k=top_k,
                         capacity_factor=capacity_factor, dtype=x.dtype,
                         ep_axis=ep_axis, ep_impl=ep_impl)
    out = out.reshape(mb, t, c)
    if dropout_rate > 0.0 and km is not None:
        out = _dropout(out, dropout_rate, km)
    return x + out, aux


class PipelinedLM(nn.Module):
    """tokens [B, T] int32 -> logits [B, T, vocab] float32, pipelined."""

    vocab_size: int = 256
    hidden: int = 192
    depth: int = 6
    heads: int = 3
    mlp_ratio: float = 4.0
    max_len: int = 1024
    n_micro: int = 4
    dropout_rate: float = 0.0
    moe_experts: int = 0               # 0 = dense MLP everywhere
    moe_every: int = 2                 # MoE in every moe_every-th block
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "auto"         # EP lowering (moe.py docstring)
    attention: str = "dense"   # dense | flash | auto | ulysses | ring
    attention_core: Any = None         # SP local core (None = auto)
    attention_block: int = 512         # blockwise/flash block inside SP
    schedule: str = "gpipe"    # gpipe | 1f1b | interleaved (pp.py)
    virtual: int = 2                   # chunks/device for interleaved
    mesh: Any = None                   # jax.sharding.Mesh or None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    input_kind = "tokens"              # init_variables dispatch

    @nn.compact
    def __call__(self, tokens, train: bool = False, segment_ids=None,
                 return_hidden: bool = False):
        """``segment_ids`` [B, T] enables packed-sequence training:
        attention masks to same-segment tokens (composed with
        causality in the core). The ids travel through the pipeline as
        the executors' non-differentiable ``extra`` input — indexed
        per microbatch by each stage, never hopped.
        ``return_hidden=True``: final-LN hidden states [B, T, C]
        float32 instead of logits (the vocab-sharded CE hook,
        tpunet/ops/vocab_ce.py — at real vocabs the replicated
        [B, T, V] float32 logits this skips dwarf the activation
        memory the 1F1B executor saves)."""
        if self.hidden % self.heads:
            raise ValueError(f"hidden {self.hidden} not divisible by "
                             f"{self.heads} heads")
        packed = segment_ids is not None
        if packed and self.attention == "ring":
            raise ValueError(
                "packed sequences don't compose with ring attention: "
                "the ring merges per-block (out, lse) attention STATES "
                "and the flash state kernel has no segment operands "
                "(tpunet/ops/flash.py local_flash_attention_state) — "
                "use --attention ulysses (segment-capable SP: the "
                "local core sees the full sequence and masks exactly) "
                "or dense/flash/auto")
        b, t = tokens.shape
        if t > self.max_len:
            raise ValueError(f"sequence {t} exceeds max_len {self.max_len}")
        embed = nn.Embed(self.vocab_size, self.hidden,
                         embedding_init=nn.initializers.normal(stddev=0.02),
                         param_dtype=self.param_dtype, name="embed")
        x = embed(tokens).astype(self.dtype)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, self.max_len, self.hidden), self.param_dtype)
        x = x + pos[:, :t].astype(self.dtype)

        rate = self.dropout_rate if train else 0.0
        key = self.make_rng("dropout") if rate > 0.0 else None
        if key is not None:
            x = _dropout(x, rate, self.make_rng("dropout"))

        ln_ones = nn.initializers.ones
        zeros = nn.initializers.zeros
        winit = _stacked_lecun_normal
        L, C, H = self.depth, self.hidden, int(self.hidden * self.mlp_ratio)
        moe = self.moe_experts > 0
        m_every = self.moe_every if moe else 1
        if moe and L % m_every:
            raise ValueError(f"depth {L} not divisible by moe_every "
                             f"{m_every} (whole super-layers required)")
        G = L // m_every
        # Dense-MLP stacks cover only the dense slots: with MoE every
        # m_every-th block routes instead, so the fc stacks hold
        # G * (m_every - 1) layers ordered by (super-layer, slot) —
        # matching TransformerLM's layout where MoE blocks have no
        # dense mlp params at all.
        n_fc = G * (m_every - 1) if moe else L
        blocks = {
            "ln1s": self.param("blocks_ln1s", ln_ones, (L, C),
                               self.param_dtype),
            "ln1b": self.param("blocks_ln1b", zeros, (L, C),
                               self.param_dtype),
            "qkv_k": self.param("blocks_qkv_k", winit, (L, C, 3 * C),
                                self.param_dtype),
            "qkv_b": self.param("blocks_qkv_b", zeros, (L, 3 * C),
                                self.param_dtype),
            "out_k": self.param("blocks_out_k", winit, (L, C, C),
                                self.param_dtype),
            "out_b": self.param("blocks_out_b", zeros, (L, C),
                                self.param_dtype),
            "ln2s": self.param("blocks_ln2s", ln_ones, (L, C),
                               self.param_dtype),
            "ln2b": self.param("blocks_ln2b", zeros, (L, C),
                               self.param_dtype),
        }
        if n_fc > 0:
            blocks.update({
                "fc1_k": self.param("blocks_fc1_k", winit, (n_fc, C, H),
                                    self.param_dtype),
                "fc1_b": self.param("blocks_fc1_b", zeros, (n_fc, H),
                                    self.param_dtype),
                "fc2_k": self.param("blocks_fc2_k", winit, (n_fc, H, C),
                                    self.param_dtype),
                "fc2_b": self.param("blocks_fc2_b", zeros, (n_fc, C),
                                    self.param_dtype),
            })
        blocks = jax.tree_util.tree_map(
            lambda a: a.astype(self.dtype), blocks)
        if moe:
            E = self.moe_experts
            # Router params stay float32 (MoeMlp's float32 Dense);
            # expert kernels keep param_dtype and moe_apply casts them
            # to the compute dtype itself — so none of these take the
            # blanket dtype cast above.
            blocks.update({
                "moe_rk": self.param(
                    "blocks_moe_rk", nn.initializers.normal(stddev=0.02),
                    (G, C, E), jnp.float32),
                "moe_rb": self.param("blocks_moe_rb", zeros, (G, E),
                                     jnp.float32),
                "moe_wi": self.param("blocks_moe_wi",
                                     _stacked_expert_normal, (G, E, C, H),
                                     self.param_dtype),
                "moe_bi": self.param("blocks_moe_bi", zeros, (G, E, H),
                                     self.param_dtype),
                "moe_wo": self.param("blocks_moe_wo",
                                     _stacked_expert_normal, (G, E, H, C),
                                     self.param_dtype),
                "moe_bo": self.param("blocks_moe_bo", zeros, (G, E, C),
                                     self.param_dtype),
            })
        heads = self.heads

        pipelined = (self.mesh is not None
                     and self.mesh.shape.get("pipe", 1) > 1)
        sp = self.attention in ("ulysses", "ring")
        if sp:
            if pipelined:
                # SP x PP: runs INSIDE the pipeline's shard_map, so the
                # stage body is already device-local — both SP ops are
                # axis-name collectives over the mesh 'seq' axis:
                # Ulysses' all-to-all pair around a locally-dense core,
                # or the ring's K/V rotation (global positions keep
                # causality exact either way).
                if self.attention == "ulysses":
                    # segment_ids (packed x SP): the seq-SHARDED id
                    # slice rides the executors' 'extra' input;
                    # ulysses_attention gathers it to global ids for
                    # its full-sequence local core.
                    def attn(q, k, v, causal=True, segment_ids=None):
                        return ulysses_attention(
                            q, k, v, axis_name="seq", causal=causal,
                            core=self.attention_core,
                            block=self.attention_block,
                            segment_ids=segment_ids)
                else:
                    def attn(q, k, v, causal=True):
                        return ring_attention(q, k, v, "seq",
                                              causal=causal,
                                              core=self.attention_core)
            elif self.attention == "ulysses":
                # pipe == 1: the partitioned wrapper shard_maps over
                # 'seq' per block, same as the unpipelined LM family.
                def attn(q, k, v, causal=True, segment_ids=None):
                    return ulysses_self_attention(
                        q, k, v, self.mesh, causal=causal,
                        core=self.attention_core,
                        block=self.attention_block,
                        segment_ids=segment_ids)
            else:
                def attn(q, k, v, causal=True):
                    return ring_self_attention(q, k, v, self.mesh,
                                               causal=causal,
                                               core=self.attention_core)
        else:
            seq_core, pipe_core = resolve_block_cores(self.attention)
            attn = pipe_core if pipelined else seq_core
        sp_in_pipe = sp and pipelined

        top_k, cap_f = self.moe_top_k, self.moe_capacity_factor
        # EP x PP: shard the expert stacks over the mesh 'model' axis
        # inside the pipeline. The lowering (--moe-dispatch) resolves
        # here against the static per-stage token count: "alltoall" is
        # the GShard capacity-buffer dispatch (each device routes its
        # 1/ep slice of the stage's tokens and two all_to_alls carry
        # the exchange), "replicated" the routing-everywhere psum
        # fallback (moe.py module docstring for the accounting).
        ep_axis = ("model" if (moe and pipelined
                               and self.mesh.shape.get("model", 1) > 1)
                   else None)
        ep_impl = "replicated"
        if ep_axis is not None:
            from tpunet.models.moe import resolve_moe_dispatch
            ep = self.mesh.shape["model"]
            dp = self.mesh.shape.get("data", 1)
            sp_n = self.mesh.shape.get("seq", 1) if sp else 1
            if (b % (dp * self.n_micro) == 0 and t % sp_n == 0):
                n_stage = (b // dp // self.n_micro) * (t // sp_n)
            elif self.moe_dispatch == "alltoall":
                raise ValueError(
                    f"moe_dispatch='alltoall' needs batch {b} divisible "
                    f"by data axis x microbatches ({dp} x "
                    f"{self.n_micro}) and seq {t} by the seq axis "
                    f"({sp_n}) to slice stage tokens over the expert "
                    "axis")
            else:
                n_stage = 1   # indivisible; the executor will raise
                #               its own divisibility error (auto path)
            ep_impl = resolve_moe_dispatch(self.moe_dispatch, ep=ep,
                                           n_tokens=n_stage,
                                           n_experts=self.moe_experts)
        elif self.moe_dispatch == "alltoall" and moe:
            raise ValueError(
                "moe_dispatch='alltoall' needs the pipelined EP x PP "
                "path (mesh 'pipe' > 1 and 'model' > 1); the "
                "unpipelined lm/vit models lower it via MoeMlp")

        def stage_apply(params, xs, *rest):
            # rest per the executor protocol: (extra?, key?) — extra is
            # this microbatch's [mb, T] segment-id slice when packed.
            if packed:
                seg_pair = (rest[0], rest[0])
                rest = rest[1:]
            else:
                seg_pair = None
            k = rest[0] if rest else None
            if k is not None and sp_in_pipe:
                # x is seq-sharded inside the pipeline under SP
                # (ulysses or ring): without this fold every
                # sequence shard would draw
                # IDENTICAL dropout masks (correlated positions T/sp
                # apart). Dense/flash stages must NOT fold — their x is
                # replicated over 'seq' and diverging masks would break
                # the replication invariant.
                k = jax.random.fold_in(k, jax.lax.axis_index("seq"))

            if not moe:
                def body(carry, inp):
                    pl, i = inp
                    lk = (jax.random.fold_in(k, i) if k is not None
                          else None)
                    return block_apply(pl, carry, heads=heads,
                                       causal=True, dropout_rate=rate,
                                       key=lk, attn=attn,
                                       segment_ids=seg_pair), None
                idx = jnp.arange(
                    jax.tree_util.tree_leaves(params)[0].shape[0])
                out, _ = jax.lax.scan(body, xs, (params, idx))
                return out

            # MoE: scan over SUPER-layers (m_every - 1 dense blocks +
            # one MoE block each) so the per-stage program stays a
            # uniform lax.scan despite heterogeneous layers. The local
            # [L_local, ...] stacks reshape to [G_local, slot, ...]
            # (contiguous, since stages hold whole super-layers).
            gl = params["moe_wi"].shape[0]
            pa = {kk: params[kk].reshape((gl, m_every)
                                         + params[kk].shape[1:])
                  for kk in _ATTN_KEYS}
            pf = ({kk: params[kk].reshape((gl, m_every - 1)
                                          + params[kk].shape[1:])
                   for kk in _FC_KEYS} if m_every > 1 else {})
            pm = {kk: params["moe_" + kk] for kk in _MOE_KEYS}

            def body(carry, inp):
                xc, auxc = carry
                pa_g, pf_g, pm_g, g = inp
                for j in range(m_every - 1):
                    pl = {kk: pa_g[kk][j] for kk in _ATTN_KEYS}
                    pl.update({kk: pf_g[kk][j] for kk in _FC_KEYS})
                    lk = (jax.random.fold_in(k, g * m_every + j)
                          if k is not None else None)
                    xc = block_apply(pl, xc, heads=heads, causal=True,
                                     dropout_rate=rate, key=lk,
                                     attn=attn, segment_ids=seg_pair)
                pl = {kk: pa_g[kk][m_every - 1] for kk in _ATTN_KEYS}
                lk = (jax.random.fold_in(k, g * m_every + m_every - 1)
                      if k is not None else None)
                xc, a = _moe_block_apply(pl, pm_g, xc, heads=heads,
                                         top_k=top_k,
                                         capacity_factor=cap_f,
                                         dropout_rate=rate, key=lk,
                                         attn=attn,
                                         segment_ids=seg_pair,
                                         ep_axis=ep_axis,
                                         ep_impl=ep_impl)
                return (xc, auxc + a), None

            (out, aux), _ = jax.lax.scan(
                body, (xs, jnp.zeros((), jnp.float32)),
                (pa, pf, pm, jnp.arange(gl)))
            return out, aux

        if pipelined and self.schedule == "interleaved":
            # Virtual stages: the executor reinterprets each device's
            # contiguous P('pipe') slice as `virtual` chunks (global
            # stage j*S + d — chunk-PERMUTED storage,
            # interleaved_layer_order; to_transformer_lm_params takes
            # (pipe, virtual) to unstack such checkpoints). Packed
            # segment ids ride the executor's `extra` input and MoE
            # composes too (chunks hold whole super-layers; aux via
            # with_aux, EP via ep_axis + the uniform backward); SP
            # stays with gpipe/1f1b — interleaved's contribution is
            # the ~v-fold smaller bubble (create_model rejects it).
            if sp:
                raise ValueError(
                    "pp_schedule='interleaved' does not compose with "
                    "SP attention — use gpipe/1f1b for dp x sp x pp")
            pspecs = None
            kw = {}
            if ep_axis is not None:
                from tpunet.parallel.tp import pp_stack_spec
                pspecs = {kk: pp_stack_spec("blocks_" + kk)
                          for kk in blocks}
                kw["ep_axis"] = ep_axis
            x = interleaved(stage_apply, blocks, x, mesh=self.mesh,
                            n_micro=self.n_micro,
                            n_virtual=self.virtual, key=key,
                            extra=segment_ids, with_aux=moe,
                            param_specs=pspecs, **kw)
        elif pipelined:
            executor = onef1b if self.schedule == "1f1b" else gpipe
            pspecs = None
            kw = {}
            if ep_axis is not None:
                # One source of truth for the stack shardings: the
                # same path rules the Trainer stores params under
                # (tpunet/parallel/tp.py VIT_PP_RULES).
                from tpunet.parallel.tp import pp_stack_spec
                pspecs = {kk: pp_stack_spec("blocks_" + kk)
                          for kk in blocks}
            if self.schedule == "1f1b":
                # the manual backward completes per-tick cotangents
                # over the EP axis itself and resolves its own
                # uniform_bwd from seq/ep (onef1b's ep_axis note)
                kw["ep_axis"] = ep_axis
            x = executor(stage_apply, blocks, x, mesh=self.mesh,
                         n_micro=self.n_micro, key=key,
                         seq_axis="seq" if sp else None,
                         with_aux=moe, extra=segment_ids,
                         param_specs=pspecs, **kw)
        else:
            args = (x,) if segment_ids is None else (x, segment_ids)
            x = (stage_apply(blocks, *args) if key is None
                 else stage_apply(blocks, *args, key))
        if moe:
            # One scalar for the whole program: sum over layers, and
            # with pipe > 1 the executor's mean over microbatch-shards
            # (tpunet/parallel/pp.py gpipe docstring). Sown into the
            # standard 'losses' collection, so the train step's
            # _aux_term picks it up exactly like MoeMlp's sow.
            x, aux = x
            self.sow("losses", "moe_aux", aux)

        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln")(x)
        if return_hidden:
            return x.astype(jnp.float32)
        logits = embed.attend(x.astype(self.param_dtype))
        return logits.astype(jnp.float32)


def to_transformer_lm_params(params: dict, *, pipe: int = None,
                             virtual: int = None) -> dict:
    """Unstack a PipelinedLM param tree into TransformerLM's layout
    (block{i:02d}/attn/..., tpunet/models/lm.py) — the two are the same
    architecture, so lm_pp training checkpoints serve through the
    TransformerLM KV-cache generation path. MoE stacks (present when
    the model was trained with --moe-experts) unstack into the
    block{i}/moe/{router, wi, bi, wo, bo} layout of MoeMlp; the MoE
    period is recovered from the stack shapes (L / G).

    ``pipe`` + ``virtual`` (interleaved checkpoints): stacks trained
    with pp_schedule='interleaved' are stored chunk-PERMUTED
    (interleaved_layer_order — device d's contiguous 'pipe' slice
    holds chunks d, S+d, ...), so unstacking them needs the training
    run's pipe-axis size and --pp-virtual to recover semantic layer
    order. Leave both None for gpipe/1f1b checkpoints."""
    if (pipe is None) != (virtual is None):
        raise ValueError("pass pipe and virtual together (both from "
                         "the interleaved training run) or neither")
    out = {"embed": params["embed"], "pos_embed": params["pos_embed"],
           "ln": params["ln"]}
    L = params["blocks_qkv_k"].shape[0]
    if pipe is not None:
        # Invert the chunk permutation per stack granularity: block
        # stacks at layer granularity [L], MoE stacks at super-layer
        # granularity [G] (chunks hold whole super-layers), dense-fc
        # stacks at [G * (m_every - 1)] expanded from the G ordering.
        order = interleaved_layer_order(L, pipe, virtual)
        invs = {L: sorted(range(L), key=order.__getitem__)}
        if "blocks_moe_wi" in params:
            G = params["blocks_moe_wi"].shape[0]
            order_g = interleaved_layer_order(G, pipe, virtual)
            inv_g = sorted(range(G), key=order_g.__getitem__)
            invs[G] = inv_g
            me = L // G
            if me > 1:
                invs[G * (me - 1)] = [g * (me - 1) + o for g in inv_g
                                      for o in range(me - 1)]
        params = {k: (v[jnp.asarray(invs[v.shape[0]])]
                      if k.startswith("blocks_") and v.shape[0] in invs
                      else v)
                  for k, v in params.items()}
    moe = "blocks_moe_wi" in params
    m_every = L // params["blocks_moe_wi"].shape[0] if moe else 0
    for i in range(L):
        block = {
            "ln1": {"scale": params["blocks_ln1s"][i],
                    "bias": params["blocks_ln1b"][i]},
            "attn": {"qkv": {"kernel": params["blocks_qkv_k"][i],
                             "bias": params["blocks_qkv_b"][i]},
                     "out": {"kernel": params["blocks_out_k"][i],
                             "bias": params["blocks_out_b"][i]}},
            "ln2": {"scale": params["blocks_ln2s"][i],
                    "bias": params["blocks_ln2b"][i]},
        }
        if moe and i % m_every == m_every - 1:
            g = i // m_every
            block["moe"] = {
                "router": {"kernel": params["blocks_moe_rk"][g],
                           "bias": params["blocks_moe_rb"][g]},
                "wi": params["blocks_moe_wi"][g],
                "bi": params["blocks_moe_bi"][g],
                "wo": params["blocks_moe_wo"][g],
                "bo": params["blocks_moe_bo"][g],
            }
        else:
            fi = ((i // m_every) * (m_every - 1) + i % m_every
                  if moe else i)
            block["mlp"] = {"fc1": {"kernel": params["blocks_fc1_k"][fi],
                                    "bias": params["blocks_fc1_b"][fi]},
                            "fc2": {"kernel": params["blocks_fc2_k"][fi],
                                    "bias": params["blocks_fc2_b"][fi]}}
        out[f"block{i:02d}"] = block
    return out


def create_model(cfg: ModelConfig, mesh=None) -> PipelinedLM:
    """Build a PipelinedLM; unsupported 'lm' features fail loudly."""
    if cfg.attention not in ("dense", "flash", "auto", "ulysses", "ring"):
        raise ValueError(
            f"lm_pp supports dense/flash/auto and ulysses/ring (SP x "
            f"PP) causal attention (got {cfg.attention!r})")
    if cfg.attention in ("ulysses", "ring"):
        if mesh is None:
            raise ValueError(
                f"attention={cfg.attention!r} requires a mesh")
        sp_size = mesh.shape.get("seq", 1)
        if (cfg.attention == "ulysses" and sp_size > 1
                and cfg.vit_heads % sp_size):
            raise ValueError(
                f"--vit-heads {cfg.vit_heads} not divisible by the "
                f"mesh 'seq' axis ({sp_size}) — Ulysses re-shards "
                "heads over it (ring SP has no head constraint)")
    if cfg.moe_experts > 0:
        if cfg.moe_every < 1:
            raise ValueError(f"moe_every must be >= 1, got "
                             f"{cfg.moe_every}")
        if cfg.vit_depth % cfg.moe_every:
            raise ValueError(
                f"--vit-depth {cfg.vit_depth} not divisible by "
                f"--moe-every {cfg.moe_every}: lm_pp stacks whole "
                "super-layers (moe_every-1 dense blocks + 1 MoE block)")
        stages = mesh.shape.get("pipe", 1) if mesh is not None else 1
        if stages > 1 and (cfg.vit_depth // cfg.moe_every) % stages:
            raise ValueError(
                f"{cfg.vit_depth // cfg.moe_every} MoE super-layers "
                f"(depth {cfg.vit_depth} / moe_every {cfg.moe_every}) "
                f"not divisible by {stages} pipeline stages")
        ep = mesh.shape.get("model", 1) if mesh is not None else 1
        if stages > 1 and ep > 1 and cfg.moe_experts % ep:
            raise ValueError(
                f"--moe-experts {cfg.moe_experts} not divisible by "
                f"the mesh 'model' axis ({ep}) — EP x PP shards the "
                "expert dim over it")
    if cfg.remat:
        raise ValueError("lm_pp does not support --remat (the pipeline "
                         "scan already bounds activation memory per "
                         "stage)")
    if cfg.pp_schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pp_schedule {cfg.pp_schedule!r}; "
                         "expected gpipe|1f1b|interleaved")
    if cfg.pp_schedule == "interleaved":
        stages = mesh.shape.get("pipe", 1) if mesh is not None else 1
        if stages < 2:
            raise ValueError(
                "pp_schedule='interleaved' needs a mesh 'pipe' axis "
                "> 1 (at pipe=1 use gpipe/1f1b — the sequential "
                "fallback would have to un-permute chunk storage)")
        if cfg.pp_virtual < 2:
            raise ValueError(f"--pp-virtual must be >= 2 (got "
                             f"{cfg.pp_virtual}); v=1 IS gpipe/1f1b")
        if cfg.vit_depth % (stages * cfg.pp_virtual):
            raise ValueError(
                f"--vit-depth {cfg.vit_depth} not divisible by "
                f"{stages} stages x {cfg.pp_virtual} virtual chunks")
        if cfg.pp_microbatches % stages:
            raise ValueError(
                f"--pp-microbatches {cfg.pp_microbatches} not "
                f"divisible by the pipe axis ({stages}) — the "
                "interleaved F-stream cycles chunks per "
                "stage-count-sized microbatch group")
        if cfg.attention in ("ulysses", "ring"):
            raise ValueError(
                "pp_schedule='interleaved' does not compose with SP "
                "attention (ulysses/ring) — use gpipe/1f1b for "
                "dp x sp x pp")
        if cfg.moe_experts > 0:
            lc = cfg.vit_depth // (stages * cfg.pp_virtual)
            if lc % cfg.moe_every:
                raise ValueError(
                    f"interleaved chunks hold {lc} layers "
                    f"(depth {cfg.vit_depth} / {stages} stages / "
                    f"{cfg.pp_virtual} virtual) — not whole "
                    f"super-layers of moe_every={cfg.moe_every}")
    if mesh is not None:
        stages = mesh.shape.get("pipe", 1)
        if stages > 1 and cfg.vit_depth % stages:
            raise ValueError(
                f"--vit-depth {cfg.vit_depth} (the transformer depth "
                f"flag — for lm_pp it is the LM's layer count) is not "
                f"divisible by {stages} pipeline stages")
    return PipelinedLM(
        vocab_size=cfg.vocab_size,
        hidden=cfg.vit_hidden,
        depth=cfg.vit_depth,
        heads=cfg.vit_heads,
        mlp_ratio=cfg.vit_mlp_ratio,
        max_len=cfg.max_seq_len,
        n_micro=cfg.pp_microbatches,
        dropout_rate=cfg.dropout_rate,
        moe_experts=cfg.moe_experts,
        moe_every=cfg.moe_every,
        moe_top_k=cfg.moe_top_k,
        moe_capacity_factor=cfg.moe_capacity_factor,
        moe_dispatch=cfg.moe_dispatch,
        attention=cfg.attention,
        attention_core=(None if cfg.attention_core == "auto"
                        else cfg.attention_core),
        attention_block=cfg.attention_block,
        schedule=cfg.pp_schedule,
        virtual=cfg.pp_virtual,
        mesh=mesh,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )
