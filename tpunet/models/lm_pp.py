"""Pipeline-parallel causal LM ("lm_pp").

The LM family is where pipeline parallelism earns its keep (depth grows
with model scale while the vision models stay shallow), so the decoder
gets the same treatment as tpunet/models/vit_pp.py: encoder blocks as
*stacked functional parameters* (leading ``depth`` dim, sharded over the
mesh 'pipe' axis by the path rule in tpunet/parallel/tp.py) streamed
through the GPipe executor (tpunet/parallel/pp.py) — one jitted SPMD
program, activations hopping stage-to-stage via ``lax.ppermute``.

Architecture matches tpunet/models/lm.py's TransformerLM: token
embedding + learned positions -> pre-LN causal blocks -> final LN ->
logits tied to the embedding transpose. Causality comes from the dense
attention mask inside block_apply (causal=True). With
``--attention ulysses`` or ``--attention ring`` the sequence is ALSO
sharded (SP x PP, dp x sp x pp meshes): the pipeline executor passes
the 'seq' axis through its shard_map and each stage runs its SP
collectives over that already-manual axis — Ulysses' all-to-all pair
around a locally-dense core (exact global causality: the core sees the
full sequence per head group), or the ring's per-step K/V ppermute
rotation (exact global causality via global positions,
tpunet/ops/attention.py ring_attention). Both ops are axis-name
shard_map-body functions, so no shard_map nesting is involved; pick
ulysses when the 'seq' axis size divides the head count (2
collectives/call), ring when it doesn't or when per-hop ICI traffic
must stay neighbor-only.

Dropout is fully supported: the train step's dropout rng threads
through gpipe, folded per (tick, stage, layer). Grad accumulation
composes too — the accumulation scan in steps.py wraps the whole
pipelined program (microbatching in TIME over microbatching in STAGES).

With pipe == 1 the stacked params run as a plain lax.scan over layers —
the same math, which the parity tests assert. No KV-cache decode path
in this module: generation/serving unstacks lm_pp checkpoints into the
(architecturally identical) TransformerLM via to_transformer_lm_params
(tpunet/infer/generate.py --model lm_pp); the reference has no LM
serving at all (SURVEY.md section 0 — this whole family is beyond
parity).

Measured on the v5e chip (scripts/bench_lm.py --model lm_pp, T=2048
B=8 depth=4 hidden=512): 276-290k tok/s at pipe=1 with the flash core
(--attention flash/auto; inside the pipeline's shard_map the local
kernel variant runs, outside it the custom_partitioning-wrapped one —
resolve_block_cores) — 1.85x the
unrolled DENSE TransformerLM (157k) and within 19% of the unrolled
flash one (357k); that residual scan-over-layers overhead is the price
of being shardable over 'pipe', which pays only at real multi-stage
meshes (unmeasurable on this 1-chip environment; the dp x pp dryrun
leg validates the program, not its scaling). With the dense core this
was 132k tok/s.

Schedule note: two executors (``--pp-schedule``). "gpipe" (default)
lets reverse-mode AD through the scan+ppermute emit the standard
backward pipeline (all forwards, then all backwards — its residuals
stack every per-tick intermediate). "1f1b" is the hand-written VJP
(tpunet/parallel/pp.py onef1b): the backward replays forwards and runs
backwards interleaved per microbatch in 1F1B order, holding at most
min(S, M) stage inputs live — the 1F1B activation bound — at the cost
of one rematerialized stage forward per microbatch. Same grads
(parity-tested), same bubble fraction; pick 1f1b when activation
memory, not compute, is the binding constraint.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpunet.config import ModelConfig
from tpunet.models.vit_pp import (_dropout, _stacked_lecun_normal,
                                  block_apply, resolve_block_cores)
from tpunet.ops.attention import (ring_attention, ring_self_attention,
                                  ulysses_attention,
                                  ulysses_self_attention)
from tpunet.parallel.pp import gpipe, onef1b


class PipelinedLM(nn.Module):
    """tokens [B, T] int32 -> logits [B, T, vocab] float32, pipelined."""

    vocab_size: int = 256
    hidden: int = 192
    depth: int = 6
    heads: int = 3
    mlp_ratio: float = 4.0
    max_len: int = 1024
    n_micro: int = 4
    dropout_rate: float = 0.0
    attention: str = "dense"   # dense | flash | auto | ulysses | ring
    attention_core: Any = None         # SP local core (None = auto)
    attention_block: int = 512         # blockwise/flash block inside SP
    schedule: str = "gpipe"            # gpipe | 1f1b (pp.py executors)
    mesh: Any = None                   # jax.sharding.Mesh or None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    input_kind = "tokens"              # init_variables dispatch

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if self.hidden % self.heads:
            raise ValueError(f"hidden {self.hidden} not divisible by "
                             f"{self.heads} heads")
        b, t = tokens.shape
        if t > self.max_len:
            raise ValueError(f"sequence {t} exceeds max_len {self.max_len}")
        embed = nn.Embed(self.vocab_size, self.hidden,
                         embedding_init=nn.initializers.normal(stddev=0.02),
                         param_dtype=self.param_dtype, name="embed")
        x = embed(tokens).astype(self.dtype)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, self.max_len, self.hidden), self.param_dtype)
        x = x + pos[:, :t].astype(self.dtype)

        rate = self.dropout_rate if train else 0.0
        key = self.make_rng("dropout") if rate > 0.0 else None
        if key is not None:
            x = _dropout(x, rate, self.make_rng("dropout"))

        ln_ones = nn.initializers.ones
        zeros = nn.initializers.zeros
        winit = _stacked_lecun_normal
        L, C, H = self.depth, self.hidden, int(self.hidden * self.mlp_ratio)
        blocks = {
            "ln1s": self.param("blocks_ln1s", ln_ones, (L, C),
                               self.param_dtype),
            "ln1b": self.param("blocks_ln1b", zeros, (L, C),
                               self.param_dtype),
            "qkv_k": self.param("blocks_qkv_k", winit, (L, C, 3 * C),
                                self.param_dtype),
            "qkv_b": self.param("blocks_qkv_b", zeros, (L, 3 * C),
                                self.param_dtype),
            "out_k": self.param("blocks_out_k", winit, (L, C, C),
                                self.param_dtype),
            "out_b": self.param("blocks_out_b", zeros, (L, C),
                                self.param_dtype),
            "ln2s": self.param("blocks_ln2s", ln_ones, (L, C),
                               self.param_dtype),
            "ln2b": self.param("blocks_ln2b", zeros, (L, C),
                               self.param_dtype),
            "fc1_k": self.param("blocks_fc1_k", winit, (L, C, H),
                                self.param_dtype),
            "fc1_b": self.param("blocks_fc1_b", zeros, (L, H),
                                self.param_dtype),
            "fc2_k": self.param("blocks_fc2_k", winit, (L, H, C),
                                self.param_dtype),
            "fc2_b": self.param("blocks_fc2_b", zeros, (L, C),
                                self.param_dtype),
        }
        blocks = jax.tree_util.tree_map(
            lambda a: a.astype(self.dtype), blocks)
        heads = self.heads

        pipelined = (self.mesh is not None
                     and self.mesh.shape.get("pipe", 1) > 1)
        sp = self.attention in ("ulysses", "ring")
        if sp:
            if pipelined:
                # SP x PP: runs INSIDE the pipeline's shard_map, so the
                # stage body is already device-local — both SP ops are
                # axis-name collectives over the mesh 'seq' axis:
                # Ulysses' all-to-all pair around a locally-dense core,
                # or the ring's K/V rotation (global positions keep
                # causality exact either way).
                if self.attention == "ulysses":
                    def attn(q, k, v, causal=True):
                        return ulysses_attention(
                            q, k, v, axis_name="seq", causal=causal,
                            core=self.attention_core,
                            block=self.attention_block)
                else:
                    def attn(q, k, v, causal=True):
                        return ring_attention(q, k, v, "seq",
                                              causal=causal,
                                              core=self.attention_core)
            elif self.attention == "ulysses":
                # pipe == 1: the partitioned wrapper shard_maps over
                # 'seq' per block, same as the unpipelined LM family.
                def attn(q, k, v, causal=True):
                    return ulysses_self_attention(
                        q, k, v, self.mesh, causal=causal,
                        core=self.attention_core,
                        block=self.attention_block)
            else:
                def attn(q, k, v, causal=True):
                    return ring_self_attention(q, k, v, self.mesh,
                                               causal=causal,
                                               core=self.attention_core)
        else:
            seq_core, pipe_core = resolve_block_cores(self.attention)
            attn = pipe_core if pipelined else seq_core
        sp_in_pipe = sp and pipelined

        def stage_apply(params, xs, k=None):
            if k is not None and sp_in_pipe:
                # x is seq-sharded inside the pipeline under SP
                # (ulysses or ring): without this fold every
                # sequence shard would draw
                # IDENTICAL dropout masks (correlated positions T/sp
                # apart). Dense/flash stages must NOT fold — their x is
                # replicated over 'seq' and diverging masks would break
                # the replication invariant.
                k = jax.random.fold_in(k, jax.lax.axis_index("seq"))

            def body(carry, inp):
                pl, i = inp
                lk = (jax.random.fold_in(k, i) if k is not None else None)
                return block_apply(pl, carry, heads=heads, causal=True,
                                   dropout_rate=rate, key=lk,
                                   attn=attn), None
            idx = jnp.arange(jax.tree_util.tree_leaves(params)[0].shape[0])
            out, _ = jax.lax.scan(body, xs, (params, idx))
            return out

        if pipelined:
            executor = onef1b if self.schedule == "1f1b" else gpipe
            x = executor(stage_apply, blocks, x, mesh=self.mesh,
                         n_micro=self.n_micro, key=key,
                         seq_axis="seq" if sp else None)
        else:
            x = (stage_apply(blocks, x) if key is None
                 else stage_apply(blocks, x, key))

        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln")(x)
        logits = embed.attend(x.astype(self.param_dtype))
        return logits.astype(jnp.float32)


def to_transformer_lm_params(params: dict) -> dict:
    """Unstack a PipelinedLM param tree into TransformerLM's layout
    (block{i:02d}/attn/..., tpunet/models/lm.py) — the two are the same
    architecture, so lm_pp training checkpoints serve through the
    TransformerLM KV-cache generation path."""
    out = {"embed": params["embed"], "pos_embed": params["pos_embed"],
           "ln": params["ln"]}
    L = params["blocks_qkv_k"].shape[0]
    for i in range(L):
        out[f"block{i:02d}"] = {
            "ln1": {"scale": params["blocks_ln1s"][i],
                    "bias": params["blocks_ln1b"][i]},
            "attn": {"qkv": {"kernel": params["blocks_qkv_k"][i],
                             "bias": params["blocks_qkv_b"][i]},
                     "out": {"kernel": params["blocks_out_k"][i],
                             "bias": params["blocks_out_b"][i]}},
            "ln2": {"scale": params["blocks_ln2s"][i],
                    "bias": params["blocks_ln2b"][i]},
            "mlp": {"fc1": {"kernel": params["blocks_fc1_k"][i],
                            "bias": params["blocks_fc1_b"][i]},
                    "fc2": {"kernel": params["blocks_fc2_k"][i],
                            "bias": params["blocks_fc2_b"][i]}},
        }
    return out


def create_model(cfg: ModelConfig, mesh=None) -> PipelinedLM:
    """Build a PipelinedLM; unsupported 'lm' features fail loudly."""
    if cfg.attention not in ("dense", "flash", "auto", "ulysses", "ring"):
        raise ValueError(
            f"lm_pp supports dense/flash/auto and ulysses/ring (SP x "
            f"PP) causal attention (got {cfg.attention!r})")
    if cfg.attention in ("ulysses", "ring"):
        if mesh is None:
            raise ValueError(
                f"attention={cfg.attention!r} requires a mesh")
        sp_size = mesh.shape.get("seq", 1)
        if (cfg.attention == "ulysses" and sp_size > 1
                and cfg.vit_heads % sp_size):
            raise ValueError(
                f"--vit-heads {cfg.vit_heads} not divisible by the "
                f"mesh 'seq' axis ({sp_size}) — Ulysses re-shards "
                "heads over it (ring SP has no head constraint)")
    if cfg.moe_experts > 0:
        raise ValueError("lm_pp does not support MoE blocks")
    if cfg.remat:
        raise ValueError("lm_pp does not support --remat (the pipeline "
                         "scan already bounds activation memory per "
                         "stage)")
    if cfg.pp_schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pp_schedule {cfg.pp_schedule!r}; "
                         "expected gpipe|1f1b")
    if mesh is not None:
        stages = mesh.shape.get("pipe", 1)
        if stages > 1 and cfg.vit_depth % stages:
            raise ValueError(
                f"--vit-depth {cfg.vit_depth} (the transformer depth "
                f"flag — for lm_pp it is the LM's layer count) is not "
                f"divisible by {stages} pipeline stages")
    return PipelinedLM(
        vocab_size=cfg.vocab_size,
        hidden=cfg.vit_hidden,
        depth=cfg.vit_depth,
        heads=cfg.vit_heads,
        mlp_ratio=cfg.vit_mlp_ratio,
        max_len=cfg.max_seq_len,
        n_micro=cfg.pp_microbatches,
        dropout_rate=cfg.dropout_rate,
        attention=cfg.attention,
        attention_core=(None if cfg.attention_core == "auto"
                        else cfg.attention_core),
        attention_block=cfg.attention_block,
        schedule=cfg.pp_schedule,
        mesh=mesh,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )
