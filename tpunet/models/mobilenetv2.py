"""MobileNetV2 in Flax (linen), TPU-native (NHWC, bf16 compute).

Functional equivalent of the reference model — torchvision
``models.mobilenet_v2(pretrained=True)`` with the classifier head swapped
to ``nn.Linear(in_features, 10)`` (cifar10_mpi_mobilenet_224.py:137-139,
cifar10_serial_mobilenet_224.py:70-72; 2,236,682 params for width 1.0 /
10 classes, logged at cifar_mpi_gpu128_26188.out:30) — re-implemented
from the MobileNetV2 paper recipe (Sandler et al., 2018):

  stem Conv3x3/s2(32) -> 17 inverted-residual blocks with
  (expansion t, channels c, repeats n, stride s) =
  (1,16,1,1) (6,24,2,2) (6,32,3,2) (6,64,4,2) (6,96,3,1) (6,160,3,2)
  (6,320,1,1) -> Conv1x1(1280) -> global avg pool -> dropout ->
  Linear(num_classes); ReLU6 activations, BatchNorm eps 1e-5 /
  momentum 0.1 (torch convention; flax decay 0.9).

Layout choices are TPU-first: NHWC images, channels padded by XLA onto
the MXU lanes, bfloat16 compute with float32 params/statistics. Explicit
((1,1),(1,1)) padding on 3x3 convs matches torch's padding=1 semantics
exactly (XLA 'SAME' pads stride-2 convs asymmetrically (0,1), which would
break converted-weight parity).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpunet.config import ModelConfig

# (expansion, out_channels, num_blocks, first_stride)
INVERTED_RESIDUAL_SETTINGS: Tuple[Tuple[int, int, int, int], ...] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)

# torch nn.init.kaiming_normal_(mode="fan_out") for convs; normal(0, 0.01)
# for the classifier — matching torchvision's from-scratch init so training
# without pretrained weights behaves comparably.
conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_init = nn.initializers.normal(stddev=0.01)


def _make_divisible(v: float, divisor: int = 8) -> int:
    """Round channel counts like torchvision does for width multipliers."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class PallasDepthwise(nn.Module):
    """3x3 depthwise conv through the Pallas kernel (tpunet.ops).

    Parameter name/shape ('kernel', (3, 3, 1, C)) matches nn.Conv with
    feature_group_count=C exactly, so checkpoints and converted torch
    weights are interchangeable between the two paths.
    """

    features: int
    stride: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        from tpunet.ops import depthwise_conv3x3
        kernel = self.param("kernel", conv_init, (3, 3, 1, self.features),
                            self.param_dtype)
        w = kernel[:, :, 0, :].astype(self.dtype)
        return depthwise_conv3x3(x.astype(self.dtype), w, self.stride)


class ConvBN(nn.Module):
    """Conv + BatchNorm (+ optional ReLU6), the MobileNetV2 building unit."""

    features: int
    kernel: int = 3
    stride: int = 1
    groups: int = 1
    act: bool = True
    use_pallas: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pad = (self.kernel - 1) // 2
        if (self.use_pallas and self.kernel == 3 and self.groups > 1
                and self.groups == self.features == x.shape[-1]):
            x = PallasDepthwise(self.features, self.stride, dtype=self.dtype,
                                param_dtype=self.param_dtype, name="conv")(x)
        else:
            x = nn.Conv(
                self.features,
                (self.kernel, self.kernel),
                strides=(self.stride, self.stride),
                padding=((pad, pad), (pad, pad)),
                feature_group_count=self.groups,
                use_bias=False,
                kernel_init=conv_init,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="conv",
            )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="bn",
        )(x)
        if self.act:
            x = jnp.minimum(jnp.maximum(x, 0.0), 6.0)  # ReLU6
        return x


class InvertedResidual(nn.Module):
    """Expansion -> depthwise -> linear projection, with residual add."""

    features: int
    stride: int
    expand_ratio: int
    use_pallas: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_features = x.shape[-1]
        hidden = in_features * self.expand_ratio
        y = x
        if self.expand_ratio != 1:
            y = ConvBN(hidden, kernel=1, dtype=self.dtype,
                       param_dtype=self.param_dtype, name="expand")(y, train)
        y = ConvBN(hidden, kernel=3, stride=self.stride, groups=hidden,
                   use_pallas=self.use_pallas, dtype=self.dtype,
                   param_dtype=self.param_dtype,
                   name="depthwise")(y, train)
        y = ConvBN(self.features, kernel=1, act=False, dtype=self.dtype,
                   param_dtype=self.param_dtype, name="project")(y, train)
        if self.stride == 1 and in_features == self.features:
            y = y + x
        return y


class MobileNetV2(nn.Module):
    """MobileNetV2 backbone + linear classifier head.

    __call__(x, train) expects NHWC float images (already normalized) and
    returns logits in float32. BatchNorm statistics live in the
    ``batch_stats`` collection; dropout needs an rng when train=True.
    """

    num_classes: int = 10
    width_mult: float = 1.0
    dropout_rate: float = 0.2
    use_pallas: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        stem_ch = _make_divisible(32 * self.width_mult)
        x = ConvBN(stem_ch, kernel=3, stride=2, dtype=self.dtype,
                   param_dtype=self.param_dtype, name="stem")(x, train)
        idx = 0
        for t, c, n, s in INVERTED_RESIDUAL_SETTINGS:
            out_ch = _make_divisible(c * self.width_mult)
            for i in range(n):
                x = InvertedResidual(
                    out_ch, stride=s if i == 0 else 1, expand_ratio=t,
                    use_pallas=self.use_pallas,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name=f"block{idx:02d}")(x, train)
                idx += 1
        head_ch = _make_divisible(1280 * max(1.0, self.width_mult))
        x = ConvBN(head_ch, kernel=1, dtype=self.dtype,
                   param_dtype=self.param_dtype, name="head")(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool, NHWC -> NC
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, kernel_init=dense_init,
                     dtype=self.dtype, param_dtype=self.param_dtype,
                     name="classifier")(x)
        return x.astype(jnp.float32)


def create_model(cfg: ModelConfig) -> MobileNetV2:
    if cfg.name != "mobilenet_v2":
        raise ValueError(f"unknown model {cfg.name!r}")
    return MobileNetV2(
        num_classes=cfg.num_classes,
        width_mult=cfg.width_mult,
        dropout_rate=cfg.dropout_rate,
        use_pallas=cfg.use_pallas_depthwise,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )


