"""MobileNetV2 in Flax (linen), TPU-native (NHWC, bf16 compute).

Functional equivalent of the reference model — torchvision
``models.mobilenet_v2(pretrained=True)`` with the classifier head swapped
to ``nn.Linear(in_features, 10)`` (cifar10_mpi_mobilenet_224.py:137-139,
cifar10_serial_mobilenet_224.py:70-72; 2,236,682 params for width 1.0 /
10 classes, logged at cifar_mpi_gpu128_26188.out:30) — re-implemented
from the MobileNetV2 paper recipe (Sandler et al., 2018):

  stem Conv3x3/s2(32) -> 17 inverted-residual blocks with
  (expansion t, channels c, repeats n, stride s) =
  (1,16,1,1) (6,24,2,2) (6,32,3,2) (6,64,4,2) (6,96,3,1) (6,160,3,2)
  (6,320,1,1) -> Conv1x1(1280) -> global avg pool -> dropout ->
  Linear(num_classes); ReLU6 activations, BatchNorm eps 1e-5 /
  momentum 0.1 (torch convention; flax decay 0.9).

Layout choices are TPU-first: NHWC images, channels padded by XLA onto
the MXU lanes, bfloat16 compute with float32 params/statistics. Explicit
((1,1),(1,1)) padding on 3x3 convs matches torch's padding=1 semantics
exactly (XLA 'SAME' pads stride-2 convs asymmetrically (0,1), which would
break converted-weight parity).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpunet.config import ModelConfig

# (expansion, out_channels, num_blocks, first_stride)
INVERTED_RESIDUAL_SETTINGS: Tuple[Tuple[int, int, int, int], ...] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)

# torch nn.init.kaiming_normal_(mode="fan_out") for convs; normal(0, 0.01)
# for the classifier — matching torchvision's from-scratch init so training
# without pretrained weights behaves comparably.
conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_init = nn.initializers.normal(stddev=0.01)

# BatchNorm hyperparameters (torch momentum 0.1 == flax decay 0.9) —
# single source of truth for every BN path (nn.BatchNorm, FusedBNAct,
# _FusedIRBN): the fused paths promise checkpoint/numerics parity with
# the plain path, which a per-call-site literal drifting would break.
BN_MOMENTUM = 0.9
BN_EPSILON = 1e-5


def _make_divisible(v: float, divisor: int = 8) -> int:
    """Round channel counts like torchvision does for width multipliers."""
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class PallasDepthwise(nn.Module):
    """3x3 depthwise conv through the Pallas kernel (tpunet.ops).

    Parameter name/shape ('kernel', (3, 3, 1, C)) matches nn.Conv with
    feature_group_count=C exactly, so checkpoints and converted torch
    weights are interchangeable between the two paths.
    """

    features: int
    stride: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        from tpunet.ops import depthwise_conv3x3
        kernel = self.param("kernel", conv_init, (3, 3, 1, self.features),
                            self.param_dtype)
        w = kernel[:, :, 0, :].astype(self.dtype)
        return depthwise_conv3x3(x.astype(self.dtype), w, self.stride)


class FusedBNAct(nn.Module):
    """Train-mode BatchNorm + optional ReLU6 as ONE fusable region.

    Byte-level restructuring of ``nn.BatchNorm`` + separate clamp for
    an HBM-bound model (same math, same variable layout — 'scale'/
    'bias' params and 'mean'/'var' float32 batch_stats — so
    checkpoints and converted torch weights are interchangeable with
    the ``nn.BatchNorm`` path):

    - the batch-stat reduction is a single pass (mean of x and of x*x
      reduced together, Var = E[x^2] - E[x]^2 like flax's
      use_fast_variance) — one read of the activation;
    - normalize, scale/shift, and clamp are folded into one
      per-channel FMA + clamp (y = x * inv + shift with inv/shift
      precomputed per channel in f32), one read + one write of the
      activation with no separate normalized-activation round-trip;
    - bf16 residency: the written activation is exactly
      ``self.dtype`` (asserted), statistics stay f32.

    The remaining second read of the activation (stats pass +
    normalize pass) is inherent to training BatchNorm; everything else
    is elementwise in one fusable region.
    """

    act: bool = True
    momentum: float = BN_MOMENTUM
    epsilon: float = BN_EPSILON
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32), (c,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32), (c,))
        if train:
            axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axes)
            # Same fusion reduces both sums in one pass over x.
            var = jnp.maximum(0.0, jnp.mean(xf * xf, axes) - mean * mean)
            # Named for the block-remat saved-residual policy: the
            # (C,)-sized stats are saved so the backward replay never
            # re-reduces a full activation (see MobileNetV2.__call__).
            from jax.ad_checkpoint import checkpoint_name
            mean = checkpoint_name(mean, "tpunet_bn_stats")
            var = checkpoint_name(var, "tpunet_bn_stats")
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        else:
            mean, var = ra_mean.value, ra_var.value
        inv = jax.lax.rsqrt(var + self.epsilon) * scale.astype(jnp.float32)
        shift = bias.astype(jnp.float32) - mean * inv
        y = x.astype(jnp.float32) * inv + shift
        if self.act:
            y = jnp.minimum(jnp.maximum(y, 0.0), 6.0)  # ReLU6
        y = y.astype(self.dtype)
        assert y.dtype == jnp.dtype(self.dtype)  # bf16 residency
        return y


class _Conv1x1Kernel(nn.Module):
    """Parameter holder for the fused-IR 1x1 conv path: the 'kernel'
    param ((1, 1, Ci, Co), same name/shape/init as ``nn.Conv`` with
    use_bias=False) lives under the same 'conv' module path, so
    checkpoints and converted torch weights are interchangeable with
    the unfused path."""

    features: int
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, in_features: int):
        return self.param("kernel", conv_init,
                          (1, 1, in_features, self.features),
                          self.param_dtype)


class _FusedIRBN(nn.Module):
    """BN affine params + running stats for the fused-IR path, living
    under the same 'bn' module path (scale/bias params, f32 mean/var
    batch_stats) as ``FusedBNAct``/``nn.BatchNorm`` — identical
    variable tree, flippable on existing checkpoints. The conv + batch
    stats + normalize/clamp all run inside
    ``tpunet.ops.fused_ir.conv1x1_bn_act`` (one-pass Pallas kernel on
    TPU where the shape pays, the exact FusedBNAct math elsewhere);
    this module contributes the parameters and consumes the returned
    batch stats for the running-average update."""

    act: bool = True
    momentum: float = BN_MOMENTUM
    epsilon: float = BN_EPSILON
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, kernel):
        c = kernel.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (c,),
                          self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32), (c,))
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32), (c,))
        from tpunet.ops import fused_ir
        y, mean, var = fused_ir.conv1x1_bn_act(
            x.astype(self.dtype), kernel[0, 0].astype(self.dtype),
            scale, bias, act=self.act, eps=self.epsilon)
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * var
        y = y.astype(self.dtype)
        assert y.dtype == jnp.dtype(self.dtype)  # bf16 residency
        return y


class ConvBN(nn.Module):
    """Conv + BatchNorm (+ optional ReLU6), the MobileNetV2 building unit.

    ``fused_bn`` (default) expresses BN + clamp through ``FusedBNAct``
    — one fusable epilogue region; off, the original ``nn.BatchNorm``
    + separate ReLU6 path (bit-compatible variable trees either way).
    ``fused_ir`` (default, train-mode 1x1 convs only) additionally
    routes conv + batch stats through the one-pass fused-IR kernel
    (tpunet/ops/fused_ir.py): the training-BN statistics read of the
    conv output never hits HBM, and the backward recomputes the
    epilogue in VMEM. Eval mode always takes the plain path, so eval
    logits are bit-identical across the flag.
    """

    features: int
    kernel: int = 3
    stride: int = 1
    groups: int = 1
    act: bool = True
    use_pallas: bool = False
    fused_bn: bool = True
    fused_ir: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if (self.fused_ir and self.fused_bn and train
                and self.kernel == 1 and self.stride == 1
                and self.groups == 1):
            kernel = _Conv1x1Kernel(self.features,
                                    param_dtype=self.param_dtype,
                                    name="conv")(x.shape[-1])
            return _FusedIRBN(act=self.act, momentum=BN_MOMENTUM,
                              epsilon=BN_EPSILON,
                              dtype=self.dtype,
                              param_dtype=self.param_dtype,
                              name="bn")(x, kernel)
        pad = (self.kernel - 1) // 2
        if (self.use_pallas and self.kernel == 3 and self.groups > 1
                and self.groups == self.features == x.shape[-1]):
            x = PallasDepthwise(self.features, self.stride, dtype=self.dtype,
                                param_dtype=self.param_dtype, name="conv")(x)
        else:
            x = nn.Conv(
                self.features,
                (self.kernel, self.kernel),
                strides=(self.stride, self.stride),
                padding=((pad, pad), (pad, pad)),
                feature_group_count=self.groups,
                use_bias=False,
                kernel_init=conv_init,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="conv",
            )(x)
        # Conv outputs are the ONLY activation-sized residuals the
        # block-remat policy keeps: the forward materializes them
        # regardless (they feed the next conv), so saving them is
        # free, and the backward replay recomputes just the
        # elementwise BN/ReLU6 epilogues from them (no conv re-runs).
        from jax.ad_checkpoint import checkpoint_name
        x = checkpoint_name(x, "tpunet_convout")
        if self.fused_bn:
            return FusedBNAct(act=self.act, momentum=BN_MOMENTUM,
                              epsilon=BN_EPSILON,
                              dtype=self.dtype,
                              param_dtype=self.param_dtype,
                              name="bn")(x, train)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=BN_MOMENTUM,
            epsilon=BN_EPSILON,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="bn",
        )(x)
        if self.act:
            x = jnp.minimum(jnp.maximum(x, 0.0), 6.0)  # ReLU6
        return x


class InvertedResidual(nn.Module):
    """Expansion -> depthwise -> linear projection, with residual add."""

    features: int
    stride: int
    expand_ratio: int
    use_pallas: bool = False
    fused_bn: bool = True
    fused_ir: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_features = x.shape[-1]
        hidden = in_features * self.expand_ratio
        y = x
        if self.expand_ratio != 1:
            y = ConvBN(hidden, kernel=1, fused_bn=self.fused_bn,
                       fused_ir=self.fused_ir, dtype=self.dtype,
                       param_dtype=self.param_dtype, name="expand")(y, train)
        y = ConvBN(hidden, kernel=3, stride=self.stride, groups=hidden,
                   use_pallas=self.use_pallas, fused_bn=self.fused_bn,
                   dtype=self.dtype, param_dtype=self.param_dtype,
                   name="depthwise")(y, train)
        y = ConvBN(self.features, kernel=1, act=False,
                   fused_bn=self.fused_bn, fused_ir=self.fused_ir,
                   dtype=self.dtype,
                   param_dtype=self.param_dtype, name="project")(y, train)
        if self.stride == 1 and in_features == self.features:
            y = y + x
        return y


class MobileNetV2(nn.Module):
    """MobileNetV2 backbone + linear classifier head.

    __call__(x, train) expects NHWC float images (already normalized) and
    returns logits in float32. BatchNorm statistics live in the
    ``batch_stats`` collection; dropout needs an rng when train=True.
    """

    num_classes: int = 10
    width_mult: float = 1.0
    dropout_rate: float = 0.2
    use_pallas: bool = False
    fused_bn: bool = True
    fused_ir: bool = False
    block_remat: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        stem_ch = _make_divisible(32 * self.width_mult)
        x = ConvBN(stem_ch, kernel=3, stride=2, fused_bn=self.fused_bn,
                   dtype=self.dtype,
                   param_dtype=self.param_dtype, name="stem")(x, train)
        # Saved-residual policy: rematerialize each inverted-residual
        # block in the backward pass saving ONLY conv outputs (which
        # the forward materializes anyway — they feed the next conv)
        # and the (C,)-sized BN batch stats. The BN/ReLU6 epilogue
        # intermediates never round-trip through HBM as autodiff
        # residuals — the backward replay recomputes them elementwise
        # from the saved conv outputs (fusing into the backward
        # consumers), and no convolution is ever re-executed (the
        # nothing_saveable policy would re-run and re-WRITE every conv
        # in the replay — measurably more bytes, not fewer). Parameter
        # trees are identical with the flag off.
        Block = InvertedResidual
        if self.block_remat:
            policy = jax.checkpoint_policies.save_only_these_names(
                "tpunet_convout", "tpunet_bn_stats")
            Block = nn.remat(InvertedResidual, static_argnums=(2,),
                             policy=policy)
        idx = 0
        for t, c, n, s in INVERTED_RESIDUAL_SETTINGS:
            out_ch = _make_divisible(c * self.width_mult)
            for i in range(n):
                x = Block(
                    out_ch, stride=s if i == 0 else 1, expand_ratio=t,
                    use_pallas=self.use_pallas, fused_bn=self.fused_bn,
                    fused_ir=self.fused_ir,
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name=f"block{idx:02d}")(x, train)
                idx += 1
        head_ch = _make_divisible(1280 * max(1.0, self.width_mult))
        x = ConvBN(head_ch, kernel=1, fused_bn=self.fused_bn,
                   dtype=self.dtype,
                   param_dtype=self.param_dtype, name="head")(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool, NHWC -> NC
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, kernel_init=dense_init,
                     dtype=self.dtype, param_dtype=self.param_dtype,
                     name="classifier")(x)
        return x.astype(jnp.float32)


def create_model(cfg: ModelConfig) -> MobileNetV2:
    if cfg.name != "mobilenet_v2":
        raise ValueError(f"unknown model {cfg.name!r}")
    if cfg.fused_ir and not cfg.fused_bn:
        # The fused-IR kernel computes the FusedBNAct epilogue math, so
        # it only engages on the fused_bn path — warn loudly rather
        # than let an A/B record claim a lever that never ran.
        import warnings
        warnings.warn("fused_ir=True has no effect with fused_bn=False "
                      "(the fused kernel computes the fused-BN epilogue); "
                      "running the plain path", stacklevel=2)
    return MobileNetV2(
        num_classes=cfg.num_classes,
        width_mult=cfg.width_mult,
        dropout_rate=cfg.dropout_rate,
        use_pallas=cfg.use_pallas_depthwise,
        fused_bn=cfg.fused_bn,
        fused_ir=cfg.fused_ir,
        block_remat=cfg.block_remat,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )


