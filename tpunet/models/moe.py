"""Mixture-of-Experts MLP with expert parallelism.

The reference is a dense CNN (SURVEY.md 2b lists EP/MoE as absent);
tpunet adds a ViT-MoE-style sparse MLP so expert parallelism is a real,
tested strategy rather than an open mesh axis. Design follows the
einsum dense-dispatch formulation (Mesh-TensorFlow / ViT-MoE / Switch):

- Router: Dense(E) over tokens -> softmax probs -> top-k experts per
  token (k=2 default), gate values renormalized over the selected k.
- Capacity: each expert processes at most C = ceil(k*N/E * factor)
  tokens; overflow tokens are dropped for that expert (their gate mass
  simply doesn't contribute — standard Switch behavior). Position in
  expert is assigned by token order via cumsum, all inside jit with
  static shapes (no sorting, no dynamic shapes — XLA/MXU friendly).
- Dispatch/combine are one-hot einsums; expert FFNs are a single
  batched einsum over the expert dim ([E, d, h] / [E, h, d] params).
- Expert parallelism = sharding the expert dim of those params over
  the mesh 'model' axis (path rules in tpunet/parallel/tp.py); GSPMD
  turns the dispatch einsums into the all-to-alls. No separate mesh
  axis needed.
- Load-balance aux loss (Shazeer et al.): E * sum_e(frac_dispatched_e
  * mean_router_prob_e), sown into the 'losses' collection; the train
  step adds cfg.moe_aux_weight * sum(losses) to the CE loss.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


def moe_apply(tokens, router_logits, wi, bi, wo, bo, *,
              top_k: int, capacity_factor: float, dtype,
              ep_axis=None) -> tuple:
    """Functional MoE MLP core: ``tokens`` [n, d] + float32 router
    logits [n, e] -> ([n, d], aux).

    The routing/dispatch/FFN math of :class:`MoeMlp` as a pure function
    of its parameters, shared by the flax module (which adds the
    router Dense, dropout and sow around it) and the stacked pipelined
    LM (tpunet/models/lm_pp.py), whose params carry a leading layer
    dim and cannot be flax submodules. Callers compute the router
    logits in float32 — gate probabilities are numerically
    load-bearing and tiny relative to the FFN cost; ``aux`` is the
    Shazeer load-balance term computed over exactly the ``n`` tokens
    given (callers decide the batch scope: global under GSPMD,
    per-shard inside shard_map).

    ``ep_axis`` (manual expert parallelism, shard_map callers): when
    given, ``wi/bi/wo/bo`` hold only this device's expert SHARD
    (global expert dim / axis size); routing/dispatch/aux run
    replicated on the GLOBAL expert count (cheap: O(n x E)), each
    device computes its local experts' FFN on its dispatch slice, and
    one ``psum`` over ``ep_axis`` assembles the output.

    Gradient correctness under manual sharding: with the output
    psummed, each device's backward sees only its LOCAL experts'
    cotangent paths (the gate path via this device's combine slice,
    the dispatched-tokens path via its xin einsum). JAX's shard_map
    AD tracks varying-manual-axes and completes those partial
    cotangents with the right psums itself — measured exact against
    the unsharded reference for every leaf (expert grads bitwise) —
    so no manual cotangent hooks are needed (an explicit
    identity-fwd/psum-bwd hook DOUBLE-counts: the vma machinery has
    already inserted the psum).
    """
    n, d = tokens.shape
    e_local = wi.shape[0]
    ep = jax.lax.psum(1, ep_axis) if ep_axis is not None else 1
    e = e_local * ep
    k = min(top_k, e)
    cap = max(k, math.ceil(k * n / e * capacity_factor))

    logits_f32 = router_logits.astype(jnp.float32)
    probs = jax.nn.softmax(logits_f32, axis=-1)

    gate_vals, expert_idx = jax.lax.top_k(probs, k)    # [n, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

    # Position of each (token, slot) inside its expert's buffer,
    # slot-major so slot-0 assignments win buffer space first.
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [n,k,e]
    flat = onehot.transpose(1, 0, 2).reshape(k * n, e)  # slot-major
    pos_flat = jnp.cumsum(flat, axis=0) * flat - 1.0    # [k*n, e]
    pos = pos_flat.reshape(k, n, e).transpose(1, 0, 2)  # [n, k, e]
    fits = (pos >= 0) & (pos < cap)

    # dispatch[n, e, c] in {0,1}; combine = dispatch * gate value.
    pos_cap = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_cap, cap, dtype=jnp.float32)
    kept = onehot * fits.astype(jnp.float32)            # [n, k, e]
    dispatch = jnp.einsum("nke,nkec->nec", kept, pos_onehot)
    combine = jnp.einsum("nke,nkec->nec",
                         kept * gate_vals[:, :, None], pos_onehot)

    # Load-balance aux loss (fraction dispatched x mean router prob).
    frac = jnp.sum(dispatch, axis=(0, 2)) / jnp.maximum(
        jnp.sum(dispatch), 1.0)                         # [e]
    mean_prob = jnp.mean(probs, axis=0)                 # [e]
    aux = e * jnp.sum(frac * mean_prob)

    # Expert FFN: one batched einsum pair over the expert dim; the
    # expert axis of wi/wo is what expert parallelism shards. Under
    # ``ep_axis`` each device runs only its expert shard's slice of
    # the dispatch/combine tensors and one psum assembles the output
    # (tokens are replicated over the axis, so no token all-to-all is
    # needed — GShard's replicated-data degenerate case).
    if ep_axis is not None:
        lo = jax.lax.axis_index(ep_axis) * e_local
        dispatch = jax.lax.dynamic_slice_in_dim(dispatch, lo, e_local, 1)
        combine = jax.lax.dynamic_slice_in_dim(combine, lo, e_local, 1)
    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype),
                     tokens.astype(dtype))
    h = jnp.einsum("ecd,edf->ecf", xin, wi.astype(dtype))
    h = nn.gelu(h + bi[:, None, :].astype(dtype))
    out = jnp.einsum("ecf,efd->ecd", h, wo.astype(dtype))
    out = out + bo[:, None, :].astype(dtype)
    y = jnp.einsum("nec,ecd->nd", combine.astype(dtype), out)
    if ep_axis is not None:
        y = jax.lax.psum(y, ep_axis)
    return y, aux


class MoeMlp(nn.Module):
    """Sparse MLP: top-k routed experts, capacity-bounded dense dispatch.

    Input/output [B, T, d] — drop-in replacement for the dense MlpBlock.
    """

    num_experts: int
    mlp_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, t, d = x.shape
        e = self.num_experts
        tokens = x.reshape(b * t, d)

        logits = nn.Dense(e, dtype=jnp.float32,
                          param_dtype=jnp.float32,
                          kernel_init=nn.initializers.normal(stddev=0.02),
                          name="router")(tokens.astype(jnp.float32))
        wi = self.param("wi", nn.initializers.variance_scaling(
            2.0, "fan_in", "truncated_normal"), (e, d, self.mlp_dim),
            self.param_dtype)
        bi = self.param("bi", nn.initializers.zeros, (e, self.mlp_dim),
                        self.param_dtype)
        wo = self.param("wo", nn.initializers.variance_scaling(
            2.0, "fan_in", "truncated_normal"), (e, self.mlp_dim, d),
            self.param_dtype)
        bo = self.param("bo", nn.initializers.zeros, (e, d),
                        self.param_dtype)
        y, aux = moe_apply(
            tokens, logits, wi, bi, wo, bo,
            top_k=self.top_k, capacity_factor=self.capacity_factor,
            dtype=self.dtype)
        self.sow("losses", "moe_aux", aux)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return y.reshape(b, t, d)
