"""Mixture-of-Experts MLP with expert parallelism.

The reference is a dense CNN (SURVEY.md 2b lists EP/MoE as absent);
tpunet adds a ViT-MoE-style sparse MLP so expert parallelism is a real,
tested strategy rather than an open mesh axis. Design follows the
einsum dense-dispatch formulation (Mesh-TensorFlow / ViT-MoE / Switch):

- Router: Dense(E) over tokens -> softmax probs -> top-k experts per
  token (k=2 default), gate values renormalized over the selected k.
- Capacity: each expert processes at most C = ceil(k*N/E * factor)
  tokens; overflow tokens are dropped for that expert (their gate mass
  simply doesn't contribute — standard Switch behavior). Position in
  expert is assigned by token order via cumsum, all inside jit with
  static shapes (no sorting, no dynamic shapes — XLA/MXU friendly).
- Dispatch/combine are one-hot einsums; expert FFNs are a single
  batched einsum over the expert dim ([E, d, h] / [E, h, d] params).
- Expert parallelism = sharding the expert dim of those params over
  the mesh 'model' axis (path rules in tpunet/parallel/tp.py).
- Load-balance aux loss (Shazeer et al.): E * sum_e(frac_dispatched_e
  * mean_router_prob_e), sown into the 'losses' collection; the train
  step adds cfg.moe_aux_weight * sum(losses) to the CE loss.

Two manual (shard_map) expert-parallel lowerings, selected by
``ep_impl`` / ``--moe-dispatch``:

- ``"alltoall"`` (preferred; ``auto`` picks it when shapes divide):
  the GShard/Switch capacity-buffer dispatch. Each device takes its
  1/ep SLICE of the (ep-replicated) token block, routes only that
  slice, builds per-global-expert capacity buffers [E, c, d], and one
  ``all_to_all`` over the expert axis ships each buffer row to the
  device that owns that expert; local FFNs run on [E/ep, ep*c, d];
  a second ``all_to_all`` returns expert outputs to the token owners
  and one ``all_gather`` restores the replicated [n, d] output.
- ``"replicated"`` (fallback, exact-global-routing semantics): every
  device routes ALL n tokens, slices dispatch/combine to its local
  experts, and one ``psum`` assembles the output.

Comm/compute accounting, per MoE layer per device (d = model dim,
n = tokens in the block, ep = expert-axis size, k*f = top_k *
capacity_factor, ring collectives, bytes = dtype width):

- replicated: psum of [n, d]  ->  2*(ep-1)/ep * n * d     bytes/layer
  (grows with n); dispatch/combine einsums cost O(n * E * c) FLOPs on
  EVERY device (replicated work).
- alltoall:   2 a2a of [E, c_l, d] + 1 all_gather of [n/ep, d]
              -> (ep-1)/ep * (2*k*f*n/ep + n) * d          bytes/layer
  — the a2a pair scales with tokens/ep (k*f*n/ep each way); only the
  boundary all_gather (restoring ep-replication for the surrounding
  dense/attention compute, at HALF a psum's cost) still scales with n.
  Dispatch/combine einsums drop to O(n/ep * E * c_l) — ep-fold less
  replicated work. Crossover vs replicated at ep ≈ 2*k*f - 2 (≈ 3 at
  the k=2, f=1.25 defaults): at ep=8 the a2a path ships 1.625x n*d vs
  psum's 1.75x ... 2x, and its routing compute is 8x cheaper. A fully
  token-sharded caller (tokens NOT replicated over the ep axis) would
  drop the all_gather term entirely; at this interface the surrounding
  per-stage compute is ep-replicated, so the boundary gather is the
  price of composing with it.

Routing-scope note: the alltoall path routes each 1/ep token slice
independently with per-slice capacity c_l = ceil(k*(n/ep)/E * f) —
the standard GShard scope — while the replicated path routes all n
tokens against one global capacity. With ample capacity (no drops) the
two produce identical outputs and identical aux (the a2a path psums
its [E]-sized count/prob statistics over the expert axis, so the aux
scope stays the full n-token block); under overflow the drop sets can
differ. Same class of documented deviation as per-microbatch-shard
routing under pipe > 1 (tpunet/models/lm_pp.py).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from tpunet.compat import shard_map


def _route(probs, k: int, e: int, cap: int):
    """Top-k capacity-bounded routing: ``probs`` [n, e] float32 ->
    (dispatch [n, e, cap], combine [n, e, cap]) in float32.

    Shared by both expert-parallel lowerings: position in each
    expert's buffer is assigned by token order via a slot-major
    cumsum (slot-0 assignments win buffer space first), overflow
    positions are dropped, and combine carries the renormalized
    top-k gate values."""
    n = probs.shape[0]
    gate_vals, expert_idx = jax.lax.top_k(probs, k)    # [n, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [n,k,e]
    flat = onehot.transpose(1, 0, 2).reshape(k * n, e)  # slot-major
    pos_flat = jnp.cumsum(flat, axis=0) * flat - 1.0    # [k*n, e]
    pos = pos_flat.reshape(k, n, e).transpose(1, 0, 2)  # [n, k, e]
    fits = (pos >= 0) & (pos < cap)

    pos_cap = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_cap, cap, dtype=jnp.float32)
    kept = onehot * fits.astype(jnp.float32)            # [n, k, e]
    dispatch = jnp.einsum("nke,nkec->nec", kept, pos_onehot)
    combine = jnp.einsum("nke,nkec->nec",
                         kept * gate_vals[:, :, None], pos_onehot)
    return dispatch, combine


def _expert_ffn(xin, wi, bi, wo, bo, dtype):
    """Batched per-expert FFN on capacity buffers ``xin`` [e, c, d]."""
    h = jnp.einsum("ecd,edf->ecf", xin, wi.astype(dtype))
    h = nn.gelu(h + bi[:, None, :].astype(dtype))
    out = jnp.einsum("ecf,efd->ecd", h, wo.astype(dtype))
    return out + bo[:, None, :].astype(dtype)


def moe_apply(tokens, router_logits, wi, bi, wo, bo, *,
              top_k: int, capacity_factor: float, dtype,
              ep_axis=None, ep_impl: str = "replicated",
              aux_axes=None) -> tuple:
    """Functional MoE MLP core: ``tokens`` [n, d] + float32 router
    logits [n, e] -> ([n, d], aux).

    The routing/dispatch/FFN math of :class:`MoeMlp` as a pure function
    of its parameters, shared by the flax module (which adds the
    router Dense, dropout and sow around it) and the stacked pipelined
    LM (tpunet/models/lm_pp.py), whose params carry a leading layer
    dim and cannot be flax submodules. Callers compute the router
    logits in float32 — gate probabilities are numerically
    load-bearing and tiny relative to the FFN cost; ``aux`` is the
    Shazeer load-balance term computed over exactly the ``n`` tokens
    given (callers decide the batch scope: global under GSPMD,
    per-shard inside shard_map).

    ``ep_axis`` (manual expert parallelism, shard_map callers): when
    given, ``wi/bi/wo/bo`` hold only this device's expert SHARD
    (global expert dim / axis size) and ``tokens`` are replicated over
    the axis. ``ep_impl`` picks the lowering (module docstring):
    ``"alltoall"`` is the GShard capacity-buffer dispatch (token work
    and a2a traffic scale with tokens/ep); ``"replicated"`` routes all
    n tokens on every device and psums the output (exact global
    routing, no token exchange — the small-scale fallback).
    ``aux_axes`` (alltoall only) widens the aux statistics' psum scope
    beyond (ep_axis,) — e.g. the unpipelined shard_map lowering passes
    its data/seq axes so aux stays the global-batch scalar GSPMD
    computes.

    Gradient correctness under manual sharding: with the output
    psummed (or a2a'd + gathered), each device's backward sees only
    its LOCAL experts' cotangent paths. JAX's shard_map AD tracks
    varying-manual-axes and completes those partial cotangents with
    the right collectives itself — measured exact against the
    unsharded reference for every leaf (expert grads bitwise) — so no
    manual cotangent hooks are needed (an explicit identity-fwd/
    psum-bwd hook DOUBLE-counts: the vma machinery has already
    inserted the psum). The 1F1B executor's hand-written backward
    handles both lowerings with one convention (tpunet/parallel/pp.py
    onef1b ep_axis): all_gather/dynamic_slice transposes
    (psum-of-shares / zero-padded partials) and the self-transposing
    all_to_alls all preserve its sums-to-truth-over-ep invariant.
    """
    if ep_impl == "alltoall":
        if ep_axis is None:
            raise ValueError("ep_impl='alltoall' requires ep_axis")
        return _moe_apply_a2a(tokens, router_logits, wi, bi, wo, bo,
                              top_k=top_k,
                              capacity_factor=capacity_factor,
                              dtype=dtype, ep_axis=ep_axis,
                              aux_axes=aux_axes)
    if ep_impl != "replicated":
        raise ValueError(f"unknown ep_impl {ep_impl!r}; "
                         "expected replicated|alltoall")
    n, d = tokens.shape
    e_local = wi.shape[0]
    ep = jax.lax.psum(1, ep_axis) if ep_axis is not None else 1
    e = e_local * ep
    k = min(top_k, e)
    cap = max(k, math.ceil(k * n / e * capacity_factor))

    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    dispatch, combine = _route(probs, k, e, cap)

    # Load-balance aux loss (fraction dispatched x mean router prob).
    frac = jnp.sum(dispatch, axis=(0, 2)) / jnp.maximum(
        jnp.sum(dispatch), 1.0)                         # [e]
    mean_prob = jnp.mean(probs, axis=0)                 # [e]
    aux = e * jnp.sum(frac * mean_prob)

    # Expert FFN: one batched einsum pair over the expert dim; the
    # expert axis of wi/wo is what expert parallelism shards. Under
    # ``ep_axis`` each device runs only its expert shard's slice of
    # the dispatch/combine tensors and one psum assembles the output
    # (tokens are replicated over the axis, so no token all-to-all is
    # needed — GShard's replicated-data degenerate case; prefer the
    # alltoall lowering past toy scales, module docstring).
    if ep_axis is not None:
        lo = jax.lax.axis_index(ep_axis) * e_local
        dispatch = jax.lax.dynamic_slice_in_dim(dispatch, lo, e_local, 1)
        combine = jax.lax.dynamic_slice_in_dim(combine, lo, e_local, 1)
    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype),
                     tokens.astype(dtype))
    out = _expert_ffn(xin, wi, bi, wo, bo, dtype)
    y = jnp.einsum("nec,ecd->nd", combine.astype(dtype), out)
    if ep_axis is not None:
        y = jax.lax.psum(y, ep_axis)
    return y, aux


def _moe_apply_a2a(tokens, router_logits, wi, bi, wo, bo, *,
                   top_k: int, capacity_factor: float, dtype,
                   ep_axis, aux_axes=None) -> tuple:
    """GShard/Switch capacity-buffer ``all_to_all`` dispatch over the
    expert axis (module docstring). ``tokens`` [n, d] replicated over
    ``ep_axis``; returns ([n, d] replicated, aux)."""
    n, d = tokens.shape
    e_local = wi.shape[0]
    ep = jax.lax.psum(1, ep_axis)           # static: the axis size
    e = e_local * ep
    if n % ep:
        raise ValueError(f"alltoall dispatch needs tokens ({n}) "
                         f"divisible by the expert axis ({ep})")
    n_l = n // ep
    idx = jax.lax.axis_index(ep_axis)
    tokens_l = jax.lax.dynamic_slice_in_dim(tokens, idx * n_l, n_l, 0)
    logits_l = jax.lax.dynamic_slice_in_dim(router_logits,
                                            idx * n_l, n_l, 0)
    k = min(top_k, e)
    cap = max(k, math.ceil(k * n_l / e * capacity_factor))

    probs = jax.nn.softmax(logits_l.astype(jnp.float32), axis=-1)
    dispatch, combine = _route(probs, k, e, cap)     # [n_l, e, cap]

    # Aux statistics psum over the expert axis (plus any caller axes),
    # so the scalar keeps the full n-token scope of the replicated
    # path despite per-slice routing — two [e]-sized collectives.
    # ``aux_axes`` WIDENS the scope: the expert axis is always
    # included (omitting it would leave per-slice counts unsummed —
    # aux diverging across ep devices).
    axes = (ep_axis,) + tuple(ax for ax in (aux_axes or ())
                              if ax != ep_axis)
    group = 1
    for ax in axes:
        group *= jax.lax.psum(1, ax)
    tot_counts = jax.lax.psum(jnp.sum(dispatch, axis=(0, 2)), axes)
    tot_probs = jax.lax.psum(jnp.sum(probs, axis=0), axes)
    frac = tot_counts / jnp.maximum(jnp.sum(tot_counts), 1.0)
    mean_prob = tot_probs / (n_l * group)
    aux = e * jnp.sum(frac * mean_prob)

    # Dispatch: per-global-expert capacity buffers from the LOCAL
    # token slice; the tiled all_to_all ships buffer rows
    # [o*e_local:(o+1)*e_local] to expert-owner o. Received dim 0
    # indexes (source shard, local expert).
    xin = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype),
                     tokens_l.astype(dtype))         # [e, cap, d]
    xin = jax.lax.all_to_all(xin, ep_axis, 0, 0, tiled=True)
    xin = (xin.reshape(ep, e_local, cap, d)
           .transpose(1, 0, 2, 3).reshape(e_local, ep * cap, d))
    out = _expert_ffn(xin, wi, bi, wo, bo, dtype)
    # Return trip: regroup by destination shard and invert the a2a;
    # dim 0 is the global expert id again, aligned with combine's.
    out = (out.reshape(e_local, ep, cap, d)
           .transpose(1, 0, 2, 3).reshape(e, cap, d))
    out = jax.lax.all_to_all(out, ep_axis, 0, 0, tiled=True)
    y = jnp.einsum("nec,ecd->nd", combine.astype(dtype), out)
    # Boundary: restore ep-replication for the surrounding compute
    # (all_gather = half a psum's bytes; a token-sharded caller could
    # skip this — module docstring accounting).
    return jax.lax.all_gather(y, ep_axis, axis=0, tiled=True), aux


def resolve_moe_dispatch(dispatch: str, *, ep: int, n_tokens: int,
                         n_experts: int) -> str:
    """Resolve a ``--moe-dispatch`` setting against static shapes.

    ``auto`` prefers ``alltoall`` whenever the shapes divide (tokens
    by the expert-axis size, experts likewise) and falls back to
    ``replicated`` otherwise; an explicit ``alltoall`` raises instead
    of silently degrading. ``ep <= 1`` always means replicated (there
    is no axis to exchange over)."""
    if dispatch not in ("auto", "alltoall", "replicated"):
        raise ValueError(f"unknown moe_dispatch {dispatch!r}; "
                         "expected auto|alltoall|replicated")
    if ep <= 1 or dispatch == "replicated":
        if dispatch == "alltoall":
            raise ValueError("moe_dispatch='alltoall' needs an expert "
                             "axis > 1 (mesh 'model')")
        return "replicated"
    ok = n_tokens % ep == 0 and n_experts % ep == 0
    if dispatch == "alltoall" and not ok:
        raise ValueError(
            f"moe_dispatch='alltoall' needs tokens ({n_tokens}) and "
            f"experts ({n_experts}) divisible by the expert axis ({ep})")
    return "alltoall" if ok else "replicated"


class MoeMlp(nn.Module):
    """Sparse MLP: top-k routed experts, capacity-bounded dense dispatch.

    Input/output [B, T, d] — drop-in replacement for the dense MlpBlock.

    ``mesh`` + ``dispatch`` (the unpipelined models' expert-parallel
    lowering, --moe-dispatch): with a mesh whose 'model' axis > 1 and
    ``dispatch`` resolving to "alltoall", the core runs inside a
    shard_map over (data, seq, model) — tokens sharded over data/seq,
    experts over 'model', the GShard a2a dispatch between them —
    instead of leaving GSPMD to partition the global-routing einsums
    (which psum token buffers over 'data'). Routing scope becomes
    per-(data x seq)-shard with per-slice capacity (the documented
    GShard deviation; aux stays the global-batch scalar via psums over
    all three axes). Falls back to the GSPMD path when the mesh or
    divisibility doesn't allow it (or dispatch="replicated")."""

    num_experts: int
    mlp_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dropout_rate: float = 0.0
    dispatch: str = "auto"             # auto | alltoall | replicated
    mesh: Any = None                   # jax.sharding.Mesh or None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    def _resolved_dispatch(self, b: int, t: int) -> str:
        """Resolve dispatch for a [b, t, d] input against the mesh:
        auto needs every involved axis to divide (batch by 'data', seq
        by 'seq', the per-shard token count and the expert count by
        'model'); explicit alltoall raises where auto falls back."""
        mesh = self.mesh
        if mesh is None or not {"data", "seq", "model"} <= set(mesh.shape):
            if self.dispatch == "alltoall":
                raise ValueError("moe_dispatch='alltoall' requires a "
                                 "mesh with data/seq/model axes")
            return "replicated"
        ep = mesh.shape["model"]
        dp = mesh.shape.get("data", 1)
        sp = mesh.shape.get("seq", 1)
        if b % dp or t % sp:
            if self.dispatch == "alltoall":
                raise ValueError(
                    f"moe_dispatch='alltoall' needs batch {b} divisible "
                    f"by the data axis ({dp}) and seq {t} by the seq "
                    f"axis ({sp})")
            return "replicated"
        return resolve_moe_dispatch(
            self.dispatch, ep=ep, n_tokens=(b // dp) * (t // sp),
            n_experts=self.num_experts)

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, t, d = x.shape
        e = self.num_experts
        tokens = x.reshape(b * t, d)

        logits = nn.Dense(e, dtype=jnp.float32,
                          param_dtype=jnp.float32,
                          kernel_init=nn.initializers.normal(stddev=0.02),
                          name="router")(tokens.astype(jnp.float32))
        wi = self.param("wi", nn.initializers.variance_scaling(
            2.0, "fan_in", "truncated_normal"), (e, d, self.mlp_dim),
            self.param_dtype)
        bi = self.param("bi", nn.initializers.zeros, (e, self.mlp_dim),
                        self.param_dtype)
        wo = self.param("wo", nn.initializers.variance_scaling(
            2.0, "fan_in", "truncated_normal"), (e, self.mlp_dim, d),
            self.param_dtype)
        bo = self.param("bo", nn.initializers.zeros, (e, d),
                        self.param_dtype)
        if self._resolved_dispatch(b, t) == "alltoall":
            y, aux = self._a2a_sharded(x, logits.reshape(b, t, e),
                                       wi, bi, wo, bo)
            y = y.reshape(b * t, d)
        else:
            y, aux = moe_apply(
                tokens, logits, wi, bi, wo, bo,
                top_k=self.top_k, capacity_factor=self.capacity_factor,
                dtype=self.dtype)
        self.sow("losses", "moe_aux", aux)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return y.reshape(b, t, d)

    def _a2a_sharded(self, x, logits, wi, bi, wo, bo):
        """shard_map the a2a core over (data, seq, model): tokens and
        router logits arrive (data x seq)-sharded and ep-replicated,
        experts 'model'-sharded; outputs shard like the input and aux
        replicates (its statistics psum over all three axes)."""
        top_k, cap_f, dtype = self.top_k, self.capacity_factor, self.dtype

        def body(x_l, lg_l, wi_l, bi_l, wo_l, bo_l):
            bl, tl, dd = x_l.shape
            y, aux = moe_apply(
                x_l.reshape(bl * tl, dd), lg_l.reshape(bl * tl, -1),
                wi_l, bi_l, wo_l, bo_l, top_k=top_k,
                capacity_factor=cap_f, dtype=dtype, ep_axis="model",
                ep_impl="alltoall", aux_axes=("data", "seq", "model"))
            return y.reshape(bl, tl, dd), aux

        tok_spec = P("data", "seq", None)
        fn = shard_map(
            body, mesh=self.mesh,
            in_specs=(tok_spec, tok_spec, P("model", None, None),
                      P("model", None), P("model", None, None),
                      P("model", None)),
            out_specs=(tok_spec, P()), check_vma=False)
        return fn(x, logits, wi, bi, wo, bo)
