"""Vision Transformer in Flax — tpunet's attention-based model family.

The reference has exactly one model (torchvision MobileNetV2,
cifar10_mpi_mobilenet_224.py:137-139). tpunet adds a ViT family because
a TPU framework's parallelism surface is defined by attention: sequence/
context parallelism (ring attention over a 'seq' mesh axis), tensor
parallelism (heads/MLP over the 'model' axis) and expert parallelism all
need a transformer to exercise them end-to-end on the same CIFAR-10
workload, trainer, checkpointing and serving stack as the CNN.

TPU-first choices:

- Pre-LN encoder, mean-pooled tokens (no CLS token: the sequence stays
  exactly ``(image/patch)**2`` long, so it divides evenly over a
  sequence-parallel mesh axis).
- bfloat16 compute / float32 params; logits float32.
- The attention implementation is injected (``attn_fn``): dense or
  blockwise for a single chip, ``ring_self_attention`` over the 'seq'
  mesh axis for sequence parallelism (tpunet/ops/attention.py). The
  module itself stays mesh-agnostic.
- QKV / output / MLP projections are single fused Dense ops — large
  matmuls for the MXU; tensor-parallel sharding of their parameters is
  applied from outside via path rules (tpunet/parallel/tp.py), not
  baked into the module.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpunet.config import ModelConfig
from tpunet.ops import blockwise_attention, dense_attention

AttnFn = Callable[..., jax.Array]  # (q, k, v) BTHD -> BTHD


@dataclasses.dataclass(frozen=True)
class PagedKV:
    """Paged KV-cache geometry (tpunet/serve continuous batching).

    The dense decode cache pins ``[B, max_seq_len]`` K/V rows per
    layer for every slot regardless of how far the slot has actually
    decoded. Paged mode replaces it with a SHARED page pool: K/V live
    in ``pages`` fixed-size pages of ``page_tokens`` tokens each, and
    every batch row addresses its tokens through a per-row page table
    (``page_table`` [B, ceil(max_seq_len/page_tokens)] int32 page
    ids). A slot then costs HBM proportional to its prompt+generated
    length, not ``max_seq_len`` — the engine (tpunet/serve/engine.py)
    owns allocation (allocate-on-advance, free-on-finish, recycling).

    Page 0 is RESERVED as the garbage page: inactive rows and the
    padded tail of a bucketed prefill scatter their writes there, and
    the host allocator never hands it to a slot — the write gate is an
    index redirect, not a select over the whole pool.

    ``dtype`` selects the page payload: "auto" stores at the compute
    dtype, "bfloat16" halves float32 payloads, "int8" quantizes each
    written token row against its own absmax with the float32 scale
    stored alongside the page (per page-row scale — a single scalar
    per page cannot absorb incremental writes without rescaling the
    whole page) and dequantizes on gather.
    """

    pages: int            # total pages INCLUDING the reserved page 0
    page_tokens: int      # tokens per page
    dtype: str = "auto"   # auto | bfloat16 | int8

    def store_dtype(self, compute_dtype):
        if self.dtype == "auto":
            return compute_dtype
        if self.dtype in ("bfloat16", "bf16"):
            return jnp.bfloat16
        if self.dtype == "int8":
            return jnp.int8
        raise ValueError(f"unknown kv dtype {self.dtype!r}")

    @property
    def quantized(self) -> bool:
        return self.dtype == "int8"


def _quantize_kv_rows(x):
    """Symmetric int8 per-row quantization of ``x`` [N, H, D]: each
    token row is scaled by its own absmax over (H, D) so one outlier
    token cannot crush every other row's resolution. Returns
    (int8 rows, float32 scale [N])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(1, 2))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xf / scale[:, None, None]), -127, 127)
    return q.astype(jnp.int8), scale


class Attention(nn.Module):
    """Multi-head self-attention with an injected core attention op.

    ``decode=True`` switches to incremental decoding against a KV cache
    (flax 'cache' collection): the call processes one new token, writes
    its K/V at the cache index, and attends over the cached prefix —
    O(T) per step instead of O(T^2) recompute. The cache buffers are
    created (sized by the input length) when the module is initialized
    with ``decode=True``; the injected attn_fn is bypassed in this mode
    (single-query attention is computed inline).

    Serving hooks (tpunet/serve continuous batching): ``positions``
    [B] int32 gives each batch row its OWN cache write index (rows
    advance independently — the slot-pool engine keeps requests at
    different depths in one batch), and generalizes the call to T >= 1
    queries per row (chunked prefill: K/V for positions
    ``positions[b] .. positions[b]+T-1`` are written in one pass,
    causally masked). ``active`` [B] bool gates the cache write per
    row — an inactive slot's cache is bit-frozen through any number of
    steps. With ``positions`` given, the module's own ``cache_index``
    is neither read nor advanced: the engine owns the clock."""

    heads: int
    attn_fn: AttnFn = dense_attention
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, decode: bool = False,
                 segment_ids=None, positions=None, active=None,
                 paged_kv=None, page_table=None):
        b, t, c = x.shape
        if c % self.heads:
            raise ValueError(
                f"hidden dim {c} not divisible by {self.heads} heads")
        head_dim = c // self.heads
        qkv = nn.Dense(3 * c, use_bias=True, dtype=self.dtype,
                       param_dtype=self.param_dtype, name="qkv")(x)
        qkv = qkv.reshape(b, t, 3, self.heads, head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if decode:
            y = self._decode_attend(q, k, v, positions, active,
                                    paged_kv, page_table)
        elif segment_ids is not None:
            # Packed sequences: same-segment masking in the core. The
            # dense/flash cores and Ulysses SP take the kwarg (packed
            # x SP composes, tpunet/ops/attention.py); ring's
            # state-merging core doesn't — config validation rejects
            # that combination up front and a TypeError backstops it.
            y = self.attn_fn(q, k, v,
                             segment_ids=(segment_ids, segment_ids))
        else:
            y = self.attn_fn(q, k, v)
        y = y.reshape(b, t, c)
        y = nn.Dense(c, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="out")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return y

    def _decode_attend(self, q, k, v, positions=None, active=None,
                       paged_kv=None, page_table=None):
        if paged_kv is not None:
            return self._paged_decode_attend(q, k, v, positions, active,
                                             paged_kv, page_table)
        is_init = not self.has_variable("cache", "cached_k")
        ck = self.variable("cache", "cached_k", jnp.zeros, k.shape, k.dtype)
        cv = self.variable("cache", "cached_v", jnp.zeros, v.shape, v.dtype)
        ci = self.variable("cache", "cache_index",
                           lambda: jnp.zeros((), jnp.int32))
        if is_init:
            # init pass (full-length dummy): the buffers are sized from
            # k/v; skip the attention core entirely (it has no params,
            # and sharded cores would impose mesh divisibility on the
            # dummy shape — decode steps never call it).
            return jnp.zeros_like(q)
        b, t = q.shape[0], q.shape[1]
        module_clock = positions is None
        if module_clock:
            # Legacy single-clock path (models.lm.generate): one shared
            # index, one token per call, module-owned advance.
            if t != 1:
                raise ValueError(
                    f"decode processes one token per call, got {t}")
            positions = jnp.broadcast_to(ci.value, (b,))

        # Per-row write of the new K/V at positions[b] .. positions[b]
        # + t - 1 (vmapped dynamic_update_slice lowers to one scatter);
        # inactive rows keep their cache bit-identical.
        def write_row(cache_row, new_row, start):
            return jax.lax.dynamic_update_slice(cache_row, new_row,
                                                (start, 0, 0))
        new_k = jax.vmap(write_row)(ck.value, k, positions)
        new_v = jax.vmap(write_row)(cv.value, v, positions)
        if active is not None:
            gate = active[:, None, None, None]
            new_k = jnp.where(gate, new_k, ck.value)
            new_v = jnp.where(gate, new_v, cv.value)
        ck.value, cv.value = new_k, new_v
        if module_clock:
            ci.value = ci.value + t
        kf, vf = ck.value, cv.value
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kf,
                       preferred_element_type=jnp.float32)
        s = s * (q.shape[-1] ** -0.5)
        # query i of row b sits at positions[b] + i; only cache entries
        # at or before it are real (causality per row).
        from tpunet.ops.attention import _NEG_INF
        qpos = positions[:, None] + jnp.arange(t)[None, :]        # [B, T]
        valid = (jnp.arange(kf.shape[1])[None, None, :]
                 <= qpos[:, :, None])                             # [B,T,K]
        s = jnp.where(valid[:, None, :, :], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        y = jnp.einsum("bhqk,bkhd->bqhd", p, vf,
                       preferred_element_type=jnp.float32)
        return y.astype(q.dtype)

    def _paged_decode_attend(self, q, k, v, positions, active,
                             paged_kv, page_table):
        """Paged decode: K/V live in a SHARED flat page pool
        ``[pages * page_tokens, H, D]`` per layer; each row's logical
        position p maps to flat row
        ``page_table[b, p // page_tokens] * page_tokens + p %
        page_tokens``. Writes are one scatter over the new rows
        (inactive rows and unallocated positions are redirected into
        the reserved garbage page 0); the attend gathers the row's
        pages back into position order and runs the exact dense masked
        attention math over them — causality (j <= qpos per row) makes
        garbage beyond each row's own written prefix invisible, the
        same invariant the dense bucketed prefill already relies on.

        int8 pages carry a float32 scale per page row (written in the
        same scatter) and dequantize on gather. The engine owns page
        allocation; this method never sees a free list.

        Prefix-cache safety contract (PR 18): the scatter only ever
        touches rows for the NEW tokens of this step — flat indices
        derived from ``positions + arange(t)``, i.e. positions >= the
        row's prefill start. Pages the engine pinned from the prefix
        cache cover positions strictly BELOW start, so shared
        refcounted pages are bitwise-frozen by construction; the
        engine enforces copy-on-write before any position inside a
        shared page could land in the scatter."""
        b, t = q.shape[0], q.shape[1]
        heads, head_dim = k.shape[2], k.shape[3]
        pt = paged_kv.page_tokens
        flat_rows = paged_kv.pages * pt
        store_dtype = paged_kv.store_dtype(k.dtype)
        is_init = not self.has_variable("cache", "cached_k")
        ck = self.variable("cache", "cached_k", jnp.zeros,
                           (flat_rows, heads, head_dim), store_dtype)
        cv = self.variable("cache", "cached_v", jnp.zeros,
                           (flat_rows, heads, head_dim), store_dtype)
        if paged_kv.quantized:
            sk = self.variable("cache", "scale_k", jnp.zeros,
                               (flat_rows,), jnp.float32)
            sv = self.variable("cache", "scale_v", jnp.zeros,
                               (flat_rows,), jnp.float32)
        if is_init:
            # Cache-creation pass (positions legitimately absent):
            # buffers sized above, attention skipped like the dense
            # init path.
            return jnp.zeros_like(q)
        if positions is None or page_table is None:
            raise ValueError("paged decode requires engine-owned "
                             "per-row positions and a page table")

        # -- write: new K/V rows scattered to their flat page rows ----
        pos_t = positions[:, None] + jnp.arange(t)[None, :]     # [B, T]
        page_slot = jnp.clip(pos_t // pt, 0, page_table.shape[1] - 1)
        page_ids = jnp.take_along_axis(page_table, page_slot, axis=1)
        flat_idx = page_ids * pt + pos_t % pt                   # [B, T]
        if active is not None:
            # Inactive rows write into the garbage page instead of
            # being where()-gated over the whole pool.
            flat_idx = jnp.where(active[:, None], flat_idx, 0)
        flat_idx = flat_idx.reshape(-1)
        k_rows = k.reshape(b * t, heads, head_dim)
        v_rows = v.reshape(b * t, heads, head_dim)
        if paged_kv.quantized:
            k_q, k_s = _quantize_kv_rows(k_rows)
            v_q, v_s = _quantize_kv_rows(v_rows)
            ck.value = ck.value.at[flat_idx].set(k_q)
            cv.value = cv.value.at[flat_idx].set(v_q)
            sk.value = sk.value.at[flat_idx].set(k_s)
            sv.value = sv.value.at[flat_idx].set(v_s)
        else:
            ck.value = ck.value.at[flat_idx].set(
                k_rows.astype(store_dtype))
            cv.value = cv.value.at[flat_idx].set(
                v_rows.astype(store_dtype))

        # -- gather: each row's pages back into position order --------
        n_page_slots = page_table.shape[1]
        rows = (page_table[:, :, None] * pt
                + jnp.arange(pt)[None, None, :]).reshape(b, -1)  # [B, K]
        kf = jnp.take(ck.value, rows, axis=0)
        vf = jnp.take(cv.value, rows, axis=0)
        if paged_kv.quantized:
            kf = kf.astype(jnp.float32) \
                * jnp.take(sk.value, rows, axis=0)[..., None, None]
            vf = vf.astype(jnp.float32) \
                * jnp.take(sv.value, rows, axis=0)[..., None, None]
        kf = kf.astype(q.dtype)
        vf = vf.astype(q.dtype)

        s = jnp.einsum("bqhd,bkhd->bhqk", q, kf,
                       preferred_element_type=jnp.float32)
        s = s * (q.shape[-1] ** -0.5)
        from tpunet.ops.attention import _NEG_INF
        qpos = pos_t                                            # [B, T]
        valid = (jnp.arange(n_page_slots * pt)[None, None, :]
                 <= qpos[:, :, None])                           # [B,T,K]
        s = jnp.where(valid[:, None, :, :], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        y = jnp.einsum("bhqk,bkhd->bqhd", p, vf,
                       preferred_element_type=jnp.float32)
        return y.astype(q.dtype)


class MlpBlock(nn.Module):
    """Transformer MLP: Dense -> GELU -> Dense."""

    mlp_dim: int
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = x.shape[-1]
        y = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="fc1")(x)
        y = nn.gelu(y)
        y = nn.Dense(c, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="fc2")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return y


class EncoderBlock(nn.Module):
    """Pre-LN block: x + Attn(LN(x)); x + Mlp(LN(x)).

    With ``moe_experts > 0`` the dense MLP is replaced by a top-k routed
    MoE MLP (tpunet/models/moe.py) — expert-parallel over the mesh
    'model' axis via the TP path rules."""

    heads: int
    mlp_dim: int
    attn_fn: AttnFn = dense_attention
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "auto"        # EP lowering (moe.py docstring)
    moe_mesh: Any = None              # mesh for the a2a EP lowering
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False, decode: bool = False,
                 segment_ids=None, positions=None, active=None,
                 paged_kv=None, page_table=None):
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln1")(x)
        x = x + Attention(self.heads, attn_fn=self.attn_fn,
                          dropout_rate=self.dropout_rate, dtype=self.dtype,
                          param_dtype=self.param_dtype,
                          name="attn")(y, train, decode, segment_ids,
                                       positions, active, paged_kv,
                                       page_table)
        y = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln2")(x)
        if self.moe_experts > 0:
            from tpunet.models.moe import MoeMlp
            mlp_out = MoeMlp(self.moe_experts, self.mlp_dim,
                             top_k=self.moe_top_k,
                             capacity_factor=self.moe_capacity_factor,
                             dispatch=self.moe_dispatch,
                             mesh=self.moe_mesh,
                             dropout_rate=self.dropout_rate,
                             dtype=self.dtype, param_dtype=self.param_dtype,
                             name="moe")(y, train)
        else:
            mlp_out = MlpBlock(self.mlp_dim, dropout_rate=self.dropout_rate,
                               dtype=self.dtype, param_dtype=self.param_dtype,
                               name="mlp")(y, train)
        return x + mlp_out


class ViT(nn.Module):
    """ViT backbone + linear head; same call signature as MobileNetV2
    (NHWC normalized images in, float32 logits out) so the trainer,
    checkpointing and serving stack are model-agnostic."""

    num_classes: int = 10
    patch_size: int = 16
    hidden: int = 192
    depth: int = 6
    heads: int = 3
    mlp_ratio: float = 4.0
    dropout_rate: float = 0.0
    attn_fn: AttnFn = dense_attention
    moe_experts: int = 0              # 0 = dense MLP everywhere
    moe_every: int = 2                # MoE in every moe_every-th block
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch: str = "auto"
    moe_mesh: Any = None
    remat: bool = False               # jax.checkpoint each block
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.moe_experts > 0 and self.moe_every < 1:
            raise ValueError(f"moe_every must be >= 1, got {self.moe_every}")
        p = self.patch_size
        if x.shape[1] % p or x.shape[2] % p:
            raise ValueError(
                f"image {x.shape[1]}x{x.shape[2]} not divisible by "
                f"patch {p}")
        x = x.astype(self.dtype)
        x = nn.Conv(self.hidden, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name="patch_embed")(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, h * w, c), self.param_dtype)
        x = x + pos.astype(self.dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # Remat: recompute each block's activations in the backward pass
        # (jax.checkpoint) — O(depth) less live memory for long contexts.
        Block = (nn.remat(EncoderBlock, static_argnums=(2,))
                 if self.remat else EncoderBlock)
        for i in range(self.depth):
            # ViT-MoE placement: sparse MLP in every moe_every-th block
            # (the later block of each pair), dense elsewhere.
            moe_here = (self.moe_experts > 0
                        and i % self.moe_every == self.moe_every - 1)
            x = Block(self.heads, int(self.hidden * self.mlp_ratio),
                             attn_fn=self.attn_fn,
                             moe_experts=self.moe_experts if moe_here else 0,
                             moe_top_k=self.moe_top_k,
                             moe_capacity_factor=self.moe_capacity_factor,
                             moe_dispatch=self.moe_dispatch,
                             moe_mesh=self.moe_mesh,
                             dropout_rate=self.dropout_rate,
                             dtype=self.dtype, param_dtype=self.param_dtype,
                             name=f"block{i:02d}")(x, train)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln")(x)
        x = jnp.mean(x, axis=1)  # mean pool over tokens
        x = nn.Dense(self.num_classes,
                     kernel_init=nn.initializers.zeros_init(),
                     dtype=self.dtype, param_dtype=self.param_dtype,
                     name="classifier")(x)
        return x.astype(jnp.float32)


# Name -> (patch, hidden, depth, heads). "vit" uses the ModelConfig's
# vit_* fields directly.
VIT_PRESETS = {
    "vit_tiny": (16, 192, 12, 3),
    "vit_small": (16, 384, 12, 6),
    "vit_base": (16, 768, 12, 12),
}


def make_attn_fn(cfg: ModelConfig, mesh=None, causal: bool = False) -> AttnFn:
    """Resolve the configured attention implementation.

    'ring' needs the mesh (sequence-parallel shard_map over its 'seq'
    axis); 'dense'/'blockwise' are mesh-free. ``causal`` is exact under
    sequence sharding (global positions, tpunet/ops/attention.py).
    """
    import functools
    if cfg.attention == "auto":
        # Measured policy (README long-context table): the flash kernel
        # wins every regime on TPU; elsewhere flash_attention itself
        # falls back to dense, so 'auto' == flash with dense semantics
        # off-TPU. Resolved at model build time.
        cfg = dataclasses.replace(
            cfg, attention=("flash" if jax.default_backend() == "tpu"
                            else "dense"))
    if cfg.attention == "dense":
        return functools.partial(dense_attention, causal=causal)
    if cfg.attention == "blockwise":
        return functools.partial(blockwise_attention,
                                 block_size=cfg.attention_block,
                                 causal=causal)
    if cfg.attention == "flash":
        from tpunet.ops.flash import flash_attention
        return functools.partial(flash_attention,
                                 block_q=cfg.attention_block,
                                 block_k=cfg.attention_block,
                                 causal=causal)
    if cfg.attention == "ring":
        if mesh is None:
            raise ValueError("attention='ring' requires a mesh")
        from tpunet.ops import ring_self_attention
        core = None if cfg.attention_core == "auto" else cfg.attention_core
        return functools.partial(ring_self_attention, mesh=mesh,
                                 causal=causal, core=core)
    if cfg.attention == "ulysses":
        if mesh is None:
            raise ValueError("attention='ulysses' requires a mesh")
        from tpunet.ops import ulysses_self_attention
        core = None if cfg.attention_core == "auto" else cfg.attention_core
        return functools.partial(ulysses_self_attention, mesh=mesh,
                                 causal=causal, core=core,
                                 block=cfg.attention_block)
    raise ValueError(f"unknown attention {cfg.attention!r}")


def create_model(cfg: ModelConfig, mesh=None) -> ViT:
    if cfg.name in VIT_PRESETS:
        patch, hidden, depth, heads = VIT_PRESETS[cfg.name]
    elif cfg.name == "vit":
        patch, hidden, depth, heads = (cfg.vit_patch, cfg.vit_hidden,
                                       cfg.vit_depth, cfg.vit_heads)
    else:
        raise ValueError(f"unknown ViT model {cfg.name!r}")
    return ViT(
        num_classes=cfg.num_classes,
        patch_size=patch,
        hidden=hidden,
        depth=depth,
        heads=heads,
        mlp_ratio=cfg.vit_mlp_ratio,
        dropout_rate=cfg.dropout_rate,
        attn_fn=make_attn_fn(cfg, mesh),
        moe_experts=cfg.moe_experts,
        moe_every=cfg.moe_every,
        moe_top_k=cfg.moe_top_k,
        moe_capacity_factor=cfg.moe_capacity_factor,
        moe_dispatch=cfg.moe_dispatch,
        moe_mesh=mesh,
        remat=cfg.remat,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )
