"""Pipeline-parallel ViT ("vit_pp").

Same architecture as tpunet/models/vit.py (pre-LN encoder, mean-pooled
tokens, linear head) but the encoder blocks are expressed as *stacked
functional parameters* — every weight has a leading ``depth`` dim — so
pipeline parallelism is just a sharding: the leading dim is split over
the mesh 'pipe' axis (path rule in tpunet/parallel/tp.py) and the GPipe
executor (tpunet/parallel/pp.py) streams microbatches through the
stages with ppermute hops.

With pipe == 1 (or mesh=None, e.g. single-chip serving) the same
stacked params run as a plain ``lax.scan`` over layers — bitwise the
same math, which is exactly what the parity tests assert.

Patch embed, final LN and the classifier head are tiny; they run
replicated on every pipe stage rather than being assigned to first/last
stages (standard trick — keeps the pipeline body uniform).

Differences from the dense ViT (documented, deliberate): the attention
core is dense, flash, or auto only — sequence parallelism lives in the
LM family (tpunet/models/lm_pp.py ulysses|ring), where sequences are
long enough to shard; flash picks the kernel variant by
context — see resolve_block_cores. Dropout IS supported: a PRNG key
threads through the GPipe executor, folded per (tick, stage, layer) —
see block_apply.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from tpunet.config import ModelConfig
from tpunet.ops import dense_attention
from tpunet.ops.flash import flash_attention, local_flash_attention
from tpunet.parallel.pp import gpipe, interleaved, onef1b


def resolve_block_cores(attention: str, block: int = 512):
    """(sequential_core, pipelined_core) for a pipeline model's blocks.

    'dense' honors the explicit request everywhere. 'blockwise' is the
    pure-JAX chunked scan (O(T x block) score memory — the bounded-
    memory core on any backend; it is mesh-free, so the same fn serves
    both contexts). 'flash'/'auto' use the fused kernel — but the
    VARIANT matters: inside the pipeline's shard_map the per-shard
    local kernel is correct (GSPMD is already done), while the
    sequential pipe==1 path runs under the top-level jit where only
    the custom_partitioning-wrapped entry keeps a batch-sharded mesh
    from all-gathering q/k/v at every layer (the failure mode
    tpunet/ops/flash.py's partitioning section documents). Both fall
    back to dense off-TPU.
    """
    if attention == "dense":
        return dense_attention, dense_attention
    if attention == "blockwise":
        import functools

        from tpunet.ops import blockwise_attention
        core = functools.partial(blockwise_attention, block_size=block)
        return core, core
    return flash_attention, local_flash_attention


def _stacked_lecun_normal(key, shape, dtype=jnp.float32):
    """lecun_normal per layer for stacked [depth, fan_in, fan_out]
    kernels: fan_in is shape[-2] only — flax's variance_scaling would
    fold the stacked depth dim into the fan, and nn.Dense in the dense
    ViT uses lecun_normal, which this matches exactly (truncated normal,
    stddev correction 1/.87962566)."""
    fan_in = shape[-2]
    std = (1.0 / fan_in) ** 0.5 / 0.87962566103423978
    return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def _layer_norm(x, scale, bias, eps=1e-6):
    # Statistics in float32 regardless of compute dtype, matching flax
    # nn.LayerNorm's upcast behavior in the dense ViT.
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def _dropout(x, rate, key):
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def attn_half_apply(p, x, *, heads, causal=False, dropout_rate=0.0,
                    key=None, attn=dense_attention, segment_ids=None):
    """The attention half of a pre-LN block: ln1 -> qkv -> ``attn`` ->
    out-projection -> dropout -> residual, then ln2. Returns
    ``(x_resid, y_ln2, mlp_key)`` — the post-residual activations, the
    ln2 output feeding whichever MLP follows (dense fc pair or the MoE
    core), and the second half of the dropout key split (None when
    dropout is off), so both block kinds share one dropout placement
    and key-split convention. ``segment_ids`` (packed sequences): a
    (q_seg, kv_seg) pair forwarded to segment-capable cores only when
    given, so SP closures without the kwarg stay usable."""
    mb, t, c = x.shape
    y = _layer_norm(x, p["ln1s"], p["ln1b"])
    qkv = y @ p["qkv_k"] + p["qkv_b"]
    qkv = qkv.reshape(mb, t, 3, heads, c // heads)
    if segment_ids is None:
        a = attn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                 causal=causal)
    else:
        a = attn(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
                 causal=causal, segment_ids=segment_ids)
    a = a.reshape(mb, t, c) @ p["out_k"] + p["out_b"]
    km = None
    if dropout_rate > 0.0 and key is not None:
        ka, km = jax.random.split(key)
        a = _dropout(a, dropout_rate, ka)
    x = x + a
    return x, _layer_norm(x, p["ln2s"], p["ln2b"]), km


def block_apply(p, x, *, heads, causal=False, dropout_rate=0.0, key=None,
                attn=dense_attention, segment_ids=None):
    """One pre-LN encoder block from a dict of per-layer params.

    Mirrors tpunet/models/vit.py's EncoderBlock: dropout (when
    ``dropout_rate > 0`` and ``key`` is given) applies after the
    attention out-projection and after the MLP's second dense, exactly
    the flax module's placements; ``causal=True`` is the LM family's
    autoregressive mask. ``attn`` is the core from
    :func:`resolve_block_cores` (dense, or the flash kernel variant
    matching the calling context)."""
    x, y, km = attn_half_apply(p, x, heads=heads, causal=causal,
                               dropout_rate=dropout_rate, key=key,
                               attn=attn, segment_ids=segment_ids)
    h = nn.gelu(y @ p["fc1_k"] + p["fc1_b"])
    h = h @ p["fc2_k"] + p["fc2_b"]
    if dropout_rate > 0.0 and km is not None:
        h = _dropout(h, dropout_rate, km)
    return x + h


class PipelinedViT(nn.Module):
    """ViT with stacked encoder params, pipelined over 'pipe'."""

    num_classes: int = 10
    patch_size: int = 4
    hidden: int = 64
    depth: int = 4
    heads: int = 4
    mlp_ratio: float = 4.0
    n_micro: int = 4
    dropout_rate: float = 0.0
    attention: str = "dense"           # dense | flash | auto
    schedule: str = "gpipe"    # gpipe | 1f1b | interleaved (pp.py)
    virtual: int = 2                   # chunks/device for interleaved
    mesh: Any = None                   # jax.sharding.Mesh or None
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.hidden % self.heads:
            raise ValueError(f"hidden {self.hidden} not divisible by "
                             f"{self.heads} heads")
        p = self.patch_size
        if x.shape[1] % p or x.shape[2] % p:
            raise ValueError(f"image {x.shape[1]}x{x.shape[2]} not "
                             f"divisible by patch {p}")
        x = x.astype(self.dtype)
        x = nn.Conv(self.hidden, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name="patch_embed")(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        pos = self.param("pos_embed", nn.initializers.normal(stddev=0.02),
                         (1, h * w, c), self.param_dtype)
        x = x + pos.astype(self.dtype)

        ln_ones = nn.initializers.ones
        zeros = nn.initializers.zeros
        winit = _stacked_lecun_normal
        L, C, H = self.depth, c, int(self.hidden * self.mlp_ratio)
        blocks = {
            "ln1s": self.param("blocks_ln1s", ln_ones, (L, C),
                               self.param_dtype),
            "ln1b": self.param("blocks_ln1b", zeros, (L, C),
                               self.param_dtype),
            "qkv_k": self.param("blocks_qkv_k", winit, (L, C, 3 * C),
                                self.param_dtype),
            "qkv_b": self.param("blocks_qkv_b", zeros, (L, 3 * C),
                                self.param_dtype),
            "out_k": self.param("blocks_out_k", winit, (L, C, C),
                                self.param_dtype),
            "out_b": self.param("blocks_out_b", zeros, (L, C),
                                self.param_dtype),
            "ln2s": self.param("blocks_ln2s", ln_ones, (L, C),
                               self.param_dtype),
            "ln2b": self.param("blocks_ln2b", zeros, (L, C),
                               self.param_dtype),
            "fc1_k": self.param("blocks_fc1_k", winit, (L, C, H),
                                self.param_dtype),
            "fc1_b": self.param("blocks_fc1_b", zeros, (L, H),
                                self.param_dtype),
            "fc2_k": self.param("blocks_fc2_k", winit, (L, H, C),
                                self.param_dtype),
            "fc2_b": self.param("blocks_fc2_b", zeros, (L, C),
                                self.param_dtype),
        }
        blocks = jax.tree_util.tree_map(
            lambda a: a.astype(self.dtype), blocks)
        heads = self.heads
        rate = self.dropout_rate if train else 0.0
        key = self.make_rng("dropout") if rate > 0.0 else None
        if key is not None:
            x = _dropout(x, rate, self.make_rng("dropout"))

        seq_core, pipe_core = resolve_block_cores(self.attention)
        pipelined = (self.mesh is not None
                     and self.mesh.shape.get("pipe", 1) > 1)
        attn = pipe_core if pipelined else seq_core

        def stage_apply(params, xs, k=None):
            def body(carry, inp):
                pl, i = inp
                lk = (jax.random.fold_in(k, i) if k is not None else None)
                return block_apply(pl, carry, heads=heads,
                                   dropout_rate=rate, key=lk,
                                   attn=attn), None
            idx = jnp.arange(jax.tree_util.tree_leaves(params)[0].shape[0])
            out, _ = jax.lax.scan(body, xs, (params, idx))
            return out

        if pipelined and self.schedule == "interleaved":
            # Virtual stages (chunk-permuted 'pipe' storage — see
            # tpunet/parallel/pp.py interleaved / lm_pp's note).
            x = interleaved(stage_apply, blocks, x, mesh=self.mesh,
                            n_micro=self.n_micro,
                            n_virtual=self.virtual, key=key)
        elif pipelined:
            executor = onef1b if self.schedule == "1f1b" else gpipe
            x = executor(stage_apply, blocks, x, mesh=self.mesh,
                         n_micro=self.n_micro, key=key)
        else:
            x = (stage_apply(blocks, x) if key is None
                 else stage_apply(blocks, x, key))

        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                         name="ln")(x)
        x = jnp.mean(x, axis=1)
        x = nn.Dense(self.num_classes,
                     kernel_init=nn.initializers.zeros_init(),
                     dtype=self.dtype, param_dtype=self.param_dtype,
                     name="classifier")(x)
        return x.astype(jnp.float32)


def create_model(cfg: ModelConfig, mesh=None) -> PipelinedViT:
    """Build a PipelinedViT. Unsupported 'vit' features fail loudly."""
    if cfg.attention not in ("dense", "flash", "auto"):
        raise ValueError(
            f"vit_pp supports dense/flash/auto attention (got "
            f"{cfg.attention!r}); sequence parallelism is the LM "
            "family's (lm/lm_pp ulysses|ring) — a 64-token patch grid "
            "has nothing to shard")
    if cfg.moe_experts > 0:
        raise ValueError("vit_pp does not support MoE blocks (the "
                         "MoE x PP composition lives in the LM "
                         "family: --model lm_pp --moe-experts N)")
    if cfg.pp_schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"unknown pp_schedule {cfg.pp_schedule!r}; "
                         "expected gpipe|1f1b|interleaved")
    if cfg.pp_schedule == "interleaved":
        stages = mesh.shape.get("pipe", 1) if mesh is not None else 1
        if stages < 2:
            raise ValueError(
                "pp_schedule='interleaved' needs a mesh 'pipe' axis "
                "> 1 (use gpipe/1f1b at pipe=1)")
        if cfg.pp_virtual < 2:
            raise ValueError(f"--pp-virtual must be >= 2 (got "
                             f"{cfg.pp_virtual})")
        if cfg.vit_depth % (stages * cfg.pp_virtual):
            raise ValueError(
                f"vit_depth {cfg.vit_depth} not divisible by "
                f"{stages} stages x {cfg.pp_virtual} virtual chunks")
        if cfg.pp_microbatches % stages:
            raise ValueError(
                f"--pp-microbatches {cfg.pp_microbatches} not "
                f"divisible by the pipe axis ({stages})")
    if cfg.remat:
        # Same contract as lm_pp: a silently-ignored memory flag is a
        # trap — the pipeline already bounds activation memory per
        # stage (use --pp-schedule 1f1b when the backward binds).
        raise ValueError("vit_pp does not support --remat (the "
                         "pipeline scan already bounds activation "
                         "memory per stage; --pp-schedule 1f1b bounds "
                         "the backward)")
    if mesh is not None:
        stages = mesh.shape.get("pipe", 1)
        if stages > 1 and cfg.vit_depth % stages:
            raise ValueError(f"vit_depth {cfg.vit_depth} not divisible by "
                             f"{stages} pipeline stages")
    return PipelinedViT(
        num_classes=cfg.num_classes,
        patch_size=cfg.vit_patch,
        hidden=cfg.vit_hidden,
        depth=cfg.vit_depth,
        heads=cfg.vit_heads,
        mlp_ratio=cfg.vit_mlp_ratio,
        n_micro=cfg.pp_microbatches,
        dropout_rate=cfg.dropout_rate,
        attention=cfg.attention,
        schedule=cfg.pp_schedule,
        virtual=cfg.pp_virtual,
        mesh=mesh,
        dtype=jnp.dtype(cfg.dtype),
        param_dtype=jnp.dtype(cfg.param_dtype),
    )
