"""Step-level observability subsystem.

One ``Observability`` object per run orchestrates the pieces:

- ``registry``   — counters / gauges / histograms (p50/p90/p99) with
  pluggable sinks: the run's ``metrics.jsonl`` (``JsonlSink``) and an
  in-memory sink for tests (``MemorySink``).
- ``spans``      — ``jax.profiler.TraceAnnotation`` context managers
  labeling step / data-wait / eval / checkpoint phases in xprof, plus
  ``WindowedProfiler`` (trace exactly steps
  ``[profile_start_step, profile_start_step + profile_num_steps)``).
- ``perf``       — analytic model FLOPs -> MFU, device peak lookup.
- ``memory``     — per-device ``memory_stats()`` gauges and the
  coordinator-side multi-host heartbeat, sampled at epoch boundaries.
- ``export``     — live off-host telemetry (StatsD/UDP, line-JSON
  HTTP) behind a bounded queue + drain thread: a dead endpoint costs
  the step path one ``put_nowait``, never a stall; overflow drops are
  counted, never silent.
- ``health``     — run-health watchdog over the same record stream:
  step stalls, NaN/spiking loss, stale heartbeats, stalled host
  threads -> ``obs_alert`` records, optionally aborting the run
  (``--halt-on-unhealthy``).
- ``flightrec``  — black-box flight recorder (default ON): crash-
  durable mmap event ring, faulthandler + native signal hooks, the
  host-thread registry, and a watcher process that assembles
  ``flightrec/crash_report.json`` when the run dies (README "Crash
  forensics").
- ``summary``    — the one summarizer ``scripts/obs_report.py`` and
  ``scripts/obs_dashboard.py`` share.

Clock discipline: all timing is ``time.perf_counter`` (monotonic);
jax dispatch is async, so per-step wall time is the host-side lap
around the dispatch call — once the dispatch queue saturates, laps
converge to true device step time — and ``block_until_ready`` fences
run at *window edges only* (profile window start/stop), never on
interior steps. Cost model: the default config (enabled, no per-step
records, no profiling) adds host-side spans and perf_counter laps per
step but NO device syncs and no record formatting; ``--no-obs``
reduces the step loop to a single predicate branch (though a
configured profile window still instruments, since tracing needs the
step hooks).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from tpunet.obs import memory as obs_memory
from tpunet.obs import perf
from tpunet.obs.health import RunUnhealthyError, Watchdog
from tpunet.obs.registry import (Counter, Gauge, Histogram, JsonlSink,
                                 MemorySink, Registry)
from tpunet.obs.spans import NULL_SPAN, WindowedProfiler, span, step_span

__all__ = [
    "Counter", "Gauge", "Histogram", "JsonlSink", "MemorySink",
    "NULL_SPAN", "Observability", "Registry", "RunUnhealthyError",
    "Watchdog", "WindowedProfiler", "perf", "span", "step_span",
]


class _RecordedSpan:
    """A trace span that also drops begin/end events into the flight
    recorder's ring — the crash tail's "which phase were we in".
    One object + two ring writes per span (~2-3 us); only built when
    a recorder is armed."""

    __slots__ = ("_inner", "_name", "_rec")

    def __init__(self, inner, name: str, rec):
        self._inner = inner
        self._name = name
        self._rec = rec

    def __enter__(self):
        self._rec.record("span", self._name)
        return self._inner.__enter__()

    def __exit__(self, *exc):
        self._rec.record("span_end", self._name)
        return self._inner.__exit__(*exc)


class Observability:
    """Run-scoped observability facade the trainer threads through.

    ``enabled`` gates all accounting and record emission;
    ``hot`` additionally covers a live profile window, so the loop
    instruments steps whenever either wants them. Everything here is
    host-side; the only device syncs this class ever issues are the
    profile-window edge fences (via the ``sync`` callable the loop
    provides).
    """

    def __init__(self, cfg, *, profile_dir: str = "",
                 checkpoint_dir: str = "", unit: str = "examples",
                 resume: bool = False):
        if cfg.step_records_every < 0:
            raise ValueError(f"obs.step_records_every must be >= 0, "
                             f"got {cfg.step_records_every}")
        if getattr(cfg, "flightrec", False) \
                and getattr(cfg, "flightrec_events", 1) < 1:
            raise ValueError(
                f"obs.flightrec_events must be >= 1 when the flight "
                f"recorder is enabled, got {cfg.flightrec_events} "
                "(use --no-flightrec to disable the recorder)")
        self.enabled = bool(cfg.enabled)
        self.unit = unit
        self.step_records_every = cfg.step_records_every
        self.registry = Registry()
        self._hist_max = getattr(cfg, "histogram_max_samples",
                                 Histogram.DEFAULT_MAX_SAMPLES)
        if self.enabled:
            # Identity stamp on every emitted record: the join keys
            # (run_id / process_index / host) that make this run's
            # stream mergeable by a fleet aggregator (tpunet/obs/agg/).
            # run_id persists next to the checkpoints, so a preemption
            # restore (resume=True) continues the SAME stream.
            import jax

            from tpunet.obs.identity import run_identity
            pidx = jax.process_index()
            self.registry.set_identity(**run_identity(
                run_id=getattr(cfg, "run_id", ""),
                directory=checkpoint_dir, resume=resume,
                process_index=pidx, persist=(pidx == 0)))
        # Black-box flight recorder (tpunet/obs/flightrec/): event
        # ring + crash handlers + host-thread registry, default ON.
        # Prior-crash detection runs FIRST: if the previous
        # incarnation of this run dir died and left a crash report,
        # it is archived now and emitted as ONE obs_crash record at
        # the first epoch (once the jsonl sink is attached).
        self.flightrec = None
        self._pending_crash = None
        if self.enabled and getattr(cfg, "flightrec", False):
            from tpunet.obs import flightrec
            rep, report_path = flightrec.prior_crash_report(
                checkpoint_dir, pidx)
            if rep is not None:
                self._pending_crash = flightrec.crash_record(
                    rep, report_path)
            self.flightrec = flightrec.install(
                checkpoint_dir, process_index=pidx,
                n_events=getattr(cfg, "flightrec_events", 1024),
                run_id=str(self.registry.identity().get("run_id", "")))
            try:
                self.flightrec.set_device_memory(
                    obs_memory.sample_memory_gauges(self.registry))
            except Exception:
                pass
        # Run-health watchdog: consumes the same host-side laps/losses
        # this facade already sees, emits obs_alert records through
        # the registry (so they reach metrics.jsonl and every live
        # exporter), and raises RunUnhealthyError when
        # --halt-on-unhealthy is set. None when obs is disabled.
        self.watchdog = None
        if self.enabled:
            import jax
            self.watchdog = Watchdog(
                cfg, self.registry,
                expected_processes=jax.process_count())
            # Emit-only wedge detector (no-op unless a heartbeat
            # budget is configured): pages through the live exporters
            # even when the training thread is stuck inside a step.
            self.watchdog.start_monitor()
        # Live exporters (statsd / line-JSON HTTP): non-blocking
        # bounded-queue sinks, coordinator-only; empty list unless
        # endpoints are configured. Flushed in close().
        self._exporters = []
        if self.enabled and getattr(cfg, "export", None) is not None:
            from tpunet.obs.export import build_exporters
            self._exporters = build_exporters(cfg.export, self.registry)
            for exporter in self._exporters:
                self.registry.add_sink(exporter)
        if ((cfg.profile_num_steps or cfg.profile_start_step)
                and not profile_dir):
            # A window knob without --profile-dir lands next to the
            # checkpoints rather than silently doing nothing: the knob
            # people reach for mid-incident should not demand a second
            # knob. (--profile-start-step alone traces from that step
            # to the end of the run.)
            profile_dir = os.path.join(checkpoint_dir or ".", "profile")
        self.profiler = WindowedProfiler(
            profile_dir, cfg.profile_start_step, cfg.profile_num_steps)
        self._run_start = time.perf_counter()
        self._flops_per_unit = 0.0
        self._last_wait = 0.0

    # -- setup ----------------------------------------------------------

    @property
    def hot(self) -> bool:
        """True when the step loop should instrument (accounting on,
        or a profile window still pending/open). The loop hoists this
        to a local per epoch, so the disabled path pays one branch per
        step."""
        return self.enabled or self.profiler.active

    def add_sink(self, sink) -> None:
        self.registry.add_sink(sink)

    def set_hbm_breakdown(self, per_image: dict) -> None:
        """Mirror a bytes/image-by-category attribution
        (tpunet/obs/hlo_bytes.per_image_breakdown) into the
        ``hbm_bytes_per_image_*`` gauge family, so exporters ship it
        and ``--obs-rule 'hbm_bytes_per_image_total > N'`` predicates
        can page on a byte regression in a live run."""
        if not self.hot or not per_image:
            return
        from tpunet.obs.hlo_bytes import emit_gauges
        emit_gauges(self.registry, per_image)

    def set_flops_per_unit(self, flops: float) -> None:
        self._flops_per_unit = float(flops)

    # -- spans ----------------------------------------------------------

    def span(self, name: str):
        if not self.hot:
            return NULL_SPAN
        if self.flightrec is not None:
            # Span begin/end also lands in the flight-recorder ring:
            # on a crash, the tail says which phase the run died in.
            return _RecordedSpan(span(name), name, self.flightrec)
        return span(name)

    def step_span(self, step: int):
        if not self.hot:
            return NULL_SPAN
        if self.flightrec is not None:
            return _RecordedSpan(step_span(step), f"step {step}",
                                 self.flightrec)
        return step_span(step)

    # -- per-step hooks (called only when ``hot``) ----------------------

    def before_step(self, step: int, sync=None) -> None:
        """Profile-window edge check; ``sync`` (block_until_ready over
        the live state) runs only when a window opens or closes at
        this step."""
        if self.profiler.active:
            self.profiler.on_step(step, sync)

    def observe_step(self, step: int, seconds: float) -> None:
        """One finished step's host lap (dispatch-side wall time).
        Feeds the watchdog's stall detector, which may raise
        ``RunUnhealthyError`` under ``--halt-on-unhealthy``."""
        if not self.enabled:
            return
        self.registry.histogram(
            "step_time_s", max_samples=self._hist_max).observe(seconds)
        every = self.step_records_every
        if every and step % every == 0:
            self.registry.emit("obs_step", {
                "step": step,
                "step_time_s": round(seconds, 6),
                "data_wait_s": round(self._last_wait, 6),
            })
        if self.watchdog is not None:
            self.watchdog.observe_step(step, seconds)

    def observe_loss(self, step: int, loss: float) -> None:
        """A loss value that is ALREADY a host float (the step-log
        line or the epoch summary) — the watchdog's NaN/spike checks
        never force a device sync of their own."""
        if self.watchdog is not None:
            self.watchdog.observe_loss(step, loss)

    def observe_data_wait(self, seconds: float) -> None:
        """Host time spent blocked on the input pipeline for one batch
        (the stall side of the stall-vs-compute split). The epoch's
        stall total is the data_wait_s histogram's window sum."""
        if not self.enabled:
            return
        self._last_wait = seconds
        self.registry.histogram(
            "data_wait_s", max_samples=self._hist_max).observe(seconds)

    # -- epoch window ----------------------------------------------------

    def begin_epoch(self, epoch: int) -> None:
        if not self.enabled:
            return
        if self._pending_crash is not None:
            # The previous incarnation of this run dir crashed and the
            # watcher left a report: emit it exactly once, now that
            # the trainer has attached the jsonl sink — the record
            # reaches metrics.jsonl, live exporters, and (through
            # them) the fleet aggregator's crash alert.
            record, self._pending_crash = self._pending_crash, None
            self.registry.counter("obs_crashes").inc()
            self.registry.emit("obs_crash", record)
        if self.flightrec is not None:
            self.flightrec.record("epoch", f"begin {epoch}")
        self.registry.reset_window()

    def end_epoch(self, *, epoch: int, step: int, units: float,
                  train_seconds: float, eval_seconds: float = 0.0,
                  partial: bool = False) -> Optional[dict]:
        """Close the epoch window: percentiles, throughput, stall
        fraction, MFU, memory gauges, heartbeat — one ``obs_epoch``
        record to every sink. Returns the record (None when
        disabled)."""
        if not self.enabled:
            return None
        reg = self.registry
        steps = reg.histogram("step_time_s").summary()
        step_total = reg.histogram("step_time_s").total
        wait_total = reg.histogram("data_wait_s").total
        busy = step_total + wait_total
        throughput = units / train_seconds if train_seconds > 0 else 0.0
        mem = obs_memory.sample_memory_gauges(reg)
        live = obs_memory.heartbeat(
            reg, time.perf_counter() - self._run_start)
        # Host-thread registry -> thread_* gauges (exporters and
        # --obs-rule predicates see them), and the flight recorder's
        # last-known device-memory / thread snapshots refresh so a
        # crash report carries this epoch's state, not the install's.
        from tpunet.obs.flightrec.threads import THREADS
        THREADS.export_gauges(reg)
        if self.flightrec is not None:
            self.flightrec.set_device_memory(mem)
            self.flightrec.refresh_threads()
        if self.watchdog is not None:
            self.watchdog.check_threads(step)
        if self.watchdog is not None:
            # Feed the liveness result BEFORE emitting the epoch
            # record: a missing_processes alert then precedes the
            # epoch row it explains in metrics.jsonl.
            self.watchdog.observe_heartbeat(live, step=step)
        # Bounded sample of the window's step-time distribution rides
        # in the record: cross-stream percentile MERGES need sample
        # points, not precomputed percentiles (a fleet p99 cannot be
        # reconstructed from per-stream p99s) — see
        # tpunet/obs/agg/merge.py for the error bound this carries.
        sample = [round(v, 6) for v in
                  reg.histogram("step_time_s").export_sample()]
        record = {
            "epoch": epoch,
            "step": step,
            "train_seconds": round(train_seconds, 4),
            "eval_seconds": round(eval_seconds, 4),
            "unit": self.unit,
            f"{self.unit}_per_sec": round(throughput, 2),
            "steps": int(steps.get("count", 0)),
            "step_time_mean_s": steps.get("mean"),
            "step_time_p50_s": steps.get("p50"),
            "step_time_p90_s": steps.get("p90"),
            "step_time_p99_s": steps.get("p99"),
            **({"step_time_approx": 1} if steps.get("approx") else {}),
            **({"step_time_sample": sample} if sample else {}),
            "input_stall_s": round(wait_total, 4),
            "stall_frac": round(wait_total / busy, 4) if busy > 0 else 0.0,
            "device_memory": mem,
            "live_processes": live,
        }
        util = perf.mfu(throughput, self._flops_per_unit)
        if util is not None:
            record["mfu"] = round(util, 4)
            # Mirror into a gauge so operator rules ("mfu < 0.3") and
            # exporters can see it — record fields are not snapshot
            # keys.
            reg.gauge("mfu").set(util)
        ckpt_saves = reg.counter("ckpt_saves").value
        if ckpt_saves:
            record["ckpt_saves"] = int(ckpt_saves)
            record["ckpt_wait_s"] = round(
                reg.counter("ckpt_wait_s").value, 4)
        if partial:
            record["partial"] = True
        reg.emit("obs_epoch", record)
        if self.watchdog is not None and self.watchdog.gauge_predicates:
            # Operator gauge rules (--obs-rule) see the same flat
            # snapshot the exporters ship, evaluated once per epoch
            # AFTER the record lands — alert-explains-record ordering.
            self.watchdog.check_gauges(step, reg.snapshot())
        return record

    # -- lifecycle -------------------------------------------------------

    def close(self, sync=None) -> None:
        """Flush a still-open profile window and drain the export
        queues (end of run / error path). Exporter close is bounded by
        the configured flush timeout, so a dead endpoint cannot wedge
        shutdown."""
        try:
            self.profiler.close(sync)
        finally:
            if self.watchdog is not None:
                self.watchdog.stop_monitor()
            for exporter in self._exporters:
                try:
                    exporter.close()
                except Exception:
                    pass
            self._exporters = []
            if self.flightrec is not None:
                # Clean shutdown: the watcher must not assemble a
                # crash report for this incarnation. Only closes the
                # global recorder if it is still ours (a newer
                # Observability may have re-armed it).
                from tpunet.obs import flightrec
                flightrec.close(self.flightrec)
                self.flightrec = None
