"""Fleet observability: merge N runs' record streams into one view.

PRs 1-2 gave one run a record stream, a watchdog, and a dashboard;
serving added per-replica ``obs_serve`` SLOs. This package is the
cross-stream layer — the MegaScale-style jump from per-host logs to
fleet-level straggler and skew detection:

- ``receiver``  — ``Aggregator``: thread-safe ingest of N concurrent
  streams (ndjson POSTs relayed by the dashboard's ``--listen`` mode,
  or offline replay of metrics.jsonl files), routed into per-stream
  digests by the ``run_id``/``process_index`` identity stamp.
- ``merge``     — the cross-stream math: counts/means merge exactly;
  percentiles merge through each stream's exported bounded sample
  with a documented rank-error bound (DKW + export striding).
- ``rollup``    — per-stream digests and the fleet rollup: merged
  step-time distribution, step-aligned straggler factor, memory
  growth trend, summed throughput, and the aggregated serve SLO view.
- ``alerts``    — ``AlertBridge``: straggler / stale-stream /
  mem-growth built-ins plus operator ``GaugePredicate`` rules, fired
  per-stream and fleet-wide as the existing ``obs_alert`` kind.

``scripts/obs_dashboard.py`` grows a fleet mode on top (multiple
metrics.jsonl paths, or ``--listen --fleet``); record kinds and fields
are documented in docs/metrics_schema.md.
"""

from __future__ import annotations

from tpunet.obs.agg.alerts import AlertBridge
from tpunet.obs.agg.receiver import Aggregator, stream_key
from tpunet.obs.agg.rollup import StreamState, fleet_rollup

__all__ = ["Aggregator", "AlertBridge", "StreamState", "fleet_rollup",
           "stream_key"]
