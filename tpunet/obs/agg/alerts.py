"""Fleet alert bridge: rollup state -> ``obs_alert`` records.

The per-run watchdog (tpunet/obs/health.py) can only see one stream;
the failure modes that live *between* streams — a straggler replica, a
stream that stopped reporting, one host's memory creeping while the
others hold flat — are detected here, from the same rollup the
dashboard renders. Alerts reuse the existing ``obs_alert`` record kind
(one page feed, whatever the scope) with two extra routing fields:
``scope`` (``fleet`` | ``stream``) and ``stream`` (the offending
stream key, when there is one).

Built-in predicates are **edge-triggered with a latch**: a condition
fires once when it becomes true and re-arms only after it clears —
deterministic under replay (no step clock exists fleet-wide to hang a
cooldown off) and quiet under a condition that persists across many
rollups. Operator ``GaugePredicate`` rules are evaluated fleet-wide
against the flat rollup and per-stream against each stream's row,
with one predicate instance per target (growth rules keep state).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tpunet.obs.health import GaugePredicate


class AlertBridge:
    """Evaluates fleet predicates over successive rollups and emits
    ``obs_alert`` records through the aggregator's registry."""

    def __init__(self, registry, *, straggler_factor: float = 2.0,
                 stream_stale_s: float = 0.0,
                 mem_growth_bytes_per_epoch: float = 0.0,
                 rules=()):
        self.registry = registry
        self.straggler_factor = straggler_factor
        self.stream_stale_s = stream_stale_s
        self.mem_growth_bytes_per_epoch = mem_growth_bytes_per_epoch
        self._rule_specs = tuple(rules)
        # Validate eagerly — a typo'd rule should fail at construction,
        # not silently never fire.
        for spec in self._rule_specs:
            GaugePredicate.parse(spec)
        self._rule_insts: Dict[tuple, GaugePredicate] = {}
        self._latched: set = set()
        # Crash alerts are count-edge-triggered, not latched: every
        # rollup that sees a stream's obs_crash count advance pages
        # once, with the cumulative count in the detail — a
        # crash-looping replica keeps paging instead of latching
        # silent after its first crash.
        self._crash_seen: Dict[str, int] = {}
        self.alerts: List[dict] = []

    # -- emission --------------------------------------------------------

    def _fire(self, reason: str, *, scope: str, stream: str = "",
              detail: Optional[dict] = None, latch_key=None) -> None:
        key = latch_key or (reason, scope, stream)
        if key in self._latched:
            return
        self._latched.add(key)
        record = {"reason": reason, "step": 0, "severity": "warn",
                  "scope": scope}
        if stream:
            record["stream"] = stream
        if detail:
            record.update(detail)
        self.alerts.append(record)
        self.registry.counter("obs_alerts").inc()
        self.registry.emit("obs_alert", record)

    def _clear(self, reason: str, scope: str, stream: str = "",
               latch_key=None) -> None:
        self._latched.discard(latch_key or (reason, scope, stream))

    # -- evaluation ------------------------------------------------------

    def check(self, rollup: dict, streams,
              now: Optional[float] = None) -> List[dict]:
        """One rollup against every predicate; returns the alerts
        fired by THIS call (all alerts accumulate in ``self.alerts``
        and in the registry's sinks)."""
        fired_before = len(self.alerts)
        self._check_crashes(streams)
        self._check_straggler(rollup)
        self._check_mem_growth(streams)
        if now is not None and self.stream_stale_s > 0:
            self._check_stale(streams, now)
        self._check_rules(rollup, streams, now)
        return self.alerts[fired_before:]

    def _check_crashes(self, streams) -> None:
        """A stream whose ``obs_crash`` count advanced since the last
        rollup crashed (and restarted) in between: page once per such
        rollup, carrying the cumulative count and the latest crash
        summary the restarted run emitted (tpunet/obs/flightrec/)."""
        for s in streams:
            seen = self._crash_seen.get(s.key, 0)
            if s.crashes <= seen:
                continue
            self._crash_seen[s.key] = s.crashes
            detail = {"count": s.crashes}
            last = s.last_crash or {}
            for field in ("cause", "signal", "report_path"):
                if last.get(field) is not None:
                    detail[field] = last[field]
            # Bypass the latch: the count edge IS the dedup.
            key = ("crash", s.key, s.crashes)
            self._fire("crash", scope="stream", stream=s.key,
                       detail=detail, latch_key=key)

    def _check_straggler(self, rollup: dict) -> None:
        factor = rollup.get("straggler_factor")
        if factor is None:
            return
        stream = rollup.get("slowest_stream", "")
        if factor > self.straggler_factor:
            # Latch per offending stream, and drop other streams'
            # straggler latches on a handoff: if replica B recovers
            # while replica C degrades (the factor never dipping below
            # threshold), C's page must not be eaten by B's latch.
            for key in [k for k in self._latched
                        if k[0] == "straggler" and k[1] != stream]:
                self._latched.discard(key)
            self._fire("straggler", scope="fleet", stream=stream,
                       latch_key=("straggler", stream), detail={
                           "step_time_p50_s":
                               rollup.get("slowest_step_time_p50_s"),
                           "fleet_median_s":
                               rollup.get("median_step_time_p50_s"),
                           "factor": factor,
                           "threshold": self.straggler_factor,
                       })
        else:
            for key in [k for k in self._latched
                        if k[0] == "straggler"]:
                self._latched.discard(key)

    def _check_mem_growth(self, streams) -> None:
        """Every stream is judged (and its latch cleared) on its OWN
        slope — judging only the fleet-worst would leave a recovered
        stream's latch set while a different stream is the current
        worst, silently eating its next real leak."""
        threshold = self.mem_growth_bytes_per_epoch
        if threshold <= 0:
            return
        for s in streams:
            slope = s.mem_growth_per_epoch()
            if slope is None:
                continue
            if slope > threshold:
                self._fire("mem_growth", scope="stream", stream=s.key,
                           detail={"slope_bytes_per_epoch":
                                   round(slope, 1),
                                   "threshold": threshold})
            else:
                self._clear("mem_growth", "stream", s.key)

    def _check_stale(self, streams, now: float) -> None:
        for s in streams:
            if s.last_seen is None:
                continue
            age = now - s.last_seen
            if age > self.stream_stale_s:
                self._fire("stream_stale", scope="stream",
                           stream=s.key, detail={
                               "age_s": round(age, 2),
                               "timeout_s": self.stream_stale_s})
            else:
                self._clear("stream_stale", "stream", s.key)

    def _check_rules(self, rollup: dict, streams,
                     now: Optional[float]) -> None:
        """Operator GaugePredicates, fleet-wide and per-stream. The
        snapshot a rule sees is the flat rollup (fleet) or the
        stream's per_stream row (stream) — the same numbers the
        dashboard shows, so a fired rule is always explainable from
        the screen."""
        if not self._rule_specs:
            return
        t = now if now is not None else 0.0
        rows = {r["stream"]: r for r in rollup.get("per_stream", [])}
        targets = [("fleet", "", rollup)]
        targets += [("stream", key, row) for key, row in rows.items()]
        for spec in self._rule_specs:
            for scope, stream, snapshot in targets:
                inst = self._rule_insts.get((spec, scope, stream))
                if inst is None:
                    inst = GaugePredicate.parse(spec)
                    self._rule_insts[(spec, scope, stream)] = inst
                detail = inst.evaluate(snapshot, t)
                latch = ("rule", spec, scope, stream)
                if detail is not None:
                    self._fire("gauge_predicate", scope=scope,
                               stream=stream, detail=detail,
                               latch_key=latch)
                else:
                    self._clear("gauge_predicate", scope, stream,
                                latch_key=latch)
