"""Cross-stream merge math: exact where exactness is possible,
bounded-error where it is not.

Counts and sums merge exactly (they are sums). Quantiles do not:
each stream exports only a bounded sample of its window
(``Histogram.export_sample``), so a merged quantile is an estimate —
but an estimate with a *known* rank-space error bound, which is the
difference between "fleet p99 is 38 ms" and a number nobody can argue
from.

The bound, stream by stream (k = exported sample size, n = window
count):

- unsaturated window (n <= reservoir bound): the reservoir holds the
  window exactly; the only loss is export striding, rank error
  <= 1/(2k) (the export keeps the values at ranks (i + 0.5)/k).
- saturated window: the reservoir is a uniform sample; by the DKW
  inequality its empirical CDF is within
  eps(k) = sqrt(ln(2/alpha) / (2k)) of the window's, with probability
  1 - alpha (we quote alpha = 0.01), plus the same striding term.

For the merged distribution F = sum_i w_i F_i (w_i = n_i / sum n),
|F_hat - F| <= sum_i w_i * eps_i — the weighted average of per-stream
bounds. ``rank_error_bound`` computes exactly that; the acceptance
test checks merged quantiles against ground truth through it.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

# (sorted sample, exact window count, saturated?) per stream.
Part = Tuple[Sequence[float], int, bool]

DKW_ALPHA = 0.01


def dkw_epsilon(k: int, alpha: float = DKW_ALPHA) -> float:
    """DKW bound on sup|F_k - F| for a k-point uniform sample, at
    confidence 1 - alpha."""
    if k < 1:
        return 1.0
    return math.sqrt(math.log(2.0 / alpha) / (2.0 * k))


def part_rank_error(sample_n: int, saturated: bool) -> float:
    """One stream's rank-space quantile error: export striding always,
    reservoir sampling only once the window saturated."""
    if sample_n < 1:
        return 1.0
    err = 1.0 / (2.0 * sample_n)
    if saturated:
        err += dkw_epsilon(sample_n)
    return err


def rank_error_bound(parts: List[Part]) -> float:
    """Weighted-average rank error of the merged quantile estimate
    (weights = exact window counts)."""
    total = sum(max(0, n) for _, n, _ in parts)
    if total <= 0:
        return 1.0
    return sum((n / total) * part_rank_error(len(s), sat)
               for s, n, sat in parts if n > 0)


def merged_mean(parts: List[Tuple[float, int]]) -> Optional[float]:
    """Exact merged mean from per-stream (mean, count) pairs."""
    total = sum(n for _, n in parts if n > 0)
    if total <= 0:
        return None
    return sum(m * n for m, n in parts if n > 0) / total


def merged_quantiles(parts: List[Part],
                     qs: Sequence[float]) -> Dict[float, float]:
    """Quantiles of the merged distribution, q in [0, 100].

    Each stream's sample points stand for count/len(sample) window
    observations apiece; the merged quantile is the weighted quantile
    over the pooled points (midpoint positions, linear interpolation
    between adjacent points — the same interpolation family as
    ``percentile_of_sorted``, degenerating to it when there is one
    stream whose sample is its whole window)."""
    pts: List[Tuple[float, float]] = []
    for sample, count, _ in parts:
        if not sample or count <= 0:
            continue
        w = count / len(sample)
        pts.extend((float(v), w) for v in sample)
    if not pts:
        return {}
    pts.sort(key=lambda p: p[0])
    total = sum(w for _, w in pts)
    # Midpoint cumulative positions: point i sits at
    # (sum of weights before it + w_i / 2) / total in [0, 1].
    positions: List[float] = []
    cum = 0.0
    for _, w in pts:
        positions.append((cum + w / 2.0) / total)
        cum += w
    out: Dict[float, float] = {}
    for q in qs:
        frac = min(1.0, max(0.0, q / 100.0))
        if frac <= positions[0]:
            out[q] = pts[0][0]
            continue
        if frac >= positions[-1]:
            out[q] = pts[-1][0]
            continue
        # Binary search for the bracketing pair, then interpolate.
        lo, hi = 0, len(positions) - 1
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if positions[mid] <= frac:
                lo = mid
            else:
                hi = mid
        span = positions[hi] - positions[lo]
        t = (frac - positions[lo]) / span if span > 0 else 0.0
        out[q] = pts[lo][0] * (1.0 - t) + pts[hi][0] * t
    return out


def record_parts(records: List[dict], sample_key: str,
                 count_key: str) -> List[Part]:
    """Extract merge parts from records carrying an exported sample
    (``<name>_sample`` lists; docs/metrics_schema.md). Records without
    the sample are skipped — a mixed-version fleet degrades to fewer
    streams, not to wrong numbers."""
    base = (sample_key[:-len("_sample")]
            if sample_key.endswith("_sample") else sample_key)
    parts: List[Part] = []
    for r in records:
        sample = r.get(sample_key)
        count = r.get(count_key)
        if not sample or not count:
            continue
        # <base>_approx marks a reservoir-saturated source window
        # (step_time_approx, ttft_approx, ...): its DKW term joins
        # the bound.
        saturated = bool(r.get(base + "_approx"))
        parts.append((sample, int(count), saturated))
    return parts
