"""Aggregation receiver: N record streams in, one fleet state out.

The ingest side accepts the two transports that already exist — the
ndjson POST bodies ``HttpLineTransport`` sends (the dashboard's
``--listen`` mode hands each parsed record here) and offline replay of
``metrics.jsonl`` files — and routes records into per-stream digests
by the identity stamp (``run_id``/``process_index``) every record now
carries. Records from a pre-identity producer fall back to the
caller's ``source`` tag (one file = one stream), so replaying old
files still works.

``ingest`` is thread-safe (the listen mode's HTTP handler threads call
it concurrently) and O(1) per record; ``rollup()`` is computed on
demand and is a pure function of the ingested records, so concurrent
live ingest and offline replay of the same streams agree exactly.
``emit_rollup()`` additionally publishes the fleet state: flat gauges
into the aggregator's own registry (so ``GaugePredicate`` rules and
exporters compose), one ``obs_fleet`` record to the registry's sinks,
and the alert bridge's ``obs_alert`` records for straggler / stale /
memory-growth / operator-rule conditions.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from tpunet.obs.agg.alerts import AlertBridge
from tpunet.obs.agg.rollup import StreamState, fleet_rollup
from tpunet.obs.registry import Registry


def stream_key(record: dict, source: str = "") -> str:
    """One stream per (run_id, process_index); identity-less records
    group by their source tag (file path / peer address)."""
    rid = record.get("run_id")
    if rid:
        return f"{rid}/{record.get('process_index', 0)}"
    return source or "anon"


class Aggregator:
    def __init__(self, *, registry: Optional[Registry] = None,
                 clock=time.monotonic,
                 straggler_factor: float = 2.0,
                 stream_stale_s: float = 0.0,
                 mem_growth_bytes_per_epoch: float = 0.0,
                 rules=()):
        self.registry = registry if registry is not None else Registry()
        self._clock = clock
        self._streams: Dict[str, StreamState] = {}
        self._lock = threading.Lock()
        self.bridge = AlertBridge(
            self.registry, straggler_factor=straggler_factor,
            stream_stale_s=stream_stale_s,
            mem_growth_bytes_per_epoch=mem_growth_bytes_per_epoch,
            rules=rules)

    # -- ingest ----------------------------------------------------------

    def ingest(self, record: dict, source: str = "",
               stamp_time: bool = True) -> None:
        """Route one record into its stream digest. ``stamp_time=False``
        is the offline-replay mode: no arrival clock is recorded, so
        replayed state is byte-identical to live state for everything
        except the (clock-derived, opt-in) staleness signals."""
        if not isinstance(record, dict):
            return
        key = stream_key(record, source)
        now = self._clock() if stamp_time else None
        with self._lock:
            state = self._streams.get(key)
            if state is None:
                state = self._streams[key] = StreamState(key, source)
            state.ingest(record, now)
        self.registry.counter("agg_records_total").inc()

    def ingest_many(self, records, source: str = "",
                    stamp_time: bool = True) -> None:
        for r in records:
            self.ingest(r, source, stamp_time)

    def replay_file(self, path: str) -> int:
        """Offline ingest of a whole metrics.jsonl (tolerates the torn
        trailing line like every other reader). Returns the record
        count."""
        from tpunet.utils.logging import MetricsLogger
        records = MetricsLogger.read_records(path)
        self.ingest_many(records, source=path, stamp_time=False)
        return len(records)

    def drop_source(self, source: str) -> None:
        """Forget every stream fed from ``source`` — the tailed file
        was truncated by a fresh run; merging two runs' records would
        corrupt every aggregate (same contract as the single-stream
        dashboard's buffer clear)."""
        with self._lock:
            self._streams = {k: s for k, s in self._streams.items()
                             if s.source != source}

    # -- views -----------------------------------------------------------

    def streams(self) -> List[StreamState]:
        with self._lock:
            return sorted(self._streams.values(), key=lambda s: s.key)

    def rollup(self) -> dict:
        # Computed under the ingest lock: handler threads mutate the
        # per-stream deques concurrently in listen mode, and iterating
        # a mutating deque raises. Pure reads — contention is one
        # O(streams) pass.
        with self._lock:
            return fleet_rollup(sorted(self._streams.values(),
                                       key=lambda s: s.key))

    def recent_alerts(self) -> List[dict]:
        """Recently ingested per-run ``obs_alert`` records (bounded
        per stream), each tagged with its stream key — the fleet
        panels surface per-run pages (thread_stalled, step_stall,
        ...) and crash records alongside the bridge's own fleet
        alerts."""
        out: List[dict] = []
        for s in self.streams():
            for a in list(s.recent_alerts):
                row = dict(a)
                row.setdefault("scope", "run")
                row.setdefault("stream", s.key)
                out.append(row)
            if s.last_crash is not None:
                row = {"reason": "crash", "scope": "run",
                       "stream": s.key, "severity": "fatal"}
                for field in ("cause", "signal", "report_path"):
                    if s.last_crash.get(field) is not None:
                        row[field] = s.last_crash[field]
                out.append(row)
        return out

    def heartbeat_ages(self) -> Dict[str, float]:
        """Seconds since each stream's last record arrived (live mode
        only — replayed streams have no arrival clock)."""
        now = self._clock()
        return {s.key: round(now - s.last_seen, 2)
                for s in self.streams() if s.last_seen is not None}

    # -- publication -----------------------------------------------------

    def emit_rollup(self, check_alerts: bool = True) -> dict:
        """Compute the rollup, mirror its flat numeric fields into the
        registry as fleet gauges, run the alert bridge, and emit one
        ``obs_fleet`` record to the registry's sinks. Returns the
        rollup (with ``fleet_alerts`` appended when any fired)."""
        streams = self.streams()
        rollup = self.rollup()
        for key, val in rollup.items():
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            self.registry.gauge(f"fleet_{key}").set(val)
        if check_alerts:
            fired = self.bridge.check(rollup, streams,
                                      now=self._clock())
            if fired:
                rollup = dict(rollup)
                rollup["fleet_alerts"] = fired
        self.registry.emit("obs_fleet", rollup)
        return rollup
