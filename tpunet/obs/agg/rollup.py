"""Per-stream state and the fleet rollup.

``StreamState`` is the bounded digest of one record stream the
receiver maintains incrementally (ingest is O(1) per record); the
rollup is computed on demand from the digests. Everything in the
rollup is a pure function of the ingested records — never of arrival
order or wall clock — so a live multi-stream ingest and an offline
replay of the same files produce the *identical* rollup (the
acceptance property the tests pin). The only clock-derived signals,
per-stream staleness ages, live in separate fields the caller opts
into (``heartbeat_ages``).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

from tpunet.obs.agg import merge

# Bounded per-stream history: enough epochs for a memory-growth trend,
# enough step records for step-aligned skew, small enough that a
# thousand-stream fleet stays in tens of MB.
EPOCH_KEEP = 64
STEP_KEEP = 512
# Per-stream trace digest bounds (obs_trace, tpunet/obs/tracing.py):
# enough phase samples for stable p99s at default 1% head sampling,
# a handful of slow-request exemplars for the dashboard panel.
TRACE_KEEP = 256
TRACE_SLOW_KEEP = 8
# Per-stream burn-rate history (obs_slo, tpunet/obs/slo.py): enough
# points for the dashboard's burn sparkline over recent emit windows.
SLO_BURN_KEEP = 64


class StreamState:
    """Rolling digest of one record stream (one (run_id,
    process_index) pair — or one replayed file)."""

    def __init__(self, key: str, source: str = ""):
        self.key = key
        self.source = source
        self.identity: Dict[str, object] = {}
        self.records = 0
        self.alerts = 0
        # Recent per-run alert/crash records (dashboard fleet panels
        # show WHAT paged, not just a count); bounded like everything
        # else here.
        self.recent_alerts: deque = deque(maxlen=8)
        self.crashes = 0
        self.last_crash: Optional[dict] = None
        self.last_seen: Optional[float] = None  # receiver clock; live only
        # Training-side digest.
        self.last_epoch: Optional[dict] = None
        self.steps_total = 0            # exact: sum of obs_epoch "steps"
        self.step_time_sum = 0.0        # exact: sum of mean * steps
        self.epoch_p50s: deque = deque(maxlen=EPOCH_KEEP)  # (epoch, p50)
        self.mem_peaks: deque = deque(maxlen=EPOCH_KEEP)   # (epoch, peak)
        self.step_laps: deque = deque(maxlen=STEP_KEEP)    # (step, lap_s)
        # Serving-side digest.
        self.last_serve: Optional[dict] = None
        self.serve_records = 0
        # Router-tier digest (tpunet/router/): the front tier's
        # window records and the evict/respawn/scale events it acted
        # on — the fleet view should say who is steering, not just
        # who is serving.
        self.last_router: Optional[dict] = None
        self.router_records = 0
        self.router_events = 0
        self.last_router_event: Optional[dict] = None
        # Trace digest (``obs_trace``): replica-hop phase samples for
        # the fleet TTFT decomposition (queue vs prefill vs
        # first-decode) and a bounded slowest-trace exemplar pool
        # (top-K by e2e — the dashboard's slow-request panel and the
        # obs_timeline lookup key).
        self.trace_records = 0
        self.trace_phases: deque = deque(maxlen=TRACE_KEEP)
        self.trace_slow: List[dict] = []
        # SLO digest (``obs_slo``, tpunet/obs/slo.py): the last record
        # per SLO name (budget remaining, burn rates, firing state,
        # probe tallies) plus a bounded burn-rate history for the
        # dashboard sparkline.
        self.slo_records = 0
        self.slo_last: Dict[str, dict] = {}
        self.slo_burn: deque = deque(maxlen=SLO_BURN_KEEP)
        # Elasticity digest (tpunet/elastic/): membership changes are
        # part of the stream's judgeable history — a shrink explains a
        # throughput step-change the regression panel would otherwise
        # flag blind.
        self.elastic_events = 0
        self.last_elastic: Optional[dict] = None

    # -- ingest ----------------------------------------------------------

    def ingest(self, record: dict, now: Optional[float] = None) -> None:
        self.records += 1
        if now is not None:
            self.last_seen = now
        for k in ("run_id", "process_index", "host",
                  "config_fingerprint"):
            if k in record:
                self.identity[k] = record[k]
        kind = record.get("kind")
        if kind == "obs_epoch":
            self.last_epoch = record
            steps = int(record.get("steps") or 0)
            mean = record.get("step_time_mean_s")
            if steps > 0 and mean is not None:
                self.steps_total += steps
                self.step_time_sum += mean * steps
            p50 = record.get("step_time_p50_s")
            if p50 is not None:
                self.epoch_p50s.append((record.get("epoch", 0), p50))
            peaks = [m.get("peak_bytes_in_use")
                     for m in record.get("device_memory", []) or []
                     if isinstance(m, dict)
                     and m.get("peak_bytes_in_use") is not None]
            if peaks:
                self.mem_peaks.append((record.get("epoch", 0),
                                       max(peaks)))
        elif kind == "obs_step":
            lap = record.get("step_time_s")
            if lap is not None:
                self.step_laps.append((int(record.get("step", 0)), lap))
        elif kind == "obs_serve":
            self.last_serve = record
            self.serve_records += 1
        elif kind == "obs_router":
            self.router_records += 1
            if record.get("event"):
                self.router_events += 1
                self.last_router_event = record
            else:
                self.last_router = record
        elif kind == "obs_alert":
            self.alerts += 1
            self.recent_alerts.append(record)
        elif kind == "obs_crash":
            # A restarted run reporting its previous incarnation's
            # death (tpunet/obs/flightrec/): tracked per stream so the
            # fleet view can say which replica is crash-looping.
            self.crashes += 1
            self.last_crash = record
        elif kind == "obs_slo":
            self.slo_records += 1
            name = str(record.get("name") or "")
            if name:
                self.slo_last[name] = record
            burn = record.get("page_burn_long")
            if burn is not None:
                self.slo_burn.append((name, burn))
        elif kind == "obs_elastic":
            self.elastic_events += 1
            self.last_elastic = record
        elif kind == "obs_trace":
            self.trace_records += 1
            if record.get("role") == "replica":
                self.trace_phases.append(
                    (record.get("queue_s"), record.get("prefill_s"),
                     record.get("first_decode_s")))
            if record.get("e2e_s") is not None:
                # Order-independent top-K (trace_id tie-break): the
                # same files replayed in any order keep the identical
                # exemplar set — the rollup purity property.
                self.trace_slow.append(record)
                self.trace_slow.sort(
                    key=lambda r: (-(r.get("e2e_s") or 0.0),
                                   str(r.get("trace_id", ""))))
                del self.trace_slow[TRACE_SLOW_KEEP:]

    # -- derived ---------------------------------------------------------

    def step_time_p50(self, step_range=None) -> Optional[float]:
        """The stream's representative step time: the median of its
        recent ``obs_step`` laps (restricted to ``step_range`` when
        given — the step-aligned comparison), falling back to the last
        epoch's p50 when no per-step records flow."""
        # list() is one C-level copy — safe against a concurrent
        # append when called outside the aggregator's ingest lock
        # (the dashboard's render path).
        laps = [t for s, t in list(self.step_laps)
                if step_range is None
                or step_range[0] <= s <= step_range[1]]
        if laps:
            laps.sort()
            return laps[len(laps) // 2]
        if self.last_epoch is not None:
            return self.last_epoch.get("step_time_p50_s")
        return None

    def step_span(self):
        if self.step_laps:
            return (self.step_laps[0][0], self.step_laps[-1][0])
        return None

    def last_step(self) -> Optional[int]:
        if self.step_laps:
            return self.step_laps[-1][0]
        if self.last_epoch is not None:
            return self.last_epoch.get("step")
        return None

    def mem_growth_per_epoch(self) -> Optional[float]:
        """Least-squares slope of peak device bytes over epochs — the
        leak shape (bytes/epoch) the fleet watchdog alerts on."""
        if len(self.mem_peaks) < 3:
            return None
        from tpunet.obs.health import _slope
        return _slope(list(self.mem_peaks))

    def throughput(self):
        """(value, unit) from the last epoch record, or None."""
        r = self.last_epoch
        if r is None:
            return None
        for key, unit in (("tokens_per_sec", "tokens"),
                          ("examples_per_sec", "examples")):
            if r.get(key) is not None:
                return r[key], unit
        return None


def _common_step_range(streams: List[StreamState]):
    """Overlapping step range across every stream that emits obs_step
    records — skew compared inside it is step-aligned (same work),
    not warmup-vs-steady-state."""
    spans = [s.step_span() for s in streams]
    spans = [sp for sp in spans if sp is not None]
    if len(spans) < 2:
        return None
    lo = max(sp[0] for sp in spans)
    hi = min(sp[1] for sp in spans)
    return (lo, hi) if lo <= hi else None


def fleet_rollup(streams: List[StreamState]) -> dict:
    """The fleet-level view over every stream digest: exact merged
    counts/means, bounded-error merged percentiles, straggler/skew,
    memory-growth trend, and the serve SLO rollup. Flat numeric fields
    plus one nested ``per_stream`` list (jsonl/HTTP carry it; statsd
    drops non-scalars by design)."""
    streams = sorted(streams, key=lambda s: s.key)
    out: dict = {
        "streams": len(streams),
        "records_total": sum(s.records for s in streams),
        "alerts_total": sum(s.alerts for s in streams),
    }
    crashes = sum(s.crashes for s in streams)
    if crashes:
        out["crashes_total"] = crashes
    elastic = sum(s.elastic_events for s in streams)
    if elastic:
        out["elastic_events_total"] = elastic
        # The most recent membership change across streams: the
        # dashboard head-line ("shrink 2->1, gen 3") without digging
        # per stream.
        last = max((s.last_elastic for s in streams
                    if s.last_elastic is not None),
                   key=lambda r: r.get("time", 0) or 0, default=None)
        if last is not None:
            out["elastic_last_event"] = str(last.get("event", ""))
            if last.get("generation") is not None:
                out["elastic_generation"] = last["generation"]
    per_stream: List[dict] = []

    # -- training rollup -------------------------------------------------
    trainers = [s for s in streams if s.last_epoch is not None]
    if trainers:
        out["steps_total"] = sum(s.steps_total for s in trainers)
        mean = merge.merged_mean([
            (s.step_time_sum / s.steps_total, s.steps_total)
            for s in trainers if s.steps_total > 0])
        if mean is not None:
            out["step_time_mean_s"] = round(mean, 6)
        parts = merge.record_parts(
            [s.last_epoch for s in trainers],
            "step_time_sample", "steps")
        if parts:
            merged = merge.merged_quantiles(parts, (50, 90, 99))
            out["step_time_p50_s"] = round(merged[50], 6)
            out["step_time_p90_s"] = round(merged[90], 6)
            out["step_time_p99_s"] = round(merged[99], 6)
            out["step_time_rank_err"] = round(
                merge.rank_error_bound(parts), 4)
            out["step_time_sample_n"] = sum(len(p[0]) for p in parts)
        thr = [s.throughput() for s in trainers]
        thr = [t for t in thr if t is not None]
        if thr:
            # One summed total PER unit — a mixed fleet (an LM and a
            # classifier run tailed together) must not silently drop
            # the minority unit's streams from "total" throughput.
            sums: Dict[str, float] = {}
            for v, u in thr:
                sums[u] = sums.get(u, 0.0) + v
            for u, v in sums.items():
                out[f"{u}_per_sec"] = round(v, 2)
            if len(sums) == 1:
                out["throughput_unit"] = next(iter(sums))
            else:
                out["throughput_units"] = sorted(sums)
        # Step-aligned straggler/skew: slowest stream vs the median of
        # the REMAINING replicas — with the slowest included, a
        # two-replica fleet's upper median IS the slowest and the
        # factor pins at 1.0 (for two streams this degenerates to
        # slowest/fastest, which is the right two-replica question).
        rng = _common_step_range(trainers)
        p50s = [(s, s.step_time_p50(rng)) for s in trainers]
        p50s = [(s, p) for s, p in p50s if p is not None]
        if len(p50s) >= 2:
            from tpunet.obs.registry import percentile_of_sorted
            slowest, slow_p50 = max(p50s, key=lambda t: t[1])
            others = sorted(p for s, p in p50s if s is not slowest)
            median = percentile_of_sorted(others, 50)
            out["median_step_time_p50_s"] = round(median, 6)
            out["slowest_step_time_p50_s"] = round(slow_p50, 6)
            out["slowest_stream"] = slowest.key
            if median > 0:
                out["straggler_factor"] = round(slow_p50 / median, 4)
        steps = [s.last_step() for s in trainers]
        steps = [s for s in steps if s is not None]
        if steps:
            out["step_min"] = min(steps)
            out["step_max"] = max(steps)
            out["step_lag"] = out["step_max"] - out["step_min"]
        growth = [(s, s.mem_growth_per_epoch()) for s in trainers]
        growth = [(s, g) for s, g in growth if g is not None]
        if growth:
            worst, slope = max(growth, key=lambda t: t[1])
            out["mem_growth_bytes_per_epoch"] = round(slope, 1)
            out["mem_growth_stream"] = worst.key

    # -- serve SLO rollup ------------------------------------------------
    servers = [s for s in streams if s.last_serve is not None]
    if servers:
        out["serve_replicas"] = len(servers)
        for field in ("queue_depth", "active_slots", "slots",
                      "requests_total", "requests_completed",
                      "requests_rejected", "tokens_total"):
            vals = [s.last_serve.get(field) for s in servers]
            vals = [v for v in vals if v is not None]
            if vals:
                out[f"serve_{field}"] = sum(vals)
        req = out.get("serve_requests_total", 0)
        rej = out.get("serve_requests_rejected", 0)
        if req:
            out["serve_reject_rate"] = round(rej / req, 4)
        for key in ("ttft", "e2e"):
            parts = merge.record_parts(
                [s.last_serve for s in servers],
                f"{key}_sample", f"{key}_count")
            if parts:
                merged = merge.merged_quantiles(parts, (50, 90, 99))
                out[f"serve_{key}_p50_s"] = round(merged[50], 6)
                out[f"serve_{key}_p90_s"] = round(merged[90], 6)
                out[f"serve_{key}_p99_s"] = round(merged[99], 6)
                out[f"serve_{key}_rank_err"] = round(
                    merge.rank_error_bound(parts), 4)

    # -- trace SLO decomposition -----------------------------------------
    # Per-phase quantiles over every stream's sampled obs_trace
    # records: the fleet TTFT p99 split into where the time went
    # (admission queue vs prefill compute vs first-decode), plus the
    # fleet-wide slowest-trace exemplars.
    tracers = [s for s in streams if s.trace_records]
    if tracers:
        from tpunet.obs.registry import percentile_of_sorted
        out["trace_records_total"] = sum(s.trace_records
                                         for s in tracers)
        phases = [p for s in tracers for p in list(s.trace_phases)]
        for i, name in enumerate(("queue", "prefill",
                                  "first_decode")):
            vals = sorted(p[i] for p in phases if p[i] is not None)
            if vals:
                out[f"trace_{name}_p50_s"] = round(
                    percentile_of_sorted(vals, 50), 6)
                out[f"trace_{name}_p99_s"] = round(
                    percentile_of_sorted(vals, 99), 6)
        slow = sorted((r for s in tracers for r in s.trace_slow),
                      key=lambda r: (-(r.get("e2e_s") or 0.0),
                                     str(r.get("trace_id", ""))))
        slow = slow[:TRACE_SLOW_KEEP]
        if slow:
            out["trace_slow"] = [
                {k: r[k] for k in
                 ("trace_id", "role", "hop", "e2e_s", "ttft_s",
                  "queue_s", "prefill_s", "first_decode_s",
                  "finish_reason", "failover_count", "preemptions",
                  "tokens_relayed")
                 if r.get(k) is not None}
                for r in slow]

    # -- router rollup ---------------------------------------------------
    routers = [s for s in streams if s.last_router is not None
               or s.router_events]
    if routers:
        out["routers"] = len(routers)
        windows = [s.last_router for s in routers
                   if s.last_router is not None]
        for field in ("replicas", "replicas_healthy",
                      "fleet_queue_depth", "fleet_slots",
                      "evictions_total", "respawns_total",
                      "scale_ups_total", "scale_downs_total"):
            vals = [w.get(field) for w in windows]
            vals = [v for v in vals if v is not None]
            if vals:
                out[f"router_{field}"] = sum(vals)
        out["router_events_total"] = sum(s.router_events
                                         for s in routers)
        last = max((s.last_router_event for s in routers
                    if s.last_router_event is not None),
                   key=lambda r: r.get("time", 0) or 0, default=None)
        if last is not None:
            out["router_last_event"] = str(last.get("event", ""))

    # -- error-budget / SLO rollup ---------------------------------------
    # Latest obs_slo record per (stream, slo name): worst budget
    # across the fleet, max burn rates, firing/page totals, probe
    # tallies, and the burn sparkline + last failed probe trace the
    # dashboard's error-budget panel renders.
    slo_streams = [s for s in streams if s.slo_last]
    if slo_streams:
        out["fleet_slo_records_total"] = sum(s.slo_records
                                             for s in slo_streams)
        table: List[dict] = []
        worst = None          # (budget_remaining, stream, name)
        max_page = max_ticket = None
        firing = 0
        pages = tickets = 0
        probe_req = probe_fail = probe_mis = 0
        last_trace = ""
        for s in slo_streams:
            rows = [s.slo_last[n] for n in sorted(s.slo_last)]
            for r in rows:
                row = {"stream": s.key, "name": r.get("name"),
                       "sli": r.get("sli"),
                       "objective": r.get("objective")}
                for k in ("budget_remaining", "error_rate",
                          "page_burn_long", "page_burn_short",
                          "ticket_burn_long", "page_firing",
                          "ticket_firing", "pages_total",
                          "tickets_total"):
                    if r.get(k) is not None:
                        row[k] = r[k]
                table.append(row)
                b = r.get("budget_remaining")
                if b is not None and (worst is None or b < worst[0]):
                    worst = (b, s.key, str(r.get("name")))
                pb, tb = (r.get("page_burn_long"),
                          r.get("ticket_burn_long"))
                if pb is not None:
                    max_page = pb if max_page is None \
                        else max(max_page, pb)
                if tb is not None:
                    max_ticket = tb if max_ticket is None \
                        else max(max_ticket, tb)
                if r.get("page_firing") or r.get("ticket_firing"):
                    firing += 1
                pages += int(r.get("pages_total") or 0)
                tickets += int(r.get("tickets_total") or 0)
                if r.get("last_failed_trace"):
                    last_trace = str(r["last_failed_trace"])
            # Probe tallies are engine-level and duplicated on every
            # SLO's record within a stream: count them once per
            # stream (max over that stream's records), sum over
            # streams.
            probe_req += max((int(r.get("probe_requests") or 0)
                              for r in rows), default=0)
            probe_fail += max((int(r.get("probe_failures") or 0)
                               for r in rows), default=0)
            probe_mis += max((int(r.get("probe_mismatches") or 0)
                              for r in rows), default=0)
        if worst is not None:
            out["fleet_slo_worst_budget_remaining"] = worst[0]
            out["fleet_slo_worst_slo"] = f"{worst[1]}:{worst[2]}"
        if max_page is not None:
            out["fleet_slo_max_page_burn"] = max_page
        if max_ticket is not None:
            out["fleet_slo_max_ticket_burn"] = max_ticket
        out["fleet_slo_firing"] = firing
        out["fleet_slo_pages_total"] = pages
        out["fleet_slo_tickets_total"] = tickets
        if probe_req:
            out["fleet_slo_probe_requests_total"] = probe_req
            out["fleet_slo_probe_failures_total"] = probe_fail
            out["fleet_slo_probe_mismatches_total"] = probe_mis
        if last_trace:
            out["fleet_slo_last_failed_trace"] = last_trace
        out["slo_table"] = table
        # Burn sparkline: the worst-budget stream's recent
        # page-burn-rate history (values only, oldest first).
        spark_stream = slo_streams[0]
        if worst is not None:
            for s in slo_streams:
                if s.key == worst[1]:
                    spark_stream = s
                    break
        out["slo_burn_spark"] = [round(float(b), 4) for _, b
                                 in list(spark_stream.slo_burn)]

    # -- per-stream table ------------------------------------------------
    for s in streams:
        row: dict = {"stream": s.key, "records": s.records,
                     "alerts": s.alerts}
        if s.crashes:
            row["crashes"] = s.crashes
        if s.elastic_events:
            row["elastic_events"] = s.elastic_events
            if s.last_elastic is not None:
                row["elastic_last_event"] = str(
                    s.last_elastic.get("event", ""))
        row.update(s.identity)
        if s.last_epoch is not None:
            row["epoch"] = s.last_epoch.get("epoch")
            row["step"] = s.last_step()
            p50 = s.step_time_p50()
            if p50 is not None:
                row["step_time_p50_s"] = round(p50, 6)
            thr = s.throughput()
            if thr is not None:
                row[f"{thr[1]}_per_sec"] = thr[0]
            if s.last_epoch.get("mfu") is not None:
                row["mfu"] = s.last_epoch["mfu"]
            if s.mem_peaks:
                row["peak_bytes_in_use"] = s.mem_peaks[-1][1]
        if s.last_router is not None:
            rt = s.last_router
            for field in ("replicas", "replicas_healthy",
                          "fleet_queue_depth", "evictions_total",
                          "respawns_total"):
                if rt.get(field) is not None:
                    row[f"router_{field}"] = rt[field]
            if s.last_router_event is not None:
                row["router_last_event"] = str(
                    s.last_router_event.get("event", ""))
        if s.slo_last:
            budgets = [(r.get("budget_remaining"), n)
                       for n, r in s.slo_last.items()
                       if r.get("budget_remaining") is not None]
            if budgets:
                b, n = min(budgets)
                row["slo_worst_budget_remaining"] = b
                row["slo_worst"] = n
            if any(r.get("page_firing") or r.get("ticket_firing")
                   for r in s.slo_last.values()):
                row["slo_firing"] = 1
        if s.last_serve is not None:
            sv = s.last_serve
            for field in ("queue_depth", "active_slots", "slots",
                          "requests_total", "requests_rejected"):
                if sv.get(field) is not None:
                    row[f"serve_{field}"] = sv[field]
            if sv.get("requests_total"):
                row["serve_reject_rate"] = round(
                    (sv.get("requests_rejected") or 0)
                    / sv["requests_total"], 4)
            for key in ("ttft_p50_s", "e2e_p99_s"):
                if sv.get(key) is not None:
                    row[f"serve_{key}"] = sv[key]
        per_stream.append(row)
    out["per_stream"] = per_stream
    return out
