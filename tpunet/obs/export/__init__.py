"""Live telemetry export: push finished obs records off-host while the
run is live.

The contract that shapes everything here: **a slow or dead endpoint
must never stall a training step.** Sinks attached to the registry are
called synchronously from the step/epoch path, so the only exporter
the trainer ever sees is ``AsyncExporter`` — a bounded in-memory queue
whose ``write`` is a single non-blocking ``put_nowait``; a background
thread drains the queue into the actual transport (StatsD/UDP,
line-JSON HTTP, or anything with a ``send``/``write`` method). When
the queue is full the record is dropped *and counted* in the registry
(``export_<name>_dropped``) — never silently; transport failures are
likewise counted (``export_<name>_send_errors``), so

    records written == sent + send_errors + dropped

accounts for every record that entered ``write`` (overflow and
flush-timeout losses both land in ``dropped``; the internal
``enqueued`` tally in ``stats()`` counts only the writes that made it
into the queue, i.e. ``written - overflow_drops``).

Exporters are coordinator-only by construction (``build_exporters``):
one process speaks for the run, mirroring MetricsLogger's jsonl
discipline, so a pod doesn't report N copies of every record.
"""

from __future__ import annotations

from tpunet.obs.export.exporter import AsyncExporter, MemoryTransport
from tpunet.obs.export.http import HttpLineTransport
from tpunet.obs.export.statsd import StatsdTransport
from tpunet.obs.export.webhook import (AlertWebhook, WebhookTransport,
                                       build_payload)

__all__ = [
    "AlertWebhook", "AsyncExporter", "HttpLineTransport",
    "MemoryTransport", "StatsdTransport", "WebhookTransport",
    "build_exporters", "build_payload",
]


def build_exporters(cfg, registry) -> list:
    """Construct the configured exporters (``ExportConfig``) on the
    coordinator process; worker processes and an endpoint-less config
    get an empty list. Bad endpoint *syntax* raises here, at setup,
    where a config error should fail loudly — endpoint *liveness* is
    never checked (a down collector is the normal case the async queue
    exists for)."""
    import jax

    out: list = []
    if jax.process_index() != 0:
        return out
    if getattr(cfg, "statsd", ""):
        host, _, port = cfg.statsd.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"--statsd expects HOST:PORT, got {cfg.statsd!r}")
        out.append(AsyncExporter(
            StatsdTransport(host, int(port), prefix=cfg.statsd_prefix),
            name="statsd", queue_size=cfg.queue_size,
            flush_timeout=cfg.flush_timeout_s, registry=registry))
    if getattr(cfg, "http", ""):
        if not cfg.http.startswith(("http://", "https://")):
            raise ValueError(
                f"--obs-http expects an http(s):// URL, got {cfg.http!r}")
        out.append(AsyncExporter(
            HttpLineTransport(cfg.http, timeout=cfg.http_timeout_s),
            name="http", queue_size=cfg.queue_size,
            flush_timeout=cfg.flush_timeout_s, registry=registry))
    if getattr(cfg, "webhook", ""):
        # URL syntax validated in WebhookTransport (same fail-at-setup
        # posture as the endpoints above).
        out.append(AlertWebhook(
            WebhookTransport(cfg.webhook, timeout=cfg.http_timeout_s),
            max_retries=cfg.webhook_max_retries,
            backoff_s=cfg.webhook_backoff_s,
            flush_timeout=cfg.flush_timeout_s, registry=registry))
    return out
