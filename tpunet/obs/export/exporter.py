"""Non-blocking exporter core: bounded queue + background drain thread.

Threading model (what makes the counters safe without locks): the
training thread is the only caller of ``write``/``close``, so it is
the single writer of the ``dropped`` counter and the ``enqueued``
tally; the drain thread is the single writer of ``sent`` and
``send_errors``. Gauges mirror the drain-side tallies into the
registry with plain assignments (atomic under the GIL). Nothing is
read-modify-written from two threads.
"""

from __future__ import annotations

import queue
import threading


# Sentinel enqueued by close(): FIFO ordering guarantees every record
# written before close() drains before the thread exits — the clean
# flush-on-close ordering the tests pin down.
_CLOSE = object()


class MemoryTransport:
    """Test transport: records land in ``self.records`` in delivery
    order. ``gate`` (a ``threading.Event``) blocks delivery until set,
    simulating a wedged endpoint; ``fail_every`` raises on every Nth
    send, simulating a flaky one."""

    def __init__(self, gate: threading.Event = None, fail_every: int = 0):
        self.records: list = []
        self.gate = gate
        self.fail_every = fail_every
        self._n = 0

    def send(self, record: dict) -> None:
        if self.gate is not None:
            self.gate.wait()
        self._n += 1
        if self.fail_every and self._n % self.fail_every == 0:
            raise IOError("injected transport failure")
        self.records.append(record)


class AsyncExporter:
    """Registry sink that never blocks the caller.

    ``write`` is ``put_nowait`` + (on a full queue) one counter
    increment — O(1) host work with no syscalls, safe on the per-step
    path even when the endpoint is down. The daemon drain thread owns
    the transport; its per-record failures increment
    ``export_<name>_send_errors`` and are otherwise swallowed (a
    telemetry endpoint must never be able to kill a run).

    ``close`` enqueues a sentinel and joins with ``flush_timeout``:
    everything enqueued before close is delivered (or counted as a
    send error) before the thread exits; if the transport is so wedged
    the flush times out, the leftover queue depth is added to the
    dropped counter so the accounting identity still holds.
    """

    def __init__(self, transport, *, name: str = "sink",
                 queue_size: int = 1024, flush_timeout: float = 5.0,
                 registry=None):
        if queue_size < 1:
            raise ValueError(f"queue_size must be >= 1, got {queue_size}")
        self.name = name
        self._send = getattr(transport, "send", None) or transport.write
        # Transports with a send_many (the HTTP one) get the queue
        # drained in batches: one request per backlog, not per record,
        # so a fast producer can't outrun the drain via per-request
        # latency alone.
        self._send_many = getattr(transport, "send_many", None)
        self._batch_max = 64
        self._transport = transport
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._flush_timeout = flush_timeout
        self._enqueued = 0
        self._sent = 0
        self._errors = 0
        self._closed = False
        self._abandoned = False
        # Guards the abandon/tally handoff on the close-timeout path:
        # without it a record whose send completes in the same instant
        # close() gives up could be counted both sent AND dropped.
        # Never touched by the training thread's write().
        self._acct = threading.Lock()
        if registry is not None:
            self._dropped = registry.counter(f"export_{name}_dropped")
            self._sent_gauge = registry.gauge(f"export_{name}_sent")
            self._err_gauge = registry.gauge(f"export_{name}_send_errors")
        else:
            from tpunet.obs.registry import Counter, Gauge
            self._dropped = Counter()
            self._sent_gauge = Gauge()
            self._err_gauge = Gauge()
        # Host-thread registry (tpunet/obs/flightrec/): the drain
        # thread flips idle (parked on the queue) / busy (sending), so
        # thread_stalled only pages on a send wedged past the budget,
        # never on an idle exporter.
        from tpunet.obs.flightrec import register_thread
        self._handle = register_thread(f"export-{name}",
                                       stall_after_s=120.0)
        self._thread = threading.Thread(
            target=self._drain, name=f"tpunet-export-{name}", daemon=True)
        self._thread.start()

    # -- training-thread side -------------------------------------------

    def write(self, record: dict) -> None:
        """Registry-sink entry point; never blocks, never raises."""
        if self._closed:
            self._dropped.inc()
            return
        try:
            self._q.put_nowait(record)
            self._enqueued += 1
        except queue.Full:
            self._dropped.inc()

    def stats(self) -> dict:
        """{enqueued, sent, send_errors, dropped} — exact once closed;
        a live snapshot (drain thread still moving) before that."""
        return {
            "enqueued": self._enqueued,
            "sent": self._sent,
            "send_errors": self._errors,
            "dropped": int(self._dropped.value),
        }

    def close(self) -> None:
        """Flush and stop: records written before this call drain (in
        order) before the thread exits, bounded by ``flush_timeout``."""
        if self._closed:
            return
        self._closed = True
        try:
            self._q.put(_CLOSE, timeout=self._flush_timeout)
        except queue.Full:
            pass  # wedged transport; the daemon thread dies with us
        self._thread.join(self._flush_timeout)
        if self._thread.is_alive():
            # Flush timed out on a wedged transport: tell the drain
            # thread to discard instead of deliver (so the records we
            # now count as dropped can't ALSO be counted sent later),
            # then account for them — enqueued == sent + errors +
            # dropped stays true. The lock pairs with the drain
            # thread's tally section so the handoff is atomic.
            with self._acct:
                self._abandoned = True
                undelivered = (self._enqueued - self._sent
                               - self._errors)
            if undelivered > 0:
                self._dropped.inc(undelivered)
        tclose = getattr(self._transport, "close", None)
        if tclose is not None:
            try:
                tclose()
            except Exception:
                pass

    # -- drain-thread side ----------------------------------------------

    def _drain(self) -> None:
        while True:
            self._handle.beat("idle")
            item = self._q.get()
            self._handle.beat("busy")
            if item is _CLOSE:
                self._handle.beat("idle")
                return
            batch = [item]
            stop = False
            if self._send_many is not None:
                # Greedy batch: one request per backlog instead of per
                # record, so per-request latency can't outrun a fast
                # producer.
                while len(batch) < self._batch_max:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _CLOSE:
                        stop = True
                        break
                    batch.append(nxt)
            if not self._abandoned:
                try:
                    if self._send_many is not None:
                        self._send_many(batch)
                    else:
                        self._send(batch[0])
                    with self._acct:
                        if not self._abandoned:
                            # close() may have given up while this
                            # send was in flight and counted it as
                            # dropped; leave it there — over-delivery
                            # is fine, double-counting is not.
                            self._sent += len(batch)
                    self._sent_gauge.set(self._sent)
                except Exception:
                    with self._acct:
                        if not self._abandoned:
                            self._errors += len(batch)
                    self._err_gauge.set(self._errors)
            if stop:
                self._handle.beat("idle")
                return
