"""Line-JSON HTTP transport: POST one ``application/x-ndjson`` line
per record.

This is the full-fidelity path (nested fields survive, unlike statsd's
numeric flattening) and the one the dashboard's ``--listen`` mode
receives. Every request carries the socket timeout, so a dead or
black-holed endpoint costs at most ``timeout`` seconds *on the drain
thread* — the training thread only ever paid a queue put. Failures
raise to the caller (``AsyncExporter`` counts them).
"""

from __future__ import annotations

import json
import urllib.request


class HttpLineTransport:
    def __init__(self, url: str, timeout: float = 1.0):
        self.url = url
        self.timeout = timeout

    def send(self, record: dict) -> None:
        self.send_many([record])

    def send_many(self, records) -> None:
        """One POST for a whole queue backlog (receivers split on
        newline — ``obs_dashboard.py --listen`` does): per-request
        latency is paid per batch, not per record, so a fast producer
        with --obs-step-every 1 can't outrun the drain thread."""
        data = "".join(json.dumps(r) + "\n" for r in records).encode()
        req = urllib.request.Request(
            self.url, data=data, method="POST",
            headers={"Content-Type": "application/x-ndjson"})
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass

    def close(self) -> None:
        pass
