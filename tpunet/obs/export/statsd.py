"""StatsD/UDP transport: one gauge line per numeric record field.

UDP is the right substrate for per-step telemetry — fire-and-forget,
no connection state, a dead collector costs one syscall per datagram.
Records flatten to the classic line protocol::

    tpunet.obs_epoch.step_time_p50_s:0.0123|g

Lines are packed into MTU-sized datagrams (statsd servers split on
newline). The endpoint is resolved once at construction so a typo'd
hostname fails loudly at setup instead of doing DNS per datagram on
the drain thread.
"""

from __future__ import annotations

import math
import socket

# Conservative payload bound: fits the common 1500-byte Ethernet MTU
# with IP+UDP headers to spare (the statsd reference uses 1432).
_MTU_PAYLOAD = 1400


def _num(val) -> str:
    """Plain decimal rendering — statsd parsers reject the scientific
    notation %g would emit for values like device-memory byte counts."""
    if isinstance(val, int):
        return str(val)
    if val == int(val) and abs(val) < 1e15:
        return str(int(val))
    return f"{val:.6f}".rstrip("0").rstrip(".")


# Identity fields ride as name tags, not gauges: a fleet collector
# needs to know WHICH run a gauge line belongs to, and statsd's only
# record-shaped channel is the dogstatsd tag suffix.
_TAG_FIELDS = ("run_id", "process_index", "host")


def _tag_value(val) -> str:
    """Tag values must not carry the protocol's delimiters."""
    return str(val).replace("|", "_").replace("#", "_").replace(",", "_")


def record_to_lines(record: dict, prefix: str = "tpunet") -> list:
    """Flatten a record's numeric scalar fields to statsd gauge lines;
    nested/str/bool fields are skipped (UDP metrics carry numbers, the
    full record shape belongs to the jsonl/HTTP paths). The identity
    stamp (run_id/process_index/host) becomes a dogstatsd-style tag
    suffix ``|#run_id:...,process_index:...,host:...`` on every line
    instead of a gauge, so multi-run collectors can split streams."""
    kind = record.get("kind", "record")
    tags = ",".join(f"{k}:{_tag_value(record[k])}"
                    for k in _TAG_FIELDS if record.get(k) is not None)
    suffix = f"|#{tags}" if tags else ""
    lines = []
    for key, val in record.items():
        if key == "kind" or key in _TAG_FIELDS or isinstance(val, bool):
            continue
        if isinstance(val, int) or (isinstance(val, float)
                                    and math.isfinite(val)):
            lines.append(f"{prefix}.{kind}.{key}:{_num(val)}|g{suffix}")
    return lines


class StatsdTransport:
    def __init__(self, host: str, port: int, prefix: str = "tpunet"):
        self.prefix = prefix
        # Resolve now (raises on a bad name); keep the packed sockaddr.
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_DGRAM)
        family, _, _, _, self._addr = infos[0]
        self._sock = socket.socket(family, socket.SOCK_DGRAM)

    def send(self, record: dict) -> None:
        lines = record_to_lines(record, self.prefix)
        if not lines:
            return
        batch: list = []
        size = 0
        for line in lines:
            n = len(line) + 1
            if batch and size + n > _MTU_PAYLOAD:
                self._sock.sendto("\n".join(batch).encode(), self._addr)
                batch, size = [], 0
            batch.append(line)
            size += n
        if batch:
            self._sock.sendto("\n".join(batch).encode(), self._addr)

    def close(self) -> None:
        self._sock.close()
