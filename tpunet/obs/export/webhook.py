"""Alert webhook: page a human (or a router) when a run goes bad.

The record stream already carries the pages — ``obs_alert`` (watchdog
and fleet bridge: straggler / crash / thread_stalled / mem_growth /
...), ``obs_crash`` (flight-recorder post-mortems), and
``obs_regression`` (cross-run compare verdicts). This sink filters
that stream down to alert kinds and POSTs one templated JSON payload
per page to an operator-configured URL (``--obs-webhook``; Slack/
PagerDuty-style receivers take it directly, and
``tests/test_obs_webhook.py`` shows the stdlib receiver shape).

Delivery discipline mirrors ``AsyncExporter`` — a dead pager endpoint
must never stall a step — plus the retry story a *page* needs that a
gauge sample does not: a failed POST is retried with exponential
backoff (an alert is rare and valuable; a metrics line is neither),
and a page that exhausts its retries lands in a bounded **dead
letter** list (``dead_letters()``) and counts in
``webhook_dead_letter``, so "the pager was down during the incident"
is itself visible after the fact. The accounting identity still
holds: every payload handed to ``write`` is eventually counted
exactly once —

    enqueued == sent + send_errors + dropped

(send_errors == dead-lettered pages; retries that eventually succeed
count once, as sent, with attempts tallied in ``webhook_retries``).
The drain thread registers in the flight-recorder host-thread
registry (tpucheck R4) and flips idle/busy around delivery, so a
wedged webhook endpoint pages through ``thread_stalled`` like any
other stuck host thread.
"""

from __future__ import annotations

import json
import queue
import threading
from collections import deque
from typing import Optional, Tuple

#: Record kinds that page. Everything else is dropped at write() for
#: the cost of one dict lookup — the "configured but idle" overhead
#: the obs budget gate measures. obs_elastic pages because a
#: membership change is operator-actionable (a shrink is capacity
#: loss; a quorum failure is an outage). obs_router pages on its
#: ACTION events only (evict/respawn/scale — records carrying an
#: ``event`` field); periodic window records are fleet state, not
#: pages, and are filtered in ``write``.
ALERT_KINDS = ("obs_alert", "obs_crash", "obs_regression",
               "obs_elastic", "obs_router")

_CLOSE = object()


def _summary_line(record: dict) -> str:
    """One human-readable line per page (the template a chat webhook
    renders); the full record rides in ``detail``."""
    kind = record.get("kind", "obs_alert")
    stream = record.get("stream") or record.get("run_id") or ""
    where = f" [{stream}]" if stream else ""
    if kind == "obs_crash":
        return (f"tpunet crash{where}: {record.get('cause', 'unknown')}"
                f" (report: {record.get('report_path', '?')})")
    if kind == "obs_regression":
        n = record.get("regressions", 0)
        return (f"tpunet regression{where}: {n} metric(s) regressed "
                f"comparing {record.get('run_b', '?')} against "
                f"{record.get('run_a', '?')}")
    if kind == "obs_router":
        event = record.get("event", "router")
        rep = record.get("replica")
        rep_s = f" {rep}" if rep else ""
        worlds = ""
        if record.get("old_replicas") is not None \
                or record.get("new_replicas") is not None:
            worlds = (f" replicas {record.get('old_replicas', '?')}->"
                      f"{record.get('new_replicas', '?')}")
        cause = record.get("cause")
        cause_s = f" ({cause})" if cause else ""
        return f"tpunet router {event}{where}:{rep_s}{worlds}{cause_s}"
    if kind == "obs_elastic":
        event = record.get("event", "elastic")
        worlds = ""
        if record.get("old_world") is not None \
                or record.get("new_world") is not None:
            worlds = (f" world {record.get('old_world', '?')}->"
                      f"{record.get('new_world', '?')}")
        gen = record.get("generation")
        gen_s = f" gen {gen}" if gen is not None else ""
        cause = record.get("cause")
        cause_s = f" ({cause})" if cause else ""
        return f"tpunet elastic {event}{where}:{worlds}{gen_s}{cause_s}"
    reason = record.get("reason", "alert")
    sev = record.get("severity", "warn")
    return f"tpunet {reason} [{sev}]{where} at step {record.get('step', 0)}"


def build_payload(record: dict, source: str = "tpunet") -> dict:
    """The documented webhook wire format (docs/metrics_schema.md
    "Alert webhook wire format"): flat routing fields + a rendered
    summary + the verbatim record."""
    payload = {
        "source": source,
        "kind": record.get("kind", "obs_alert"),
        "reason": record.get("reason",
                             "crash" if record.get("kind") == "obs_crash"
                             else record.get("event")
                             or record.get("verdict", "alert")),
        "severity": record.get("severity", "warn"),
        "summary": _summary_line(record),
        "detail": record,
    }
    for key in ("run_id", "process_index", "host", "scope", "stream"):
        if record.get(key) is not None:
            payload[key] = record[key]
    return payload


class WebhookTransport:
    """Stdlib JSON POST (one request per page). Raises on transport
    errors and non-2xx responses — retry/backoff policy belongs to the
    sink, not here."""

    def __init__(self, url: str, timeout: float = 2.0):
        if not url.startswith(("http://", "https://")):
            raise ValueError(
                f"--obs-webhook expects an http(s):// URL, got {url!r}")
        self.url = url
        self.timeout = timeout

    def send(self, payload: dict) -> None:
        import urllib.request
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            status = getattr(resp, "status", 200)
            if status >= 300:
                raise IOError(f"webhook endpoint returned {status}")


class AlertWebhook:
    """Registry sink: alert-kind records -> templated JSON POSTs.

    ``write`` never blocks or raises (non-alert kinds cost one dict
    lookup; alert kinds one payload build + ``put_nowait``). The
    daemon drain thread owns delivery: per-page retries with
    exponential backoff (``backoff_s * 2**attempt``, capped), then
    the dead-letter list. ``close`` flushes in order, bounded by
    ``flush_timeout`` — a wedged pager cannot wedge shutdown, and the
    abandoned backlog is counted as dropped (identity preserved).
    """

    DEAD_LETTER_KEEP = 64

    def __init__(self, transport, *, name: str = "webhook",
                 queue_size: int = 64, max_retries: int = 3,
                 backoff_s: float = 0.25, backoff_cap_s: float = 5.0,
                 flush_timeout: float = 5.0, registry=None,
                 kinds: Tuple[str, ...] = ALERT_KINDS,
                 source: str = "tpunet"):
        if isinstance(transport, str):
            transport = WebhookTransport(transport)
        if queue_size < 1:
            raise ValueError(f"queue_size must be >= 1, got {queue_size}")
        self.name = name
        self.kinds = tuple(kinds)
        self.source = source
        self._transport = transport
        self._send = transport.send
        self._max_retries = max_retries
        self._backoff_s = backoff_s
        self._backoff_cap_s = backoff_cap_s
        self._flush_timeout = flush_timeout
        self._q: queue.Queue = queue.Queue(maxsize=queue_size)
        self._enqueued = 0
        self._sent = 0
        self._errors = 0
        self._closed = False
        self._abandoned = threading.Event()
        self._acct = threading.Lock()
        self.dead: deque = deque(maxlen=self.DEAD_LETTER_KEEP)
        if registry is not None:
            self._dropped = registry.counter("webhook_dropped")
            self._retries = registry.counter("webhook_retries")
            self._dead_ctr = registry.counter("webhook_dead_letter")
            self._sent_gauge = registry.gauge("webhook_sent")
            self._err_gauge = registry.gauge("webhook_send_errors")
        else:
            from tpunet.obs.registry import Counter, Gauge
            self._dropped = Counter()
            self._retries = Counter()
            self._dead_ctr = Counter()
            self._sent_gauge = Gauge()
            self._err_gauge = Gauge()
        from tpunet.obs.flightrec import register_thread
        self._handle = register_thread(f"webhook-{name}"
                                       if name != "webhook" else name,
                                       stall_after_s=60.0)
        self._thread = threading.Thread(
            target=self._drain, name=f"tpunet-webhook-{name}",
            daemon=True)
        self._thread.start()

    # -- producer side ---------------------------------------------------

    def write(self, record: dict) -> None:
        """Registry-sink entry point; never blocks, never raises.
        Non-alert kinds are filtered here, before any queue work."""
        if record.get("kind") not in self.kinds:
            return
        if record.get("kind") == "obs_router" \
                and not record.get("event"):
            return        # periodic window record, not a page
        if self._closed:
            self._dropped.inc()
            return
        try:
            self._q.put_nowait(build_payload(record, self.source))
            self._enqueued += 1
        except queue.Full:
            self._dropped.inc()

    def stats(self) -> dict:
        return {
            "enqueued": self._enqueued,
            "sent": self._sent,
            "send_errors": self._errors,
            "dropped": int(self._dropped.value),
            "retries": int(self._retries.value),
            "dead_letter": int(self._dead_ctr.value),
        }

    def dead_letters(self) -> list:
        """The most recent pages that exhausted their retries (bounded
        — post-incident evidence, not a redelivery queue)."""
        return list(self.dead)

    def close(self) -> None:
        """Flush and stop: pages written before this call are
        delivered (or dead-lettered) in order, bounded by
        ``flush_timeout``."""
        if self._closed:
            return
        self._closed = True
        try:
            self._q.put(_CLOSE, timeout=self._flush_timeout)
        except queue.Full:
            pass
        self._thread.join(self._flush_timeout)
        if self._thread.is_alive():
            # Same abandoned-backlog handoff as AsyncExporter.close:
            # the event also cuts any in-flight backoff sleep short.
            with self._acct:
                self._abandoned.set()
                undelivered = (self._enqueued - self._sent
                               - self._errors)
            if undelivered > 0:
                self._dropped.inc(undelivered)

    # -- drain side ------------------------------------------------------

    def _deliver(self, payload: dict) -> None:
        """One page: send with retry/backoff; ends in exactly one of
        sent / dead-letter."""
        attempt = 0
        while True:
            if self._abandoned.is_set():
                return             # counted as dropped by close()
            try:
                self._send(payload)
                with self._acct:
                    if not self._abandoned.is_set():
                        self._sent += 1
                self._sent_gauge.set(self._sent)
                return
            except Exception as e:
                if attempt >= self._max_retries:
                    with self._acct:
                        if self._abandoned.is_set():
                            return
                        self._errors += 1
                    self._err_gauge.set(self._errors)
                    self._dead_ctr.inc()
                    self.dead.append({"payload": payload,
                                      "error": str(e),
                                      "attempts": attempt + 1})
                    return
                self._retries.inc()
                delay = min(self._backoff_s * (2 ** attempt),
                            self._backoff_cap_s)
                attempt += 1
                # Interruptible backoff: close() setting the abandoned
                # flag wakes the wait instead of serving it out.
                if self._abandoned.wait(delay):
                    return

    def _drain(self) -> None:
        while True:
            self._handle.beat("idle")
            item = self._q.get()
            self._handle.beat("busy")
            if item is _CLOSE:
                self._handle.beat("idle")
                return
            self._deliver(item)
