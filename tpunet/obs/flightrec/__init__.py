"""Black-box flight recorder: crash forensics for the host runtime.

Three instruments, one goal — when a run dies, the last milliseconds
are evidence, not a shrug:

- ``ring``     — crash-durable (file-backed mmap) ring of recent
  structured events: span begin/end, alerts, checkpoint / export /
  prefetch state transitions. ~1-2 us per event, default-ON.
- ``threads``  — the host-thread registry: every background thread
  (orbax writer, exporter drain, watchdog monitor, native prefetcher,
  serve engine) registers with a name, heartbeat, and stall budget;
  exported as ``thread_*`` gauges and feeding the watchdog's
  ``thread_stalled`` alert.
- ``crash``    — crash handlers (faulthandler + the C extension's
  SIGSEGV/SIGABRT/SIGBUS hook) plus a post-mortem watcher process
  that assembles a torn-write-safe ``crash_report.json`` from the
  ring tail, per-thread Python stacks, the native batcher journal,
  and the last device ``memory_stats()`` sample.

This module owns the process-global singleton: ``install()`` arms the
recorder, ``record()`` is the no-op-when-disabled event hook call
sites use (one global read + None check), ``close()`` marks a clean
shutdown. ``tpunet/obs/__init__.py`` wires it to the run lifecycle;
``scripts/obs_crash_report.py`` renders reports.
"""

from __future__ import annotations

from typing import Callable, Optional

from tpunet.obs.flightrec.crash import (FlightRecorder, crash_record,
                                        prior_crash_report)
from tpunet.obs.flightrec.ring import EventRing
from tpunet.obs.flightrec.threads import (BUSY, IDLE, THREADS,
                                          ThreadHandle, ThreadRegistry)

__all__ = [
    "BUSY", "EventRing", "FlightRecorder", "IDLE", "THREADS",
    "ThreadHandle", "ThreadRegistry", "close", "crash_record", "get",
    "install", "prior_crash_report", "record", "register_thread",
]

_REC: Optional[FlightRecorder] = None


def install(directory: str, **kw: object) -> FlightRecorder:
    """Arm the process-global recorder (closing any previous one —
    crash handlers and the watcher are process-wide, so the newest
    run dir wins)."""
    global _REC
    if _REC is not None:
        _REC.close()
    _REC = FlightRecorder(directory, **kw).install()
    return _REC


def get() -> Optional[FlightRecorder]:
    return _REC


def record(kind: str, msg: str = "") -> None:
    """Append one event to the installed recorder's ring; a cheap
    no-op (one global read) when no recorder is armed — call sites
    never need to guard."""
    rec = _REC
    if rec is not None:
        rec.record(kind, msg)


def register_thread(name: str, stall_after_s: float = 0.0,
                    clock: Optional[Callable[[], float]] = None
                    ) -> ThreadHandle:
    """Register a background thread in the process-global registry
    (convenience over ``THREADS.register``)."""
    import time
    return THREADS.register(name, stall_after_s,
                            clock if clock is not None
                            else time.monotonic)


def close(recorder: Optional[FlightRecorder] = None) -> None:
    """Clean-shutdown the global recorder (or only ``recorder`` if it
    still IS the global one — a newer install must not be closed by
    its predecessor's owner)."""
    global _REC
    if _REC is None or (recorder is not None and recorder is not _REC):
        return
    _REC.close()
    _REC = None
