"""FlightRecorder: always-on crash forensics for one process.

Capture is split by what each death mode allows:

- **events**: the Python event ring lives in a file-backed mmap
  (ring.py) — durable the instant an event is recorded, under every
  death mode including SIGKILL.
- **per-thread Python stacks**: ``faulthandler.enable`` onto a file in
  the flightrec dir — the only async-signal-safe way to get
  interpreter stacks out of a SIGSEGV/SIGABRT/SIGBUS.
- **native journal**: the C extension's op ring is spilled to disk by
  its own C-level signal handler (``tn_crash_install``), installed
  AFTER faulthandler so the chain runs C-journal -> Python stacks ->
  default action. This is the instrument aimed at the glibc
  heap-corruption resume bug: the journal is the last N
  alloc/free/enqueue/shutdown ops the batcher performed before malloc
  blew up.
- **report assembly**: a watcher subprocess (watch.py) detects parent
  death via pipe EOF and materializes ``crash_report.json`` — no
  crash-time JSON, no malloc in handlers, works for OOM-kills too.

One recorder per process (crash handlers are process-global); the
module-level ``install``/``record``/``close`` in ``__init__`` manage
the singleton. Everything here is best-effort by design: the recorder
must never be the thing that kills a healthy run.
"""

from __future__ import annotations

import faulthandler
import json
import os
import subprocess
import sys
import time
from typing import Optional, Tuple

from tpunet.obs.flightrec import report as _report
from tpunet.obs.flightrec.ring import DEFAULT_SLOTS, EventRing
from tpunet.obs.flightrec.threads import THREADS

# One watcher process serves every recorder install in this process's
# lifetime (re-pointed with DIR lines); spawning per-install would leak
# a subprocess per Trainer in test suites.
_WATCHER: Optional[subprocess.Popen] = None


def _watcher_send(line: str) -> None:
    global _WATCHER
    if _WATCHER is None or _WATCHER.poll() is not None:
        return
    try:
        _WATCHER.stdin.write((line + "\n").encode())
        _WATCHER.stdin.flush()
    except (OSError, ValueError):
        _WATCHER = None


def _ensure_watcher() -> bool:
    global _WATCHER
    if _WATCHER is not None and _WATCHER.poll() is None:
        return True
    watch_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "watch.py")
    try:
        # By file path, not -m: the watcher must not import tpunet.obs
        # (and with it jax) just to idle next to the run.
        _WATCHER = subprocess.Popen(
            [sys.executable, watch_py], stdin=subprocess.PIPE,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            close_fds=True)
        return True
    except OSError:
        _WATCHER = None
        return False


class FlightRecorder:
    def __init__(self, directory: str, *, process_index: int = 0,
                 n_events: int = DEFAULT_SLOTS, watcher: bool = True,
                 native: bool = True, run_id: str = ""):
        self.directory = (os.path.join(directory, "flightrec")
                          if directory else "")
        self.process_index = process_index
        self.n_events = n_events
        self.run_id = run_id
        self._want_watcher = watcher and bool(self.directory)
        self._want_native = native
        self.ring: Optional[EventRing] = None
        self._stacks_file = None
        self._prev_faulthandler = False
        self._installed = False
        self._closed = False

    def _path(self, name: str) -> str:
        return _report.artifact(self.directory, name, self.process_index)

    # -- lifecycle -------------------------------------------------------

    def install(self) -> "FlightRecorder":
        if self._installed:
            return self
        self._installed = True
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            # A fresh incarnation: the clean marker and any stale
            # capture files belong to the previous one — a report
            # assembled later must not mix this incarnation's meta
            # with a dead incarnation's thread/memory snapshots.
            for name in (_report.CLEAN_MARKER,
                         _report.NATIVE_JOURNAL_TXT,
                         _report.THREADS_JSON,
                         _report.DEVICE_MEM_JSON):
                try:
                    os.unlink(self._path(name))
                except OSError:
                    pass
            self._write_json(_report.META_JSON, {
                "pid": os.getpid(),
                "argv": sys.argv,
                "run_id": self.run_id,
                "process_index": self.process_index,
                "started_t": round(time.time(), 3),
            })
        self.ring = EventRing(
            self._path(_report.EVENTS_RING) if self.directory else None,
            self.n_events)
        if self.directory:
            self._install_faulthandler()
            if self._want_native:
                self._install_native()
            if self._want_watcher and _ensure_watcher():
                # The pid rides along so a lingering watcher from a
                # PREVIOUS incarnation of a reused run dir can never
                # assemble a report over this incarnation's files
                # (watch.py checks it against meta.json). The path is
                # LAST and parsed as the remainder of the line, so run
                # dirs with spaces survive the wire format.
                _watcher_send(f"DIR {self.process_index} "
                              f"{os.getpid()} {self.directory}")
        self.record("flightrec", f"installed pid={os.getpid()}")
        return self

    def _install_faulthandler(self) -> None:
        try:
            self._prev_faulthandler = faulthandler.is_enabled()
            # Keep the file object referenced for the process's life —
            # faulthandler holds only the fd.
            self._stacks_file = open(self._path(_report.STACKS_TXT), "w")
            faulthandler.enable(file=self._stacks_file,
                                all_threads=True)
        except OSError:
            self._stacks_file = None

    def _install_native(self) -> None:
        """Arm the C extension's journal spill: its SIGSEGV/SIGABRT/
        SIGBUS handler writes the native op ring to the flightrec dir
        and then chains to the handler faulthandler just installed
        (install order is the chain order)."""
        try:
            from tpunet.data import native
            native.crash_install(
                self._path(_report.NATIVE_JOURNAL_TXT))
        except Exception:
            pass          # no toolchain / no library: python-only report

    def close(self) -> None:
        """Clean shutdown: tell the watcher this was not a crash."""
        if self._closed or not self._installed:
            return
        self._closed = True
        self.record("flightrec", "clean close")
        if self.directory:
            try:
                with open(self._path(_report.CLEAN_MARKER), "w") as f:
                    f.write(str(time.time()))
            except OSError:
                pass
            _watcher_send("CLEAN")
        if self._stacks_file is not None:
            try:
                # Hand faulthandler back to whoever had it (pytest's
                # plugin enables it on stderr) instead of leaving it
                # aimed at a file we are about to close.
                if self._prev_faulthandler:
                    faulthandler.enable()
                else:
                    faulthandler.disable()
                self._stacks_file.close()
            except (OSError, ValueError):
                pass
            self._stacks_file = None
        if self.ring is not None:
            self.ring.close()

    # -- capture ---------------------------------------------------------

    def record(self, kind: str, msg: str = "") -> None:
        if self.ring is not None and not self._closed:
            self.ring.record(kind, msg)

    def set_device_memory(self, mem: Optional[dict]) -> None:
        """Refresh the last-known device ``memory_stats()`` snapshot
        (epoch boundaries). Crash handlers cannot query a device, so
        the report carries the most recent sample."""
        if self.directory and mem:
            self._write_json(_report.DEVICE_MEM_JSON, {
                "sampled_t": round(time.time(), 3), "devices": mem})

    def refresh_threads(self) -> None:
        """Persist the host-thread registry snapshot (epoch
        boundaries) so the report can say what each background thread
        was last doing."""
        if self.directory:
            self._write_json(_report.THREADS_JSON, THREADS.snapshot())

    def _write_json(self, name: str, obj) -> None:
        path = self._path(name)
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(obj, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            pass


# -- prior-crash detection ----------------------------------------------


def prior_crash_report(directory: str, process_index: int = 0
                       ) -> Tuple[Optional[dict], Optional[str]]:
    """(report dict, archived path) when the previous incarnation of
    this run dir left a crash report; (None, None) otherwise. The
    report file is archived (renamed with its mtime) so one crash
    emits one ``obs_crash`` record across restarts."""
    if not directory:
        return None, None
    path = _report.artifact(os.path.join(directory, "flightrec"),
                            _report.REPORT_NAME, process_index)
    if not os.path.isfile(path):
        return None, None
    try:
        with open(path) as f:
            rep = json.load(f)
        root, ext = os.path.splitext(path)
        archived = f"{root}.{int(os.path.getmtime(path))}{ext}"
        os.replace(path, archived)
    except (OSError, ValueError):
        return None, None
    return rep, archived


def crash_record(rep: dict, path: str) -> dict:
    """The ``obs_crash`` record summarizing one crash report
    (docs/metrics_schema.md) — emitted through a Registry so it
    reaches metrics.jsonl, live exporters, and the fleet
    aggregator."""
    nj = rep.get("native_journal") or {}
    stacks = rep.get("stacks") or {}
    meta = rep.get("meta") or {}
    return {
        "cause": rep.get("cause", "unknown"),
        "signal": rep.get("signal"),
        "report_path": path,
        "crashed_pid": meta.get("pid"),
        "events": len(rep.get("events") or ()),
        "stack_threads": len(stacks.get("threads") or ()),
        "native_ops": len(nj.get("ops") or ()),
        "assembled_t": rep.get("assembled_t"),
    }
