"""Crash-report assembly: flightrec artifacts -> crash_report.json.

The in-crash capture paths are deliberately dumb (an mmap'd event
ring, a faulthandler text dump, a native journal spill from a C signal
handler) because they must work while the process is dying; this
module is where the intelligence lives. It runs OUTSIDE the crash: in
the post-mortem watcher (watch.py) after the training process dies, in
``scripts/obs_crash_report.py``, and in tests.

Stdlib-only and dual-mode importable (as
``tpunet.obs.flightrec.report`` or as a bare script module): the
watcher executes this by file path so it never imports ``tpunet.obs``
— and therefore never pays a jax import or its resident memory — while
it idles alongside a training run.

The report file write is torn-write-safe (tmp + ``os.replace``): a
reader either sees no report or a complete one, never half a JSON
object.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import List, Optional

if __package__:
    from tpunet.obs.flightrec import ring as _ring
else:                                        # script mode (the watcher)
    import ring as _ring                     # type: ignore

REPORT_VERSION = 1
REPORT_NAME = "crash_report.json"
CLEAN_MARKER = "clean"

# File names inside the flightrec dir; multi-process runs suffix
# ``.pN`` before the extension for every process but the coordinator.
EVENTS_RING = "events.ring"
STACKS_TXT = "stacks.txt"
NATIVE_JOURNAL_TXT = "native_journal.txt"
DEVICE_MEM_JSON = "device_mem.json"
THREADS_JSON = "threads.json"
META_JSON = "meta.json"

_SIGNAMES = {4: "SIGILL", 6: "SIGABRT", 7: "SIGBUS", 8: "SIGFPE",
             11: "SIGSEGV"}

# Mirrors the JournalOp enum in cxx/batcher.cc (bump together).
NATIVE_OPS = {1: "create", 2: "destroy", 3: "epoch_start",
              4: "epoch_reject", 5: "next_pop", 6: "next_eof",
              7: "batch_alloc", 8: "batch_push", 9: "worker_enter",
              10: "worker_exit", 11: "stop_begin", 12: "stop_joined",
              13: "gather"}


def artifact(directory: str, name: str, process_index: int = 0) -> str:
    """Path of one flightrec artifact; non-coordinator processes get a
    ``.pN`` suffix so a shared run dir never collides."""
    if process_index:
        root, ext = os.path.splitext(name)
        name = f"{root}.p{process_index}{ext}"
    return os.path.join(directory, name)


def _read_text(path: str) -> str:
    try:
        with open(path, "r", errors="replace") as f:
            return f.read()
    except OSError:
        return ""


def _read_json(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def parse_stacks(text: str) -> dict:
    """Parse a faulthandler dump into {fatal, threads:[{ident,
    current, frames}]}; the raw text rides along (the parse is a
    convenience, the evidence is the dump)."""
    fatal = None
    m = re.search(r"^Fatal Python error: (.+)$", text, re.M)
    if m:
        fatal = m.group(1).strip()
    threads: List[dict] = []
    current: Optional[dict] = None
    for line in text.splitlines():
        m = re.match(r"^(Current thread|Thread) (0x[0-9a-fA-F]+)", line)
        if m:
            current = {"ident": m.group(2),
                       "current": m.group(1) == "Current thread",
                       "frames": []}
            threads.append(current)
        elif current is not None and line.startswith("  "):
            current["frames"].append(line.strip())
    return {"fatal": fatal, "threads": threads, "raw": text}


def parse_native_journal(text: str) -> Optional[dict]:
    """Parse the C crash handler's spill: a ``tn-crash sig=N seq=M``
    header plus one ``j <seq> <op> <tid> <a> <b>`` line per ring
    entry, oldest first."""
    if not text.strip():
        return None
    out: dict = {"signal": None, "ops": []}
    for line in text.splitlines():
        parts = line.split()
        if not parts:
            continue
        if parts[0] == "tn-crash":
            for kv in parts[1:]:
                k, _, v = kv.partition("=")
                if k == "sig" and v.lstrip("-").isdigit():
                    out["signal"] = int(v)
                elif k == "seq" and v.isdigit():
                    out["last_seq"] = int(v)
        elif parts[0] == "j" and len(parts) == 6:
            try:
                seq, op, tid, a, b = (int(x) for x in parts[1:])
            except ValueError:
                continue
            out["ops"].append({"seq": seq,
                               "op": NATIVE_OPS.get(op, f"op{op}"),
                               "tid": tid, "a": a, "b": b})
    out["ops"].sort(key=lambda e: e["seq"])
    return out


def assemble(directory: str, process_index: int = 0,
             events_tail: int = 256) -> dict:
    """Build the crash report dict from whatever artifacts the dead
    process left behind. Every section is best-effort: a report with
    holes beats no report."""
    def p(name: str) -> str:
        return artifact(directory, name, process_index)

    stacks = parse_stacks(_read_text(p(STACKS_TXT)))
    native = parse_native_journal(_read_text(p(NATIVE_JOURNAL_TXT)))
    signal = native["signal"] if native else None
    if signal is not None:
        cause = _SIGNAMES.get(signal, f"signal {signal}")
    elif stacks["fatal"]:
        cause = stacks["fatal"]
    else:
        # No fatal-signal evidence but no clean marker either:
        # SIGKILL / OOM-kill / exit without close. Still a report —
        # the ring tail and thread registry are the whole story then.
        cause = "died-without-fatal-signal"
    return {
        "version": REPORT_VERSION,
        "cause": cause,
        "signal": signal,
        "assembled_t": round(time.time(), 3),
        "process_index": process_index,
        "meta": _read_json(p(META_JSON)),
        "events": _ring.read_ring_file(p(EVENTS_RING), events_tail),
        "threads": _read_json(p(THREADS_JSON)),
        "stacks": stacks,
        "native_journal": native,
        "device_memory": _read_json(p(DEVICE_MEM_JSON)),
    }


def write_report(directory: str, process_index: int = 0) -> str:
    """Assemble and persist ``crash_report.json`` (torn-write-safe).
    Returns the report path."""
    report = assemble(directory, process_index)
    path = artifact(directory, REPORT_NAME, process_index)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1)
    os.replace(tmp, path)
    return path


def is_clean(directory: str, process_index: int = 0) -> bool:
    return os.path.exists(artifact(directory, CLEAN_MARKER,
                                   process_index))
