"""Crash-durable ring buffer of recent structured events.

The black-box half of the flight recorder: a fixed number of
fixed-size slots in a file-backed ``mmap``, so the last N events
survive ANY death mode — SIGSEGV, SIGABRT, even SIGKILL/OOM — with no
crash-time cooperation from the dying process (the page cache owns the
bytes the moment ``pack_into`` returns). Recording an event is one
atomic counter increment plus one 128-byte ``struct.pack_into`` into
mapped memory: ~1-2 us on the host, no syscalls, no locks, no flush —
cheap enough for span begin/end on the per-step path.

Lock-free discipline: the write cursor is an ``itertools.count``
(atomic under the GIL — ``__next__`` never releases it), so concurrent
recorders from any thread claim distinct slots; the only lossy race is
a writer lapped by a FULL ring rotation mid-pack, which corrupts one
slot's text payload at worst (readers decode with ``errors="replace"``
and drop slots whose seq is 0). Readers never coordinate with writers:
``tail()`` snapshots all slots, keeps the highest seqs, and sorts.

Stdlib-only ON PURPOSE: the post-mortem watcher process
(``watch.py``) parses this file without importing jax/numpy — keep it
that way. Dual-mode import (package or bare script) for the same
reason.
"""

from __future__ import annotations

import itertools
import mmap
import os
import struct
import threading
import time
from typing import List, Optional

MAGIC = b"TPFR1\x00"
HEADER = struct.Struct("<6sHII")           # magic, version, slot_size, n_slots
SLOT = struct.Struct("<QdQ16s80s")         # seq, wall_t, tid, kind, msg
SLOT_SIZE = SLOT.size                      # 120
VERSION = 1

DEFAULT_SLOTS = 1024


class EventRing:
    """Fixed-capacity event ring over a file-backed (or anonymous)
    mmap. ``path=None`` backs the ring with anonymous memory — same
    code path, nothing durable (unit tests, dir-less installs)."""

    def __init__(self, path: Optional[str] = None,
                 n_slots: int = DEFAULT_SLOTS):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.path = path
        self.n_slots = n_slots
        size = HEADER.size + n_slots * SLOT_SIZE
        if path:
            # O_TRUNC: one ring = one process incarnation (a resumed
            # run starts a fresh ring; the crash report it might need
            # was already assembled from the old one).
            fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_TRUNC,
                         0o644)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        else:
            self._mm = mmap.mmap(-1, size)
        HEADER.pack_into(self._mm, 0, MAGIC, VERSION, SLOT_SIZE, n_slots)
        self._seq = itertools.count(1)     # 0 marks a never-written slot
        self._closed = False

    # -- write path ------------------------------------------------------

    def record(self, kind: str, msg: str = "") -> None:
        """Append one event. Never raises on the hot path: a recorder
        that can throw is a recorder nobody dares leave on."""
        try:
            seq = next(self._seq)
            off = HEADER.size + ((seq - 1) % self.n_slots) * SLOT_SIZE
            SLOT.pack_into(
                self._mm, off, seq, time.time(),
                threading.get_ident() & 0xFFFFFFFFFFFFFFFF,
                kind.encode("utf-8", "replace")[:16],
                msg.encode("utf-8", "replace")[:80])
        except (TypeError, ValueError, OSError):
            pass                            # closed/unmapped: drop

    # -- read path -------------------------------------------------------

    def tail(self, n: int = 0) -> List[dict]:
        """The last ``n`` events (all, when 0) in seq order."""
        return read_slots(self._mm, n)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass                            # a racing record holds a view


def _decode(raw: bytes) -> str:
    return raw.split(b"\x00", 1)[0].decode("utf-8", "replace")


def read_slots(buf: "bytes | mmap.mmap", n: int = 0) -> List[dict]:
    """Parse ring slots out of any buffer laid out by ``EventRing``
    (live mmap or a post-mortem file read). Torn/garbage slots are
    tolerated; unwritten ones (seq 0) are dropped."""
    try:
        magic, version, slot_size, n_slots = HEADER.unpack_from(buf, 0)
    except struct.error:
        return []
    if magic != MAGIC or slot_size != SLOT_SIZE:
        return []
    events = []
    for i in range(n_slots):
        off = HEADER.size + i * slot_size
        try:
            seq, t, tid, kind, msg = SLOT.unpack_from(buf, off)
        except struct.error:
            break
        if seq == 0:
            continue
        events.append({"seq": seq, "t": round(t, 6), "tid": tid,
                       "kind": _decode(kind), "msg": _decode(msg)})
    events.sort(key=lambda e: e["seq"])
    return events[-n:] if n else events


def read_ring_file(path: str, n: int = 0) -> List[dict]:
    """Post-mortem reader: parse a ring file left behind by a dead
    process."""
    try:
        with open(path, "rb") as f:
            return read_slots(f.read(), n)
    except OSError:
        return []
