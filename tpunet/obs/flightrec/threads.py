"""Host-thread registry: every background thread, one place.

The host runtime that competes with the step loop — the orbax async
checkpoint writer, the telemetry exporter drain thread, the watchdog
monitor, the native prefetcher, the serving engine loop — used to be
invisible: no inventory, no liveness, no way to say WHICH thread a
wedged process was waiting on. Threads now register here with a name
and heartbeat; the registry exports ``thread_*`` gauges (age since
last beat, cumulative beats) and feeds the watchdog's
``thread_stalled`` alert (tpunet/obs/health.py): a thread that
declared a stall budget and has been ``busy`` past it pages through
the existing alert/exporter path.

``beat()`` is one clock read + three attribute stores (atomic enough
under the GIL) — safe on any thread at any rate. Stall detection only
judges *busy* threads: a drain thread parked on an empty queue is
idle, not stalled, so handles flip ``idle``/``busy`` around their
blocking work.

The registry is process-global (``THREADS``) because crash forensics
is process-global: the flight recorder snapshots it into crash
reports, and re-registering a name replaces the old handle (thread
restarts, successive Trainer instances in one process).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

IDLE = "idle"
BUSY = "busy"


class ThreadHandle:
    __slots__ = ("name", "stall_after_s", "state", "last_beat", "beats",
                 "ident", "started_t", "_clock")

    def __init__(self, name: str, stall_after_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.stall_after_s = float(stall_after_s)
        self._clock = clock
        self.state = IDLE
        self.last_beat = clock()
        self.started_t = self.last_beat
        self.beats = 0
        self.ident: Optional[int] = None

    def beat(self, state: Optional[str] = None) -> None:
        """Heartbeat from the owning thread; optionally transitions
        the idle/busy state in the same call. State *transitions*
        (not same-state beats) also land in the flight-recorder ring
        — the busy/idle periods the unified timeline exporter
        (tpunet/obs/history/timeline.py) renders as per-thread
        tracks; one ring write per flip, nothing on same-state
        beats."""
        if state is not None and state != self.state:
            self.state = state
            from tpunet.obs import flightrec
            flightrec.record("thread", f"{state} {self.name}")
        elif state is not None:
            self.state = state
        self.last_beat = self._clock()
        self.beats += 1
        if self.ident is None:
            self.ident = threading.get_ident()

    def set_state(self, state: str) -> None:
        self.beat(state)

    def age_s(self, now: Optional[float] = None) -> float:
        return (now if now is not None else self._clock()) - self.last_beat

    def stalled(self, now: Optional[float] = None) -> bool:
        """True when this thread declared a budget, is marked busy,
        and has not beaten within it."""
        return (self.stall_after_s > 0 and self.state == BUSY
                and self.age_s(now) > self.stall_after_s)


def _gauge_key(name: str) -> str:
    return re.sub(r"[^0-9A-Za-z]+", "_", name).strip("_")


class ThreadRegistry:
    """Name -> handle map; mutation is locked, beats are not (a beat
    touches only its own handle)."""

    def __init__(self):
        self._handles: Dict[str, ThreadHandle] = {}
        self._lock = threading.Lock()

    def register(self, name: str, stall_after_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic
                 ) -> ThreadHandle:
        handle = ThreadHandle(name, stall_after_s, clock)
        with self._lock:
            self._handles[name] = handle
        return handle

    def unregister(self, name: str) -> None:
        with self._lock:
            self._handles.pop(name, None)

    def handles(self) -> List[ThreadHandle]:
        with self._lock:
            return sorted(self._handles.values(), key=lambda h: h.name)

    def stalled(self, now: Optional[float] = None
                ) -> List[Tuple[ThreadHandle, float]]:
        """Every registered thread currently past its stall budget,
        with its heartbeat age."""
        out = []
        for h in self.handles():
            if h.stalled(now):
                out.append((h, h.age_s(now)))
        return out

    def export_gauges(self, registry: object) -> None:
        """Mirror the registry into ``thread_*`` gauges on an obs
        Registry (docs/metrics_schema.md "Registry snapshot keys"):
        ``thread_count`` plus per-thread ``thread_<name>_age_s`` /
        ``thread_<name>_beats``."""
        handles = self.handles()
        registry.gauge("thread_count").set(len(handles))
        for h in handles:
            key = _gauge_key(h.name)
            registry.gauge(f"thread_{key}_age_s").set(round(h.age_s(), 3))
            registry.gauge(f"thread_{key}_beats").set(h.beats)

    def snapshot(self) -> List[dict]:
        """JSON-able rows for the crash report."""
        return [{"name": h.name, "state": h.state,
                 "age_s": round(h.age_s(), 3), "beats": h.beats,
                 "stall_after_s": h.stall_after_s, "ident": h.ident}
                for h in self.handles()]


# The process-wide registry every subsystem registers into.
THREADS = ThreadRegistry()
