"""Post-mortem watcher: the process that outlives the crash.

Signal handlers can capture state but cannot be trusted to assemble a
report — after glibc heap corruption the dying process may not survive
a single ``malloc``, and SIGKILL/OOM-kill run no handlers at all. So
the flight recorder leans on the one mechanism the kernel guarantees:
this tiny sibling process holds the read end of a pipe whose write end
lives in the training process, and ``read()`` returning EOF means the
parent is gone — every death mode, no cooperation required. If the
parent did not mark a clean shutdown, the watcher assembles
``crash_report.json`` from the artifacts the parent's mmap'd rings and
faulthandler left on disk.

Launched BY FILE PATH (``python watch.py``), never as a package
module: importing ``tpunet.obs`` would drag jax in, and this process
idles next to every training run — it must stay a few-MB stdlib
process. Protocol on stdin, one command per line (the dir is the
LAST field and runs to end of line, so paths with spaces survive):

    DIR <process-index> <pid> <flightrec-dir>   watch this dir
    CLEAN                                       shut down cleanly
    ASSEMBLE                                    assemble now (tests)

One watcher serves successive recorder installs in one training
process (the parent re-points it with a new DIR line).
"""

from __future__ import annotations

import os
import sys
from typing import Optional

if __package__:
    from tpunet.obs.flightrec import report as _report
else:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import report as _report                 # type: ignore


def _owned(current: str, pidx: int, pid: int) -> bool:
    """False when meta.json says a DIFFERENT (newer) incarnation owns
    the dir: run dirs are reused across restarts, and a lingering
    watcher whose parent died mid-shutdown must not assemble a report
    over the successor's files."""
    if not pid:
        return True
    import json
    try:
        with open(_report.artifact(current, _report.META_JSON,
                                   pidx)) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return True                  # no/unreadable meta: assemble anyway
    return meta.get("pid") in (None, pid)


def main(stdin: Optional[object] = None) -> int:
    stdin = stdin if stdin is not None else sys.stdin
    current = ""
    pidx = 0
    pid = 0
    for line in stdin:
        parts = line.rstrip("\r\n").split(" ", 3)
        if not parts or not parts[0]:
            continue
        if parts[0] == "DIR" and len(parts) == 4:
            try:
                pidx = int(parts[1])
                pid = int(parts[2])
            except ValueError:
                continue             # malformed: never die over one line
            current = parts[3]
        elif parts[0] == "CLEAN":
            current = ""
        elif parts[0] == "ASSEMBLE" and current:
            try:
                _report.write_report(current, pidx)
            except Exception:
                pass
    # EOF: the parent is dead. A clean parent said CLEAN (or left the
    # marker — close() does both, belt and suspenders); anything else
    # is a crash.
    if current and not _report.is_clean(current, pidx) \
            and _owned(current, pidx, pid):
        try:
            _report.write_report(current, pidx)
        except Exception:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
