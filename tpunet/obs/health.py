"""Run-health watchdog: turn the obs record stream into pages.

The watchdog rides the same host-side observations the registry
already collects — no extra device syncs, no new collectives — and
emits ``obs_alert`` records (through ``Registry.emit``, so they reach
metrics.jsonl AND every live exporter) when a run goes bad in one of
the ways that actually burn walltime:

- **step stall**: a step takes ``stall_factor``x the rolling median of
  recent steps (and at least ``stall_min_s`` — compile-scale blips on
  millisecond steps are not incidents).
- **nan loss / loss spike**: a non-finite loss, or a loss above
  ``loss_spike_factor``x its warmed-up EMA (the divergence shape that
  precedes NaN by a few hundred steps).
- **stale heartbeat / missing processes**: no heartbeat inside
  ``heartbeat_timeout_s`` (a wedged epoch), or an epoch heartbeat
  counting fewer live processes than the pod started with.
- **thread stalled**: a background thread registered in the host-
  thread registry (``tpunet/obs/flightrec/threads.py`` — orbax async
  writer, exporter drain, native prefetcher, serve engine) has been
  ``busy`` past its declared stall budget — per-thread attribution
  for "the host runtime is wedged", with per-thread cooldown keys so
  two stalled threads are two pages.

Alerts are per-reason rate-limited (``alert_cooldown_steps``) so a
stalled input pipeline pages once, not once per step; suppressed
repeats still count (``obs_alerts_suppressed``). With
``halt_on_unhealthy`` a fatal alert raises ``RunUnhealthyError`` after
the record is emitted — the record always lands first, so the
post-mortem shows *why* the run stopped.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Optional


class GaugePredicate:
    """Alert rule over any exported gauge / snapshot key.

    The watchdog's built-in predicates cover the failure shapes we
    could name in advance; these cover the ones the operator names at
    launch time (``--obs-rule``), and the fleet aggregator evaluates
    the same rules per-stream and fleet-wide. Three rule forms, one
    spec grammar::

        serve_queue_depth > 10        # fire while above a threshold
        mfu < 0.3                     # fire while below
        bytes_in_use + 1e6 / s        # fire when the least-squares
                                      # growth rate exceeds 1e6 per
                                      # second (leak shape)

    Threshold rules are stateless; growth rules keep a bounded
    ``(t, value)`` series per predicate instance, so evaluate one
    instance per stream (the aggregator does). ``evaluate`` returns a
    detail dict when the rule fires, else None — alert routing
    (cooldown, halt, emission) belongs to the caller.
    """

    # NAME > VALUE | NAME < VALUE | NAME + VALUE / s
    _SPEC = re.compile(
        r"^\s*([A-Za-z_][A-Za-z0-9_.]*)\s*"
        r"(?:([<>])\s*([-+0-9.eE]+)"
        r"|\+\s*([-+0-9.eE]+)\s*/\s*s)\s*$")

    WINDOW = 32          # growth-rule series bound
    MIN_POINTS = 3       # growth needs a trend, not two samples

    def __init__(self, name: str, *, above: Optional[float] = None,
                 below: Optional[float] = None,
                 grow_per_s: Optional[float] = None,
                 fatal: bool = False, spec: str = ""):
        if sum(x is not None for x in (above, below, grow_per_s)) != 1:
            raise ValueError(
                "exactly one of above/below/grow_per_s is required")
        self.name = name
        self.above = above
        self.below = below
        self.grow_per_s = grow_per_s
        self.fatal = fatal
        self.spec = spec or self._render_spec()
        self._series: deque = deque(maxlen=self.WINDOW)

    def _render_spec(self) -> str:
        if self.above is not None:
            return f"{self.name} > {self.above:g}"
        if self.below is not None:
            return f"{self.name} < {self.below:g}"
        return f"{self.name} + {self.grow_per_s:g}/s"

    @classmethod
    def parse(cls, spec: str, *, fatal: bool = False) -> "GaugePredicate":
        def bad():
            return ValueError(
                f"bad gauge rule {spec!r} (expected 'NAME > N', "
                f"'NAME < N', or 'NAME + N/s')")

        m = cls._SPEC.match(spec)
        if not m:
            raise bad()
        name, cmp_op, threshold, rate = m.groups()
        try:
            # The numeric charset is permissive ("1e", "+-3" match);
            # float() is the real validator — fold its failure into
            # the one diagnostic every malformed rule gets.
            value = float(rate if rate is not None else threshold)
        except ValueError:
            raise bad() from None
        if rate is not None:
            return cls(name, grow_per_s=value, fatal=fatal,
                       spec=spec.strip())
        if cmp_op == ">":
            return cls(name, above=value, fatal=fatal,
                       spec=spec.strip())
        return cls(name, below=value, fatal=fatal, spec=spec.strip())

    def evaluate(self, snapshot: dict, now: float) -> Optional[dict]:
        """One snapshot against the rule. Growth rules also fold the
        sample into their series (so call once per snapshot)."""
        val = snapshot.get(self.name)
        if val is None or isinstance(val, bool) \
                or not isinstance(val, (int, float)) \
                or not math.isfinite(val):
            return None
        if self.above is not None:
            if val > self.above:
                return {"rule": self.spec, "gauge": self.name,
                        "value": val, "threshold": self.above}
            return None
        if self.below is not None:
            if val < self.below:
                return {"rule": self.spec, "gauge": self.name,
                        "value": val, "threshold": self.below}
            return None
        self._series.append((float(now), float(val)))
        if len(self._series) < self.MIN_POINTS:
            return None
        slope = _slope(self._series)
        if slope is not None and slope > self.grow_per_s:
            return {"rule": self.spec, "gauge": self.name,
                    "value": val,
                    "slope_per_s": round(slope, 6),
                    "threshold": self.grow_per_s}
        return None


def _slope(series) -> Optional[float]:
    """Least-squares slope of (t, value) pairs; None on a degenerate
    time axis."""
    n = len(series)
    t0 = series[0][0]
    ts = [t - t0 for t, _ in series]
    vs = [v for _, v in series]
    t_mean = sum(ts) / n
    v_mean = sum(vs) / n
    denom = sum((t - t_mean) ** 2 for t in ts)
    if denom <= 0:
        return None
    return sum((t - t_mean) * (v - v_mean)
               for t, v in zip(ts, vs)) / denom


class RunUnhealthyError(RuntimeError):
    """Raised by the watchdog under ``--halt-on-unhealthy`` after the
    corresponding ``obs_alert`` record has been emitted."""


class Watchdog:
    # Steps of step-time history backing the rolling median baseline.
    WINDOW = 64
    # Baseline warmup: no stall verdicts until this many steps seen
    # (the first steps include compile time and are not a baseline).
    MIN_BASELINE = 8
    # Loss-EMA warmup before spike verdicts, and its decay.
    MIN_LOSS_OBS = 5
    LOSS_EMA_DECAY = 0.9
    # Host-thread stall checks piggyback every Nth step (plus the
    # monitor loop and epoch boundaries).
    THREAD_CHECK_STEPS = 16

    def __init__(self, cfg, registry, *, expected_processes: int = 1,
                 clock=time.monotonic):
        self.cfg = cfg
        self.registry = registry
        self.expected_processes = expected_processes
        # Multi-host halt hook: raising RunUnhealthyError on ONE
        # process of a pod would wedge the others in their next
        # collective, so the trainer sets this to the preemption
        # guard's request() — the existing cross-host-agreed stop then
        # halts every process at a step boundary. When unset
        # (single-process), a fatal alert raises directly.
        self.on_fatal = None
        # Proactive checkpoint-and-evict hook (--evict-on-straggler,
        # docs/elasticity.md): the trainer sets this; straggler-shaped
        # alerts (step_stall / thread_stalled) on THIS replica then
        # trigger a checkpoint-now-then-evict through the agreed stop
        # instead of letting the slow host stall the whole pod. Called
        # AFTER the alert record is emitted, subject to the same
        # cooldown as the page itself.
        self.on_evict = None
        self._clock = clock
        self._laps: deque = deque(maxlen=self.WINDOW)
        self._loss_ema: Optional[float] = None
        self._loss_obs = 0
        self._last_beat = clock()
        self._last_progress = clock()
        self._last_step = 0
        self._last_alert_step: dict = {}
        self._monitor: Optional[threading.Thread] = None
        self._stop_monitor = threading.Event()
        self.alerts: list = []
        # Operator-defined GaugePredicate rules (--obs-rule), checked
        # against registry.snapshot() at epoch boundaries.
        self.gauge_predicates: list = []
        for spec in getattr(cfg, "gauge_rules", ()) or ():
            self.gauge_predicates.append(GaugePredicate.parse(spec))

    # -- observations ----------------------------------------------------

    def observe_step(self, step: int, seconds: float) -> None:
        """One finished step's host lap. Checks the stall predicate
        against the pre-existing baseline, then folds the lap in (a
        median baseline is robust to the stalled samples landing in
        the window), then piggybacks the heartbeat-staleness check —
        the step loop is the only reliable periodic pulse we have."""
        cfg = self.cfg
        if (len(self._laps) >= self.MIN_BASELINE
                and cfg.stall_factor > 0):
            baseline = sorted(self._laps)[len(self._laps) // 2]
            threshold = max(baseline * cfg.stall_factor, cfg.stall_min_s)
            if seconds > threshold:
                self._alert("step_stall", step, fatal=True, detail={
                    "step_time_s": round(seconds, 4),
                    "baseline_p50_s": round(baseline, 4),
                    "threshold_s": round(threshold, 4),
                })
        self._laps.append(seconds)
        self._last_progress = self._clock()
        self._last_step = step
        self.check_heartbeat(step=step)
        if step % self.THREAD_CHECK_STEPS == 0:
            # Cheap but not free (a lock + list copy in the registry),
            # so piggyback every Nth step; the monitor thread and the
            # epoch boundary also check, covering wedged-loop cases.
            self.check_threads(step)

    def observe_loss(self, step: int, loss: float) -> None:
        """A host-available loss value (the per-step log line or the
        epoch summary — the watchdog never forces a device sync to get
        one)."""
        if not math.isfinite(loss):
            self._alert("nan_loss", step, fatal=True,
                        detail={"loss": str(loss)})
            return
        spike = self.cfg.loss_spike_factor
        if (spike > 0 and self._loss_ema is not None
                and self._loss_obs >= self.MIN_LOSS_OBS
                and loss > spike * self._loss_ema):
            self._alert("loss_spike", step, fatal=True, detail={
                "loss": round(loss, 6),
                "ema": round(self._loss_ema, 6),
                "factor": spike,
            })
        d = self.LOSS_EMA_DECAY
        self._loss_ema = (loss if self._loss_ema is None
                          else d * self._loss_ema + (1.0 - d) * loss)
        self._loss_obs += 1

    def observe_heartbeat(self, live: int, step: int = 0) -> None:
        """An epoch-boundary heartbeat: ``live`` processes answered
        the allgather."""
        self._last_beat = self._clock()
        if live < self.expected_processes:
            self._alert("missing_processes", step, fatal=True, detail={
                "live": live, "expected": self.expected_processes})

    def check_heartbeat(self, step: int = 0) -> None:
        """Stale-heartbeat predicate: too long since the last epoch
        heartbeat. Off by default (``heartbeat_timeout_s == 0``) —
        epoch length varies by orders of magnitude across configs, so
        the operator sets the budget."""
        timeout = self.cfg.heartbeat_timeout_s
        if timeout <= 0:
            return
        age = self._clock() - self._last_beat
        if age > timeout:
            self._last_beat = self._clock()  # re-arm, don't re-fire per step
            self._alert("stale_heartbeat", step, fatal=False, detail={
                "age_s": round(age, 2), "timeout_s": timeout})

    def check_threads(self, step: int = 0) -> None:
        """``thread_stalled``: a registered host thread
        (tpunet/obs/flightrec/threads.py) past its declared stall
        budget while marked busy. Non-fatal — a stalled writer thread
        is a page, not automatically a dead run — and cooldown-keyed
        per thread, so the orbax writer stalling and the exporter
        stalling in the same window are two distinct pages."""
        from tpunet.obs.flightrec.threads import THREADS
        for handle, age in THREADS.stalled():
            self._alert("thread_stalled", step, fatal=False, detail={
                "thread": handle.name,
                "age_s": round(age, 2),
                "stall_after_s": handle.stall_after_s,
                "state": handle.state,
            }, cooldown_key=f"thread_stalled:{handle.name}")

    def check_gauges(self, step: int, snapshot: dict) -> None:
        """Evaluate every configured ``GaugePredicate`` against a
        registry snapshot (the epoch-boundary hook — the same flat
        gauge view the exporters ship). Fired rules emit a
        ``gauge_predicate`` obs_alert through the normal path
        (cooldown, halt, record-first ordering all apply); the rule
        spec rides in the detail so the page says which rule."""
        now = self._clock()
        for pred in self.gauge_predicates:
            detail = pred.evaluate(snapshot, now)
            if detail is not None:
                # Cooldown per rule, not per reason: two different
                # rules firing in the same window are two pages.
                self._alert("gauge_predicate", step,
                            fatal=pred.fatal, detail=detail,
                            cooldown_key=f"gauge_predicate:{pred.spec}")

    # -- wedge monitor ---------------------------------------------------

    def start_monitor(self) -> None:
        """Background wedge detector (``heartbeat_timeout_s > 0``
        only): the per-step checks above can never fire when the
        training thread is stuck *inside* a step (the canonical dead-
        collective failure) — this daemon thread watches for the
        absence of any progress and emits a ``stale_heartbeat`` alert
        that still reaches the live exporters, so the operator gets
        paged even though the process itself is wedged. Emit-only: it
        never raises or requests a halt (the training thread may be
        beyond saving, and the alert is the point)."""
        if self._monitor is not None or self.cfg.heartbeat_timeout_s <= 0:
            return
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="tpunet-watchdog",
            daemon=True)
        self._monitor.start()

    def stop_monitor(self) -> None:
        if self._monitor is None:
            return
        self._stop_monitor.set()
        self._monitor.join(timeout=2.0)
        self._monitor = None

    def _monitor_loop(self) -> None:
        from tpunet.obs.flightrec import register_thread
        handle = register_thread("watchdog-monitor")
        timeout = self.cfg.heartbeat_timeout_s
        poll = min(max(timeout / 4.0, 0.5), 5.0)
        while not self._stop_monitor.wait(poll):
            handle.beat()
            # Thread stalls are checkable even while the training
            # thread is wedged inside a step — that is this thread's
            # whole reason to exist.
            self.check_threads(self._last_step)
            age = self._clock() - max(self._last_beat,
                                      self._last_progress)
            if age > timeout:
                # The step counter is frozen while wedged, so the
                # per-reason cooldown keyed on it fires exactly once.
                self._alert("stale_heartbeat", self._last_step,
                            fatal=False, detail={
                                "age_s": round(age, 2),
                                "timeout_s": timeout,
                                "source": "monitor"})

    # -- alert emission --------------------------------------------------

    def _alert(self, reason: str, step: int, *, fatal: bool,
               detail: dict, cooldown_key: str = "") -> None:
        # Every detection lands in the flight-recorder ring (raw
        # forensic signal, a ring cannot be flooded); the page feed
        # below still honors the cooldown.
        from tpunet.obs import flightrec
        flightrec.record("alert", f"{reason} step={step}")
        key = cooldown_key or reason
        last = self._last_alert_step.get(key)
        cooldown = self.cfg.alert_cooldown_steps
        if (last is not None and cooldown > 0 and step - last < cooldown):
            # Uniform suppression, fatal included: on the raising path
            # the first alert already ended the run, and on the
            # on_fatal path the stop agreement takes up to
            # STOP_POLL_STEPS steps to land — re-paging every stalled
            # step in between is exactly what the cooldown exists to
            # prevent (guard.request is idempotent, one call suffices).
            self.registry.counter("obs_alerts_suppressed").inc()
            return
        self._last_alert_step[key] = step
        self.registry.counter("obs_alerts").inc()
        record = {"reason": reason, "step": step,
                  "severity": "fatal" if fatal else "warn"}
        record.update(detail)
        self.alerts.append(record)
        self.registry.emit("obs_alert", record)
        if (self.on_evict is not None
                and reason in ("step_stall", "thread_stalled")):
            # Straggler shape on this replica: hand the record to the
            # trainer's evict path (record-first ordering preserved —
            # the page explains the evict that follows).
            self.on_evict(record)
        if self.cfg.halt_on_unhealthy and fatal:
            if self.on_fatal is not None:
                self.on_fatal(record)
                return
            raise RunUnhealthyError(
                f"run unhealthy: {reason} at step {step} ({detail}); "
                "--halt-on-unhealthy is set")
