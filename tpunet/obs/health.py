"""Run-health watchdog: turn the obs record stream into pages.

The watchdog rides the same host-side observations the registry
already collects — no extra device syncs, no new collectives — and
emits ``obs_alert`` records (through ``Registry.emit``, so they reach
metrics.jsonl AND every live exporter) when a run goes bad in one of
the ways that actually burn walltime:

- **step stall**: a step takes ``stall_factor``x the rolling median of
  recent steps (and at least ``stall_min_s`` — compile-scale blips on
  millisecond steps are not incidents).
- **nan loss / loss spike**: a non-finite loss, or a loss above
  ``loss_spike_factor``x its warmed-up EMA (the divergence shape that
  precedes NaN by a few hundred steps).
- **stale heartbeat / missing processes**: no heartbeat inside
  ``heartbeat_timeout_s`` (a wedged epoch), or an epoch heartbeat
  counting fewer live processes than the pod started with.

Alerts are per-reason rate-limited (``alert_cooldown_steps``) so a
stalled input pipeline pages once, not once per step; suppressed
repeats still count (``obs_alerts_suppressed``). With
``halt_on_unhealthy`` a fatal alert raises ``RunUnhealthyError`` after
the record is emitted — the record always lands first, so the
post-mortem shows *why* the run stopped.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Optional


class RunUnhealthyError(RuntimeError):
    """Raised by the watchdog under ``--halt-on-unhealthy`` after the
    corresponding ``obs_alert`` record has been emitted."""


class Watchdog:
    # Steps of step-time history backing the rolling median baseline.
    WINDOW = 64
    # Baseline warmup: no stall verdicts until this many steps seen
    # (the first steps include compile time and are not a baseline).
    MIN_BASELINE = 8
    # Loss-EMA warmup before spike verdicts, and its decay.
    MIN_LOSS_OBS = 5
    LOSS_EMA_DECAY = 0.9

    def __init__(self, cfg, registry, *, expected_processes: int = 1,
                 clock=time.monotonic):
        self.cfg = cfg
        self.registry = registry
        self.expected_processes = expected_processes
        # Multi-host halt hook: raising RunUnhealthyError on ONE
        # process of a pod would wedge the others in their next
        # collective, so the trainer sets this to the preemption
        # guard's request() — the existing cross-host-agreed stop then
        # halts every process at a step boundary. When unset
        # (single-process), a fatal alert raises directly.
        self.on_fatal = None
        self._clock = clock
        self._laps: deque = deque(maxlen=self.WINDOW)
        self._loss_ema: Optional[float] = None
        self._loss_obs = 0
        self._last_beat = clock()
        self._last_progress = clock()
        self._last_step = 0
        self._last_alert_step: dict = {}
        self._monitor: Optional[threading.Thread] = None
        self._stop_monitor = threading.Event()
        self.alerts: list = []

    # -- observations ----------------------------------------------------

    def observe_step(self, step: int, seconds: float) -> None:
        """One finished step's host lap. Checks the stall predicate
        against the pre-existing baseline, then folds the lap in (a
        median baseline is robust to the stalled samples landing in
        the window), then piggybacks the heartbeat-staleness check —
        the step loop is the only reliable periodic pulse we have."""
        cfg = self.cfg
        if (len(self._laps) >= self.MIN_BASELINE
                and cfg.stall_factor > 0):
            baseline = sorted(self._laps)[len(self._laps) // 2]
            threshold = max(baseline * cfg.stall_factor, cfg.stall_min_s)
            if seconds > threshold:
                self._alert("step_stall", step, fatal=True, detail={
                    "step_time_s": round(seconds, 4),
                    "baseline_p50_s": round(baseline, 4),
                    "threshold_s": round(threshold, 4),
                })
        self._laps.append(seconds)
        self._last_progress = self._clock()
        self._last_step = step
        self.check_heartbeat(step=step)

    def observe_loss(self, step: int, loss: float) -> None:
        """A host-available loss value (the per-step log line or the
        epoch summary — the watchdog never forces a device sync to get
        one)."""
        if not math.isfinite(loss):
            self._alert("nan_loss", step, fatal=True,
                        detail={"loss": str(loss)})
            return
        spike = self.cfg.loss_spike_factor
        if (spike > 0 and self._loss_ema is not None
                and self._loss_obs >= self.MIN_LOSS_OBS
                and loss > spike * self._loss_ema):
            self._alert("loss_spike", step, fatal=True, detail={
                "loss": round(loss, 6),
                "ema": round(self._loss_ema, 6),
                "factor": spike,
            })
        d = self.LOSS_EMA_DECAY
        self._loss_ema = (loss if self._loss_ema is None
                          else d * self._loss_ema + (1.0 - d) * loss)
        self._loss_obs += 1

    def observe_heartbeat(self, live: int, step: int = 0) -> None:
        """An epoch-boundary heartbeat: ``live`` processes answered
        the allgather."""
        self._last_beat = self._clock()
        if live < self.expected_processes:
            self._alert("missing_processes", step, fatal=True, detail={
                "live": live, "expected": self.expected_processes})

    def check_heartbeat(self, step: int = 0) -> None:
        """Stale-heartbeat predicate: too long since the last epoch
        heartbeat. Off by default (``heartbeat_timeout_s == 0``) —
        epoch length varies by orders of magnitude across configs, so
        the operator sets the budget."""
        timeout = self.cfg.heartbeat_timeout_s
        if timeout <= 0:
            return
        age = self._clock() - self._last_beat
        if age > timeout:
            self._last_beat = self._clock()  # re-arm, don't re-fire per step
            self._alert("stale_heartbeat", step, fatal=False, detail={
                "age_s": round(age, 2), "timeout_s": timeout})

    # -- wedge monitor ---------------------------------------------------

    def start_monitor(self) -> None:
        """Background wedge detector (``heartbeat_timeout_s > 0``
        only): the per-step checks above can never fire when the
        training thread is stuck *inside* a step (the canonical dead-
        collective failure) — this daemon thread watches for the
        absence of any progress and emits a ``stale_heartbeat`` alert
        that still reaches the live exporters, so the operator gets
        paged even though the process itself is wedged. Emit-only: it
        never raises or requests a halt (the training thread may be
        beyond saving, and the alert is the point)."""
        if self._monitor is not None or self.cfg.heartbeat_timeout_s <= 0:
            return
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="tpunet-watchdog",
            daemon=True)
        self._monitor.start()

    def stop_monitor(self) -> None:
        if self._monitor is None:
            return
        self._stop_monitor.set()
        self._monitor.join(timeout=2.0)
        self._monitor = None

    def _monitor_loop(self) -> None:
        timeout = self.cfg.heartbeat_timeout_s
        poll = min(max(timeout / 4.0, 0.5), 5.0)
        while not self._stop_monitor.wait(poll):
            age = self._clock() - max(self._last_beat,
                                      self._last_progress)
            if age > timeout:
                # The step counter is frozen while wedged, so the
                # per-reason cooldown keyed on it fires exactly once.
                self._alert("stale_heartbeat", self._last_step,
                            fatal=False, detail={
                                "age_s": round(age, 2),
                                "timeout_s": timeout,
                                "source": "monitor"})

    # -- alert emission --------------------------------------------------

    def _alert(self, reason: str, step: int, *, fatal: bool,
               detail: dict) -> None:
        last = self._last_alert_step.get(reason)
        cooldown = self.cfg.alert_cooldown_steps
        if (last is not None and cooldown > 0 and step - last < cooldown):
            # Uniform suppression, fatal included: on the raising path
            # the first alert already ended the run, and on the
            # on_fatal path the stop agreement takes up to
            # STOP_POLL_STEPS steps to land — re-paging every stalled
            # step in between is exactly what the cooldown exists to
            # prevent (guard.request is idempotent, one call suffices).
            self.registry.counter("obs_alerts_suppressed").inc()
            return
        self._last_alert_step[reason] = step
        self.registry.counter("obs_alerts").inc()
        record = {"reason": reason, "step": step,
                  "severity": "fatal" if fatal else "warn"}
        record.update(detail)
        self.alerts.append(record)
        self.registry.emit("obs_alert", record)
        if self.cfg.halt_on_unhealthy and fatal:
            if self.on_fatal is not None:
                self.on_fatal(record)
                return
            raise RunUnhealthyError(
                f"run unhealthy: {reason} at step {step} ({detail}); "
                "--halt-on-unhealthy is set")
