"""Run history: remember runs, compare runs, replay runs on one clock.

Three pieces over the telemetry the rest of ``tpunet/obs`` already
emits (nothing here adds a byte to the hot path):

- ``store``       — append-only run-history index: completed run dirs
  (``metrics.jsonl``) and ``BENCH_r*.json`` artifacts digested into
  bounded per-run summaries, keyed by ``run_id`` + config
  fingerprint.
- ``compare``     — cross-run regression compare: overlapping-step
  alignment, quantile deltas judged against the DKW rank-error bounds
  from ``tpunet/obs/agg/merge.py``, emitted as ``obs_regression``
  records (``scripts/obs_compare.py`` exit-codes on the verdict).
- ``timeline``    — unified Perfetto/chrome-trace exporter over the
  flight-recorder rings: host threads, device phases, and serve
  request lifecycles from one or more runs on one wall clock
  (``scripts/obs_timeline.py``).

``fingerprint`` supplies the config hash both joins key on
(docs/metrics_schema.md "Run identity").
"""

from __future__ import annotations

from tpunet.obs.history.compare import (compare_summaries,
                                        emit_regression,
                                        quantile_verdict,
                                        stream_regressions)
from tpunet.obs.history.fingerprint import (config_fingerprint,
                                            train_fingerprint)
from tpunet.obs.history.store import (RunHistory, bench_entry,
                                      summarize_run)
from tpunet.obs.history.timeline import build_timeline, write_trace

__all__ = [
    "RunHistory", "bench_entry", "build_timeline", "compare_summaries",
    "config_fingerprint", "emit_regression", "quantile_verdict",
    "stream_regressions", "summarize_run", "train_fingerprint",
    "write_trace",
]
