"""Cross-run regression compare with honest error bars.

Comparing two runs' step-time percentiles naively calls every wobble a
regression: each run's quantiles are estimates from bounded exported
samples (``Histogram.export_sample``), so the comparison must carry
the same DKW + striding rank-error bound the fleet merge math already
quantifies (``tpunet/obs/agg/merge.py``). The rule here: a
"regression" verdict is only emitted when the two runs' quantile
*confidence intervals* — each quantile widened by its own rank-error
bound, translated to value space through the run's own sample — do
not overlap. Everything inside the bars is ``within_error``, which is
the honest answer, not a hedge.

Alignment: two runs of the same config fingerprint are compared over
their overlapping global-step range (epoch windows fully inside the
overlap), so a short run's warmup is never judged against a long
run's steady state. Exact scalars (throughput, MFU) have no sampling
error bar; they get a relative ``tolerance`` instead, mirroring the
byte/serve budget gates.

The result dict is the ``obs_regression`` record body
(docs/metrics_schema.md) — ``scripts/obs_compare.py`` exit-codes on
its ``verdict``, the fleet dashboard renders it, and the alert
webhook pages on it.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from tpunet.obs.agg import merge

QUANTILES = (50, 90, 99)

#: Relative tolerance for exact scalars (throughput, MFU) — same
#: spirit as docs/bytes_budget.json's tolerance_frac.
DEFAULT_TOLERANCE = 0.05

VERDICT_REGRESSION = "regression"
VERDICT_IMPROVEMENT = "improvement"
VERDICT_WITHIN_ERROR = "within_error"
VERDICT_OK = "ok"
VERDICT_INCOMPARABLE = "incomparable"


def _window_span(w: dict) -> Optional[Tuple[int, int]]:
    step, steps = w.get("step"), int(w.get("steps") or 0)
    if step is None or steps <= 0:
        return None
    return (step - steps + 1, step)


def overlap_range(a: dict, b: dict) -> Optional[Tuple[int, int]]:
    """Overlapping global-step range of two run summaries; None when
    either run carries no step extent or they never overlap."""
    if None in (a.get("step_lo"), a.get("step_hi"),
                b.get("step_lo"), b.get("step_hi")):
        return None
    lo = max(a["step_lo"], b["step_lo"])
    hi = min(a["step_hi"], b["step_hi"])
    return (lo, hi) if lo <= hi else None


def aligned_windows(summary: dict,
                    step_range: Optional[Tuple[int, int]] = None
                    ) -> List[dict]:
    """A summary's epoch windows restricted to those fully inside
    ``step_range`` (falling back to windows that merely intersect it
    when none fit — two runs whose epoch grids disagree still
    compare, on the closest-aligned data available)."""
    windows = summary.get("epoch_windows") or []
    if step_range is None:
        return list(windows)
    lo, hi = step_range
    inside, touching = [], []
    for w in windows:
        span = _window_span(w)
        if span is None:
            continue
        if span[0] >= lo and span[1] <= hi:
            inside.append(w)
        elif span[1] >= lo and span[0] <= hi:
            touching.append(w)
    return inside if inside else touching


def window_parts(summary: dict,
                 step_range: Optional[Tuple[int, int]] = None
                 ) -> List[merge.Part]:
    """Merge parts from a summary's (aligned) epoch windows."""
    out: List[merge.Part] = []
    for w in aligned_windows(summary, step_range):
        sample = w.get("sample")
        steps = int(w.get("steps") or 0)
        if sample and steps > 0:
            out.append((sample, steps, bool(w.get("approx"))))
    return out


def _aligned_scalar(summary: dict, key: str,
                    step_range: Optional[Tuple[int, int]]
                    ) -> Optional[float]:
    """Steps-weighted mean of a per-window scalar over the SAME
    aligned window set the quantiles use — a short run's compile/
    warmup epochs must not weigh into its mean any more than they
    weigh into its percentiles (they fall outside the overlap, or
    carry their own small step weight inside it)."""
    num = den = 0.0
    for w in aligned_windows(summary, step_range):
        v = w.get(key)
        steps = int(w.get("steps") or 0)
        if v is not None and steps > 0:
            num += v * steps
            den += steps
    return num / den if den > 0 else None


def quantile_verdict(parts_a: List[merge.Part],
                     parts_b: List[merge.Part], q: float,
                     *, larger_is_worse: bool = True) -> Optional[dict]:
    """One quantile's comparison row, or None when either side has no
    sample data.

    The interval for run X at quantile q is
    ``[Q_X(q - err_X), Q_X(q + err_X)]`` where ``err_X`` is the run's
    rank-error bound (striding + DKW, ``merge.rank_error_bound``): the
    true quantile's rank is within ``err_X`` of q, so its value lies
    between the estimated quantiles at the shifted ranks. Disjoint
    intervals are a verdict; overlapping ones are ``within_error``.
    """
    if not parts_a or not parts_b:
        return None
    err_a = merge.rank_error_bound(parts_a)
    err_b = merge.rank_error_bound(parts_b)

    def interval(parts, err):
        qs = (max(0.0, q - 100.0 * err), q, min(100.0, q + 100.0 * err))
        m = merge.merged_quantiles(parts, qs)
        return m[qs[0]], m[q], m[qs[2]]

    a_lo, a, a_hi = interval(parts_a, err_a)
    b_lo, b, b_hi = interval(parts_b, err_b)
    if b_lo > a_hi:
        verdict = (VERDICT_REGRESSION if larger_is_worse
                   else VERDICT_IMPROVEMENT)
    elif b_hi < a_lo:
        verdict = (VERDICT_IMPROVEMENT if larger_is_worse
                   else VERDICT_REGRESSION)
    else:
        verdict = VERDICT_WITHIN_ERROR
    return {
        "a": round(a, 6), "b": round(b, 6),
        "delta": round(b - a, 6),
        "delta_frac": round((b - a) / a, 4) if a else None,
        "a_lo": round(a_lo, 6), "a_hi": round(a_hi, 6),
        "b_lo": round(b_lo, 6), "b_hi": round(b_hi, 6),
        "rank_err_a": round(err_a, 4), "rank_err_b": round(err_b, 4),
        "verdict": verdict,
    }


def _scalar_row(metric: str, a, b, tolerance: float,
                larger_is_worse: bool) -> Optional[dict]:
    """Exact-scalar comparison (throughput, MFU): no sampling error,
    so the bar is a relative tolerance."""
    if a is None or b is None or a == 0:
        return None
    delta_frac = (b - a) / abs(a)
    worse = delta_frac > tolerance if larger_is_worse \
        else delta_frac < -tolerance
    better = delta_frac < -tolerance if larger_is_worse \
        else delta_frac > tolerance
    return {
        "metric": metric, "a": round(a, 6), "b": round(b, 6),
        "delta": round(b - a, 6), "delta_frac": round(delta_frac, 4),
        "tolerance": tolerance,
        "verdict": (VERDICT_REGRESSION if worse
                    else VERDICT_IMPROVEMENT if better
                    else VERDICT_WITHIN_ERROR),
    }


def _serve_parts(summary: dict, key: str) -> List[merge.Part]:
    raw = (summary.get("serve") or {}).get(f"{key}_parts") or []
    return [(s, int(n), bool(sat)) for s, n, sat in raw]


def _trace_parts(summary: dict, key: str) -> List[merge.Part]:
    raw = (summary.get("trace") or {}).get(f"{key}_parts") or []
    return [(s, int(n), bool(sat)) for s, n, sat in raw]


def compare_summaries(a: dict, b: dict, *,
                      quantiles: Sequence[float] = QUANTILES,
                      tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Two run summaries (``store.summarize_run``) -> one
    ``obs_regression`` record body. ``a`` is the baseline; verdicts
    describe ``b`` relative to it."""
    fp_a = a.get("config_fingerprint")
    fp_b = b.get("config_fingerprint")
    out: dict = {
        "run_a": a.get("run_id") or a.get("source", ""),
        "run_b": b.get("run_id") or b.get("source", ""),
        "fingerprint_match": (fp_a == fp_b
                              if fp_a and fp_b else None),
    }
    if fp_a:
        out["config_fingerprint"] = fp_a
    rng = overlap_range(a, b)
    if rng is not None:
        out["step_lo"], out["step_hi"] = rng

    metrics: List[dict] = []
    parts_a = window_parts(a, rng)
    parts_b = window_parts(b, rng)
    out["windows_a"] = len(parts_a)
    out["windows_b"] = len(parts_b)
    for q in quantiles:
        row = quantile_verdict(parts_a, parts_b, q)
        if row is not None:
            metrics.append({"metric": f"step_time_p{q:g}_s", **row})
    # Scalars are aligned to the SAME overlap windows as the
    # quantiles (a 3-epoch candidate's compile epoch must not carry
    # 1/3 weight against a 30-epoch baseline's 1/30); the whole-run
    # summary means are only a fallback for fingerprint-stamped but
    # window-less streams.
    thr_key = {"tokens": "tokens_per_sec",
               "examples": "examples_per_sec"}.get(
                   a.get("throughput_unit") or b.get("throughput_unit"))
    scalars = []
    if thr_key:
        scalars.append(("throughput_mean", thr_key))
    scalars.append(("mfu", "mfu"))
    for metric, key in scalars:
        va = _aligned_scalar(a, key, rng)
        vb = _aligned_scalar(b, key, rng)
        if va is None or vb is None:
            va, vb = a.get(metric), b.get(metric)
        row = _scalar_row(metric, va, vb, tolerance,
                          larger_is_worse=False)
        if row is not None:
            metrics.append(row)
    for key in ("ttft", "e2e"):
        sp_a, sp_b = _serve_parts(a, key), _serve_parts(b, key)
        for q in quantiles:
            row = quantile_verdict(sp_a, sp_b, q)
            if row is not None:
                metrics.append({"metric": f"serve_{key}_p{q:g}_s",
                                **row})
    # Trace-phase quantiles (store.TRACE_PHASES): the TTFT
    # decomposition, so a serve regression names the phase it lives
    # in (queue grew vs prefill grew) instead of just the symptom.
    for key in ("queue", "prefill", "first_decode"):
        tp_a, tp_b = _trace_parts(a, key), _trace_parts(b, key)
        for q in quantiles:
            row = quantile_verdict(tp_a, tp_b, q)
            if row is not None:
                metrics.append({"metric": f"trace_{key}_p{q:g}_s",
                                **row})
    out["metrics"] = metrics
    out["regressions"] = sum(
        1 for m in metrics if m["verdict"] == VERDICT_REGRESSION)
    out["improvements"] = sum(
        1 for m in metrics if m["verdict"] == VERDICT_IMPROVEMENT)
    for side, run in (("a", a), ("b", b)):
        for key in ("alerts", "crashes"):
            if run.get(key):
                out[f"{key}_{side}"] = run[key]
    if not metrics:
        out["verdict"] = VERDICT_INCOMPARABLE
    elif out["regressions"]:
        out["verdict"] = VERDICT_REGRESSION
    else:
        out["verdict"] = VERDICT_OK
    return out


def emit_regression(registry, comparison: dict) -> None:
    """One ``obs_regression`` record through a Registry, so it reaches
    metrics sinks, live exporters, and the alert webhook (which pages
    on the kind when the verdict says regression)."""
    registry.emit("obs_regression", comparison)


def stream_regressions(streams) -> List[dict]:
    """Fleet-dashboard panel rows: pairwise last-window compare of
    trainer streams sharing a config fingerprint (identity-stamped
    since this PR). Baseline = lexicographically-first stream key per
    fingerprint group, so the pairing is deterministic under replay."""
    by_fp: dict = {}
    for s in streams:
        fp = (s.identity or {}).get("config_fingerprint")
        if fp and s.last_epoch is not None:
            by_fp.setdefault(fp, []).append(s)
    rows: List[dict] = []
    for fp in sorted(by_fp):
        group = sorted(by_fp[fp], key=lambda s: s.key)
        if len(group) < 2:
            continue
        base = group[0]
        parts_a = merge.record_parts([base.last_epoch],
                                     "step_time_sample", "steps")
        for other in group[1:]:
            parts_b = merge.record_parts([other.last_epoch],
                                         "step_time_sample", "steps")
            row = quantile_verdict(parts_a, parts_b, 50)
            if row is None:
                continue
            rows.append({"fingerprint": fp, "base": base.key,
                         "stream": other.key,
                         "metric": "step_time_p50_s", **row})
    return rows
