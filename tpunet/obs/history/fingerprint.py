"""Config fingerprint: the join key that makes two runs comparable.

A cross-run comparison is only meaningful between runs of the SAME
workload — same model, data shape, optimizer, mesh. Nothing in a
record stream says so; run_id only names one run. The fingerprint is a
stable short hash of the compute-relevant config, stamped into the run
identity (docs/metrics_schema.md "Run identity") and into bench.py's
BENCH records, so the history store can (a) group runs that are
apples-to-apples and (b) join bench rounds to the training config that
produced them.

Stability contract: the hash is over a canonical JSON rendering
(sorted keys, no whitespace variance) of a *selected* sub-config —
fields that change the computation. Bookkeeping knobs (checkpoint
directory, run_id, telemetry endpoints, log cadence) are excluded on
purpose: re-running the same training job with a different dashboard
attached must not change its fingerprint.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any

#: Hex digest length. 12 hex chars = 48 bits: collision-free for any
#: plausible number of distinct configs in one history store.
DIGEST_LEN = 12


def _canonical(obj: Any) -> Any:
    """JSON-able canonical form: dataclasses -> sorted dicts, tuples ->
    lists, everything else passed through json's own type checks."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _canonical(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {str(k): _canonical(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    return obj


def config_fingerprint(obj: Any) -> str:
    """Stable short hash of any JSON-able / dataclass config value."""
    blob = json.dumps(_canonical(obj), sort_keys=True,
                      separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:DIGEST_LEN]


def train_fingerprint(cfg: Any) -> str:
    """Fingerprint of a ``TrainConfig``: the compute-relevant
    sub-configs only (model / data / optim / mesh + epoch count).
    Checkpoint paths, obs/export endpoints, and profiling knobs are
    deliberately excluded — they do not change what the run computes,
    so they must not break run-to-run comparability."""
    return config_fingerprint({
        "model": _canonical(cfg.model),
        "data": _canonical(cfg.data),
        "optim": _canonical(cfg.optim),
        "mesh": _canonical(cfg.mesh),
        "epochs": getattr(cfg, "epochs", None),
    })
