"""Run-history store: an append-only index of completed runs.

The paper's workload is a one-shot script with no memory of previous
runs; tpunet emits rich per-run telemetry (``metrics.jsonl``,
``BENCH_r*.json``) but nothing that remembers run N when run N+1
lands. This store closes that gap: ``ingest_run`` digests a finished
run directory into one bounded summary line, ``ingest_bench`` files a
bench artifact next to the training run that produced it (joined by
``run_id`` + config fingerprint — not by filename convention), and the
read side hands back the latest summary per run for the regression
compare (``tpunet/obs/history/compare.py``) and the CLI
(``scripts/obs_compare.py``).

Storage discipline: one jsonl file (``history.jsonl``), append-only
with per-line flush — the same torn-line-tolerant format as
``metrics.jsonl``, read back through ``MetricsLogger.read_records``.
Re-ingesting a run appends a fresh line; readers resolve latest-wins
per ``(kind, run_id)``, so the index never needs rewriting and a crash
mid-append costs at most the last line.

Summaries are deterministic functions of the ingested records — no
wall-clock stamps — so ingesting the same run dir twice produces
byte-identical lines and downstream compare verdicts are reproducible
(the acceptance property the fixture tests pin).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from tpunet.obs.agg import merge

INDEX_NAME = "history.jsonl"

#: Per-run bound on retained epoch windows (newest kept): enough for
#: any overlap alignment window, small enough that a summary line
#: stays a few tens of KB even with full 256-point samples.
EPOCH_WINDOWS_KEEP = 64
#: Same bound for retained serve windows.
SERVE_WINDOWS_KEEP = 64
#: Per-phase cap on retained trace samples: a deterministic stride
#: over the run's full sorted span population (the striding term of
#: ``merge.part_rank_error`` covers it — no reservoir randomness).
TRACE_SAMPLE_CAP = 256

#: The per-phase TTFT decomposition obs_trace replica-role spans
#: carry (docs/metrics_schema.md "obs_trace"): admission wait,
#: prefill device time, prefill-done -> first token out.
TRACE_PHASES = ("queue", "prefill", "first_decode")


def summarize_run(records: List[dict], source: str = "") -> dict:
    """One run's record stream -> the bounded summary the store files.

    Pure function of the records (no clock, no filesystem): throughput
    and MFU from the epoch rows, step-time quantiles merged from the
    exported rank-strided samples (``Histogram.export_sample``) with
    their DKW rank-error bound, serve TTFT/e2e SLO merges, and
    alert/crash counts.
    """
    summary: dict = {"kind": "run", "source": source}
    epochs = [r for r in records if r.get("kind") == "obs_epoch"]
    serves = [r for r in records if r.get("kind") == "obs_serve"]
    alerts = [r for r in records if r.get("kind") == "obs_alert"]
    crashes = [r for r in records if r.get("kind") == "obs_crash"]
    for r in records:
        for k in ("run_id", "config_fingerprint", "host"):
            if r.get(k) is not None:
                summary[k] = r[k]
    summary.setdefault("run_id", "")
    summary["records"] = len(records)

    windows = []
    for r in epochs:
        w = {"epoch": r.get("epoch"), "step": r.get("step"),
             "steps": int(r.get("steps") or 0)}
        if r.get("step_time_sample"):
            w["sample"] = r["step_time_sample"]
        if r.get("step_time_approx"):
            w["approx"] = 1
        for key in ("examples_per_sec", "tokens_per_sec", "mfu",
                    "step_time_p50_s"):
            if r.get(key) is not None:
                w[key] = r[key]
        windows.append(w)
    windows = windows[-EPOCH_WINDOWS_KEEP:]
    if windows:
        summary["epochs"] = len(epochs)
        summary["epoch_windows"] = windows
        summary["steps_total"] = sum(w["steps"] for w in windows)
        spans = [(w["step"] - w["steps"] + 1, w["step"])
                 for w in windows
                 if w.get("step") is not None and w["steps"] > 0]
        if spans:
            summary["step_lo"] = min(lo for lo, _ in spans)
            summary["step_hi"] = max(hi for _, hi in spans)
        last = epochs[-1]
        for key, unit in (("tokens_per_sec", "tokens"),
                          ("examples_per_sec", "examples")):
            if last.get(key) is not None:
                summary["throughput"] = last[key]
                summary["throughput_unit"] = unit
                vals = [w[key] for w in windows if w.get(key) is not None]
                if vals:
                    summary["throughput_mean"] = round(
                        sum(vals) / len(vals), 2)
                break
        if last.get("mfu") is not None:
            summary["mfu"] = last["mfu"]
        parts = merge.record_parts(
            [{"step_time_sample": w.get("sample"),
              "steps": w["steps"],
              "step_time_approx": w.get("approx")} for w in windows],
            "step_time_sample", "steps")
        if parts:
            merged = merge.merged_quantiles(parts, (50, 90, 99))
            summary["step_time_p50_s"] = round(merged[50], 6)
            summary["step_time_p90_s"] = round(merged[90], 6)
            summary["step_time_p99_s"] = round(merged[99], 6)
            summary["step_time_rank_err"] = round(
                merge.rank_error_bound(parts), 4)

    if serves:
        last = serves[-1]
        sv: dict = {"windows": len(serves)}
        for key in ("requests_total", "requests_completed",
                    "requests_rejected", "tokens_total", "slots"):
            if last.get(key) is not None:
                sv[key] = last[key]
        for key in ("ttft", "e2e"):
            parts = merge.record_parts(serves[-SERVE_WINDOWS_KEEP:],
                                       f"{key}_sample", f"{key}_count")
            if parts:
                merged = merge.merged_quantiles(parts, (50, 90, 99))
                sv[f"{key}_p50_s"] = round(merged[50], 6)
                sv[f"{key}_p90_s"] = round(merged[90], 6)
                sv[f"{key}_p99_s"] = round(merged[99], 6)
                sv[f"{key}_rank_err"] = round(
                    merge.rank_error_bound(parts), 4)
                sv[f"{key}_parts"] = [
                    [list(s), n, bool(sat)] for s, n, sat in parts]
        summary["serve"] = sv

    # Per-phase TTFT decomposition from replica-role trace spans:
    # where a regression LIVES (admission wait vs prefill vs first
    # decode), not just that TTFT moved. Spans are raw scalars per
    # record, so the part is built here: full sorted population,
    # stride-capped, count = true span count (compare.py merges it
    # through the same DKW machinery as step-time/serve samples).
    spans = [r for r in records if r.get("kind") == "obs_trace"
             and r.get("role") == "replica"]
    if spans:
        tr: dict = {"spans": len(spans)}
        for phase in TRACE_PHASES:
            vals = sorted(float(r[f"{phase}_s"]) for r in spans
                          if r.get(f"{phase}_s") is not None)
            if not vals:
                continue
            n = len(vals)
            if n > TRACE_SAMPLE_CAP:
                stride = n / TRACE_SAMPLE_CAP
                vals = [vals[min(n - 1, int(i * stride))]
                        for i in range(TRACE_SAMPLE_CAP)]
            parts = [(vals, n, False)]
            merged = merge.merged_quantiles(parts, (50, 90, 99))
            tr[f"{phase}_p50_s"] = round(merged[50], 6)
            tr[f"{phase}_p90_s"] = round(merged[90], 6)
            tr[f"{phase}_p99_s"] = round(merged[99], 6)
            tr[f"{phase}_rank_err"] = round(
                merge.rank_error_bound(parts), 4)
            tr[f"{phase}_parts"] = [
                [list(s), cnt, bool(sat)] for s, cnt, sat in parts]
        summary["trace"] = tr

    if alerts:
        by_reason: Dict[str, int] = {}
        for a in alerts:
            r = str(a.get("reason", "unknown"))
            by_reason[r] = by_reason.get(r, 0) + 1
        summary["alerts"] = len(alerts)
        summary["alerts_by_reason"] = dict(sorted(by_reason.items()))
    if crashes:
        summary["crashes"] = len(crashes)
    return summary


def bench_entry(record: dict, source: str = "") -> dict:
    """A BENCH artifact -> its index line. Accepts both the raw
    bench.py record and the driver-style wrapper (``{"parsed": ...}``)
    the checked-in ``BENCH_r*.json`` files use."""
    if isinstance(record.get("parsed"), dict):
        record = record["parsed"]
    entry: dict = {"kind": "bench", "source": source}
    for key in ("run_id", "config_fingerprint", "metric", "value",
                "unit", "device_kind", "mfu", "pct_of_roofline",
                "roofline_bytes_per_image", "model_overrides"):
        if record.get(key) is not None:
            entry[key] = record[key]
    entry.setdefault("run_id", "")
    return entry


class RunHistory:
    """Append-only run index under one directory.

    Readers tolerate the torn trailing line; writers append one
    flushed line per ingest. Latest line wins per ``(kind, run_id)``
    — ingesting a run again (more epochs landed) simply supersedes
    the earlier summary.
    """

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, INDEX_NAME)

    # -- write side ------------------------------------------------------

    def _append(self, entry: dict) -> dict:
        os.makedirs(self.directory, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(entry) + "\n")
        return entry

    def ingest_run(self, run_dir: str) -> dict:
        """Digest ``<run_dir>/metrics.jsonl`` into one summary line.
        Raises FileNotFoundError when the run dir has no metrics."""
        path = os.path.join(run_dir, "metrics.jsonl")
        if not os.path.isfile(path):
            raise FileNotFoundError(
                f"no metrics.jsonl under {run_dir!r} — not a completed "
                "run directory")
        from tpunet.utils.logging import MetricsLogger
        records = MetricsLogger.read_records(path)
        return self._append(summarize_run(records, source=run_dir))

    def ingest_bench(self, path: str) -> dict:
        """File one ``BENCH_r*.json`` (or raw bench.py stdout record)
        under its ``run_id`` + ``config_fingerprint``."""
        with open(path) as f:
            record = json.load(f)
        return self._append(bench_entry(record, source=path))

    # -- read side -------------------------------------------------------

    def entries(self, kind: Optional[str] = None) -> List[dict]:
        """Every index line in append order (optionally one kind)."""
        if not os.path.isfile(self.path):
            return []
        from tpunet.utils.logging import MetricsLogger
        out = MetricsLogger.read_records(self.path)
        if kind is not None:
            out = [e for e in out if e.get("kind") == kind]
        return out

    def runs(self, fingerprint: Optional[str] = None) -> List[dict]:
        """Latest summary per run_id (append order preserved),
        optionally restricted to one config fingerprint."""
        latest: Dict[str, dict] = {}
        for e in self.entries("run"):
            latest[str(e.get("run_id") or e.get("source"))] = e
        out = list(latest.values())
        if fingerprint is not None:
            out = [e for e in out
                   if e.get("config_fingerprint") == fingerprint]
        return out

    def run(self, run_id: str) -> Optional[dict]:
        """Latest summary for one run_id (or a run-dir source path)."""
        for e in reversed(self.entries("run")):
            if e.get("run_id") == run_id or e.get("source") == run_id:
                return e
        return None

    def bench_for(self, run: dict) -> List[dict]:
        """Bench entries joined to a run summary: by run_id when both
        sides carry one, else by config fingerprint."""
        rid = run.get("run_id")
        fp = run.get("config_fingerprint")
        out = []
        for e in self.entries("bench"):
            if rid and e.get("run_id") == rid:
                out.append(e)
            elif fp and e.get("config_fingerprint") == fp \
                    and not e.get("run_id"):
                out.append(e)
        return out
