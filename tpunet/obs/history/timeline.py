"""Unified Perfetto / chrome-trace timeline from flight-recorder rings.

Every run already records its host story into the crash-durable event
ring (``tpunet/obs/flightrec/``): span begin/end pairs
(``_RecordedSpan`` — step, data-wait, eval, checkpoint, serve prefill/
decode phases), host-thread busy/idle transitions (``ThreadHandle``
state flips), serve request lifecycles (submit -> prefill ->
first_token -> finish), alerts, and epoch marks — each slot stamped
with ``time.time()`` and the recording thread id. This module turns
one or more run dirs' rings into a single chrome-trace JSON loadable
in ui.perfetto.dev (or chrome://tracing): the first view that shows
host threads, device phases, and serve requests on one clock.

Event mapping (chrome trace format):

- ``span``/``span_end``  -> ``B``/``E`` duration pairs on the
  recording thread's track (unmatched opens are closed at the ring's
  last timestamp so the output is always phase-paired);
- ``thread`` beats       -> one synthetic track per registered thread
  name, busy periods as complete ``X`` events;
- ``req`` lifecycle      -> one synthetic track per request:
  ``queue``/``prefill``/``decode`` ``X`` phases, finish reason in args;
- everything else        -> thread-scoped instant events (``i``).

Timestamps are microseconds relative to the earliest event across all
rings (wall clock — the rings of one host share it), emitted in
non-decreasing order. Multi-process runs contribute one trace process
per ring (``events.ring``, ``events.p1.ring``, ...); thread names come
from the run's persisted host-thread registry snapshot when present.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from tpunet.obs.flightrec import ring as _ring
from tpunet.obs.tracing import parse_crumb

#: Instant-event kinds worth a mark on the timeline (everything not
#: otherwise structured lands here too — unknown kinds degrade to
#: instants, never to silence).
_RING_GLOB = re.compile(r"^events(\.p(\d+))?\.ring$")


def discover_rings(run_dir: str) -> List[Tuple[int, str]]:
    """(process_index, ring path) for every ring under a run dir —
    accepts the run dir itself, its ``flightrec/`` subdir, or a direct
    ring file path."""
    if os.path.isfile(run_dir):
        return [(0, run_dir)]
    for base in (os.path.join(run_dir, "flightrec"), run_dir):
        if not os.path.isdir(base):
            continue
        out = []
        for name in sorted(os.listdir(base)):
            m = _RING_GLOB.match(name)
            if m:
                out.append((int(m.group(2) or 0),
                            os.path.join(base, name)))
        if out:
            return out
    return []


def _read_meta(ring_path: str, process_index: int) -> dict:
    base = os.path.dirname(ring_path)
    name = ("meta.json" if process_index == 0
            else f"meta.p{process_index}.json")
    try:
        with open(os.path.join(base, name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _read_thread_names(ring_path: str, process_index: int
                       ) -> Dict[int, str]:
    """ident -> registered name from the persisted registry snapshot
    (refreshed at epoch boundaries), when the run left one."""
    base = os.path.dirname(ring_path)
    name = ("threads.json" if process_index == 0
            else f"threads.p{process_index}.json")
    try:
        with open(os.path.join(base, name)) as f:
            rows = json.load(f)
    except (OSError, ValueError):
        return {}
    out: Dict[int, str] = {}
    for row in rows or []:
        if isinstance(row, dict) and row.get("ident") is not None:
            out[int(row["ident"]) & 0xFFFFFFFFFFFFFFFF] = str(
                row.get("name", ""))
    return out


class _ProcessTrack:
    """Trace events for one ring (= one process incarnation)."""

    # Synthetic tid ranges: real threads are remapped to small ids,
    # thread-beat tracks and request tracks live above them so the
    # groups sort together in the Perfetto UI.
    THREAD_TRACK_BASE = 1000
    REQ_TRACK_BASE = 2000

    def __init__(self, pid: int, label: str,
                 thread_names: Dict[int, str],
                 trace_join: "Optional[_TraceJoin]" = None):
        self.pid = pid
        self.label = label
        self.events: List[dict] = []
        self._tid_map: Dict[int, int] = {}
        self._tid_names = thread_names
        self._span_stack: Dict[int, List[Tuple[str, float]]] = {}
        self._busy: Dict[str, float] = {}      # thread name -> busy ts
        self._beat_tids: Dict[str, int] = {}
        self._reqs: Dict[str, dict] = {}
        self._trace_join = trace_join
        self._last_ts = 0.0

    # -- track bookkeeping ----------------------------------------------

    def _tid(self, raw_tid: int) -> int:
        if raw_tid not in self._tid_map:
            self._tid_map[raw_tid] = len(self._tid_map) + 1
        return self._tid_map[raw_tid]

    def _beat_tid(self, name: str) -> int:
        if name not in self._beat_tids:
            self._beat_tids[name] = (self.THREAD_TRACK_BASE
                                     + len(self._beat_tids))
        return self._beat_tids[name]

    def _emit(self, **ev) -> None:
        ev["pid"] = self.pid
        self.events.append(ev)

    # -- per-kind handling ----------------------------------------------

    def feed(self, event: dict, ts: float) -> None:
        self._last_ts = max(self._last_ts, ts)
        kind, msg = event["kind"], event["msg"]
        tid = self._tid(event["tid"])
        if kind == "span":
            self._span_stack.setdefault(tid, []).append((msg, ts))
            self._emit(name=msg, ph="B", ts=ts, tid=tid)
        elif kind == "span_end":
            stack = self._span_stack.get(tid) or []
            if stack:
                stack.pop()
                self._emit(name=msg, ph="E", ts=ts, tid=tid)
            # span_end without an open span (ring wrapped past the
            # begin): dropped — an unpaired E breaks B/E pairing.
        elif kind == "thread":
            state, _, name = msg.partition(" ")
            name = name or "?"
            started = self._busy.pop(name, None)
            if started is not None:
                self._emit(name="busy", ph="X", ts=started,
                           dur=max(0.0, ts - started),
                           tid=self._beat_tid(name))
            if state == "busy":
                self._busy[name] = ts
        elif kind == "req":
            parts = msg.split()
            if len(parts) < 2:
                return
            verb, rid = parts[0], parts[1]
            req = self._reqs.setdefault(rid, {})
            req.setdefault(verb, ts)
            if verb == "finish" and len(parts) > 2:
                req["reason"] = parts[2]
        elif kind == "trace":
            # Cross-process breadcrumb (tpunet/obs/tracing.py): fed to
            # the shared join — rings share the wall clock, so one
            # trace's crumbs from a router ring and N replica rings
            # line up causally — plus a local instant so the crumb is
            # visible in this process's own track too.
            crumb = parse_crumb(msg)
            if crumb is None:
                return
            if self._trace_join is not None:
                self._trace_join.feed(crumb, ts, self.label)
            self._emit(name=f"trace {crumb['verb']}", ph="i", ts=ts,
                       tid=tid, s="t",
                       args={"trace_id": crumb["trace_id"],
                             "hop": crumb["hop"]})
        else:
            self._emit(name=f"{kind}: {msg}" if msg else kind,
                       ph="i", ts=ts, tid=tid, s="t")

    # -- finalization ----------------------------------------------------

    def _close_open(self) -> None:
        ts = self._last_ts
        for tid, stack in self._span_stack.items():
            while stack:
                name, _ = stack.pop()
                self._emit(name=name, ph="E", ts=ts, tid=tid)
        for name, started in sorted(self._busy.items()):
            self._emit(name="busy", ph="X", ts=started,
                       dur=max(0.0, ts - started),
                       tid=self._beat_tid(name))
        self._busy = {}

    def _req_events(self) -> None:
        """One synthetic track per request: queue (submit ->
        prefill), prefill (-> first token), decode (-> finish). A
        request killed while queued collapses to one queue phase."""
        for i, rid in enumerate(sorted(self._reqs, key=_req_sort_key)):
            req = self._reqs[rid]
            tid = self.REQ_TRACK_BASE + i
            self._emit(name="thread_name", ph="M", ts=0.0, tid=tid,
                       args={"name": f"req {rid}"})
            end = req.get("finish", self._last_ts)
            # A request whose only prefill was a resume-prefill (a
            # cross-replica failover resume landing on this replica)
            # still gets a prefill phase — the re-prefill IS the
            # request's compute cost here.
            pf = req.get("prefill", req.get("resume_prefill"))
            marks = [("queue", req.get("submit"),
                      pf if pf is not None else end),
                     ("prefill", pf, req.get("first_token", end)),
                     ("decode", req.get("first_token"), end)]
            for name, t0, t1 in marks:
                if t0 is None:
                    continue
                args = {"req": rid}
                if name == "decode" and req.get("reason"):
                    args["finish_reason"] = req["reason"]
                self._emit(name=name, ph="X", ts=t0,
                           dur=max(0.0, min(t1, end) - t0), tid=tid,
                           args=args)
            # Non-phase lifecycle verbs (client_gone on a mid-stream
            # disconnect, resume on a failover landing) become
            # instants on the request's own track — a decode ending
            # "cancelled" with this mark next to it reads as the
            # client's fault, not the engine's.
            for verb, t in sorted(req.items()):
                if verb in ("submit", "prefill", "resume_prefill",
                            "first_token", "finish", "reason"):
                    continue
                self._emit(name=verb, ph="i", ts=t, tid=tid, s="t",
                           args={"req": rid})

    def finalize(self) -> List[dict]:
        self._close_open()
        self._req_events()
        meta = [{"name": "process_name", "ph": "M", "ts": 0.0,
                 "pid": self.pid, "tid": 0,
                 "args": {"name": self.label}}]
        for raw, small in self._tid_map.items():
            name = self._tid_names.get(raw) or f"thread {raw & 0xFFFF}"
            meta.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                         "pid": self.pid, "tid": small,
                         "args": {"name": name}})
        for name, tid in self._beat_tids.items():
            meta.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                         "pid": self.pid, "tid": tid,
                         "args": {"name": f"host-thread {name}"}})
        return meta + self.events


def _req_sort_key(rid: str):
    return (0, int(rid)) if rid.isdigit() else (1, rid)


class _TraceJoin:
    """Cross-process request join: ``trace``-kind crumbs from EVERY
    ring (a router dir + N replica dirs), grouped by trace_id, render
    as one synthetic "trace" process — per trace, a router relay row
    plus one row per hop, so a failed-over request reads as a single
    causal track: hop-1 queue/prefill/decode cut at the failover seam,
    hop-2 resume-prefill/decode continuing it. A first hop whose
    replica was SIGKILLed never wrote a finish crumb; its decode phase
    is force-closed at the ROUTER's seam timestamp (the orphaned-
    lifecycle fix the per-process view can't make — only the router
    knows when the stream actually died)."""

    PID = 1                 # real rings start at pid 100
    TRACK_STRIDE = 8        # rows per trace: router + up to 7 hops

    def __init__(self):
        # trace_id -> [(ts, crumb, source label)]
        self._traces: Dict[str, List[Tuple[float, dict, str]]] = {}

    def feed(self, crumb: dict, ts: float, source: str) -> None:
        self._traces.setdefault(crumb["trace_id"], []).append(
            (ts, crumb, source))

    def _hop_rows(self, trace_id: str, base: int, evs) -> List[dict]:
        out: List[dict] = []
        last_ts = max(ts for ts, _, _ in evs)
        by_hop: Dict[int, List[Tuple[float, dict, str]]] = {}
        for ts, c, src in evs:
            by_hop.setdefault(min(c["hop"],
                                  self.TRACK_STRIDE - 1), []).append(
                (ts, c, src))
        short = trace_id[:8]
        for hop in sorted(by_hop):
            tid = base + hop
            row = "router" if hop == 0 else f"hop {hop}"
            out.append({"name": "thread_name", "ph": "M", "ts": 0.0,
                        "pid": self.PID, "tid": tid,
                        "args": {"name": f"trace {short} {row}"}})
            first: Dict[str, float] = {}
            meta: Dict[str, str] = {}
            source = ""
            for ts, c, src in by_hop[hop]:
                first.setdefault(c["verb"], ts)
                if c["verb"] == "finish" and "reason" in c:
                    meta["finish_reason"] = c["reason"]
                if c["verb"] == "seam" and "tokens" in c:
                    meta["tokens_relayed"] = c["tokens"]
                if c["verb"] == "open" and "rep" in c:
                    # The ROUTER's record of which replica served this
                    # hop — survives even when that replica's ring is
                    # gone (a SIGKILLed victim's respawn truncates it).
                    meta["replica"] = c["rep"]
                if c["verb"] not in ("recv", "open", "seam",
                                     "finish"):
                    source = src      # the replica that computed
            args = {"trace_id": trace_id, **meta}
            if source:
                args["process"] = source
            if hop == 0:
                t0 = first.get("recv", by_hop[hop][0][0])
                t1 = first.get("finish", last_ts)
                out.append({"name": "relay", "ph": "X", "ts": t0,
                            "dur": max(0.0, t1 - t0),
                            "pid": self.PID, "tid": tid,
                            "args": args})
                continue
            end = first.get("finish")
            if end is None and "seam" in first:
                end = first["seam"]
                args["force_closed"] = "failover_seam"
            if end is None:
                end = last_ts
            pf = first.get("prefill", first.get("resume_prefill"))
            marks = [("queue", first.get("submit"),
                      pf if pf is not None else end),
                     ("resume_prefill" if "resume_prefill" in first
                      else "prefill", pf,
                      first.get("first_token", end)),
                     ("decode", first.get("first_token"), end)]
            for name, t0, t1 in marks:
                if t0 is None:
                    continue
                out.append({"name": name, "ph": "X", "ts": t0,
                            "dur": max(0.0, min(t1, end) - t0),
                            "pid": self.PID, "tid": tid,
                            "args": args})
            for verb in ("open", "seam", "preempt"):
                if verb in first:
                    out.append({"name": verb, "ph": "i", "s": "t",
                                "ts": first[verb], "pid": self.PID,
                                "tid": tid, "args": args})
        return out

    def finalize(self) -> List[dict]:
        if not self._traces:
            return []
        out = [{"name": "process_name", "ph": "M", "ts": 0.0,
                "pid": self.PID, "tid": 0,
                "args": {"name": "trace (cross-process join)"}}]
        for idx, trace_id in enumerate(sorted(self._traces)):
            evs = sorted(self._traces[trace_id],
                         key=lambda e: e[0])
            out.extend(self._hop_rows(trace_id,
                                      idx * self.TRACK_STRIDE, evs))
        return out


def build_timeline(run_dirs: Sequence[str]) -> dict:
    """One chrome-trace dict from any number of run dirs. Raises
    FileNotFoundError when none of them contains a flight-recorder
    ring (the timeline would be silently empty otherwise)."""
    rings: List[Tuple[str, int, str]] = []
    for d in run_dirs:
        for pidx, path in discover_rings(d):
            rings.append((d, pidx, path))
    if not rings:
        raise FileNotFoundError(
            "no flightrec events.ring under any of: "
            + ", ".join(run_dirs) + " (runs record one by default; "
            "--no-flightrec runs leave no timeline)")

    parsed = []
    t_min: Optional[float] = None
    for run_dir, pidx, path in rings:
        events = _ring.read_ring_file(path)
        for e in events:
            t_min = e["t"] if t_min is None else min(t_min, e["t"])
        parsed.append((run_dir, pidx, path, events))
    t_min = t_min or 0.0

    out_events: List[dict] = []
    join = _TraceJoin()
    for i, (run_dir, pidx, path, events) in enumerate(parsed):
        meta = _read_meta(path, pidx)
        label = os.path.basename(os.path.normpath(run_dir)) or run_dir
        if meta.get("run_id"):
            label = f"{label} ({meta['run_id']})"
        if pidx:
            label = f"{label} p{pidx}"
        track = _ProcessTrack(
            pid=(i + 1) * 100 + pidx, label=label,
            thread_names=_read_thread_names(path, pidx),
            trace_join=join)
        for e in events:
            track.feed(e, round((e["t"] - t_min) * 1e6, 3))
        out_events.extend(track.finalize())
    out_events.extend(join.finalize())

    # Metadata first, then everything else in timestamp order —
    # non-decreasing ts is part of the exported contract.
    metas = [e for e in out_events if e["ph"] == "M"]
    rest = sorted((e for e in out_events if e["ph"] != "M"),
                  key=lambda e: e["ts"])
    return {"traceEvents": metas + rest, "displayTimeUnit": "ms",
            "otherData": {"source": "tpunet flightrec",
                          "clock": "time.time (host wall clock)"}}


def write_trace(run_dirs: Sequence[str], out_path: str) -> dict:
    """Build and write ``trace.json`` (load at ui.perfetto.dev).
    Returns the trace dict."""
    trace = build_timeline(run_dirs)
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, out_path)
    return trace
