"""Per-op HBM byte attribution from compiled (post-fusion) HLO text.

XLA's ``cost_analysis()["bytes accessed"]`` is one opaque number; this
module decomposes it so a bytes/image regression names the op category
that moved. The model is the same one XLA's cost analysis uses: every
top-level (non-fused) instruction in the optimized module reads its
operands from HBM and writes its output to HBM — instructions INSIDE a
fusion stay on-chip and cost nothing. Parsing the post-optimization
text (``compiled.as_text()``) means the counts reflect what the
compiler actually scheduled, remat and epilogue fusion included; the
per-device SPMD module is what prints, so counts are per chip, like
``cost_analysis``.

Categories (the byte-amplification suspects of the HBM-bound
MobileNetV2 step):

- ``conv_fwd`` / ``conv_bwd``  — convolutions (and conv-rooted
  fusions); bwd = ops under a ``transpose(...)`` autodiff scope.
- ``matmul``     — dot/dot-rooted fusions (the classifier head).
- ``bn``         — ops in a ``/bn/`` module scope: batch-stat
  reductions + the normalize/scale/shift/clamp epilogue regions.
- ``optimizer``  — the ``tpunet_optimizer`` / ``tpunet_ema`` named
  scopes (Adam moments, EMA).
- ``augment``    — the ``tpunet_augment`` named scope: the on-device
  input pipeline (resize/crop/rotate/jitter), a measured ~20%% of the
  round-4 step — kept distinct from model fwd work.
- ``copy_pad``   — layout traffic: copies, pads, transposes, slices,
  concats, converts at top level (or fusions rooted there).
- ``reduce``     — non-BN reductions (pool, loss, metrics).
- ``collective`` — cross-chip all-reduce/gather/permute traffic.
- ``elementwise``— everything else (augment chains, losses, adds).

``phase_of`` / ``is_backward`` classify framework op names by training
phase; scripts/obs_report.py reuses them for device-TIME attribution
from profiler traces, so the bytes and time tables split the step the
same way.

Known approximations (documented, stable across runs, so the >5%%
regression gate is still meaningful): ``while``/``conditional`` bodies
are counted once (the bench train step is straight-line at
grad_accum=1); CPU-backend ``call`` thunks are traversed into.
"""

from __future__ import annotations

import re
from typing import Dict, Iterator, List, Optional, Tuple

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "fp8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# Produce/consume no HBM traffic of their own (aliases, metadata ops).
_SKIP_OPS = {
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "domain", "opt-barrier",
}

# Traverse instead of count: their cost is the instructions they run.
_CALL_OPS = {"call", "while", "conditional", "async-start"}

_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]{0,15})\[([0-9,]*)\]")
_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?%?[\w.\-]+\s+=\s+(.*)$")
_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s+\(.*\{\s*$")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLED_RE = re.compile(
    r"(?:to_apply|body|condition|true_computation|false_computation)"
    r"=%?([\w.\-]+)|branch_computations=\{([^}]*)\}")

_COPY_ROOTS = {
    "copy", "pad", "transpose", "slice", "dynamic-slice", "dynamic_slice",
    "dynamic-update-slice", "dynamic_update_slice", "concatenate",
    "reshape", "convert", "gather", "scatter", "squeeze", "broadcast",
    "broadcast_in_dim", "rev", "copy-start",
}
_COLLECTIVES = {
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "psum", "all_gather",
    "all_to_all", "ppermute",
}

CATEGORIES = ("conv_fwd", "conv_bwd", "matmul", "bn", "augment",
              "optimizer", "copy_pad", "reduce", "collective",
              "elementwise")

# ---------------------------------------------------------------------------
# Named-scope marker table — THE contract between the kernels in
# tpunet/ops/ and byte/phase attribution. Each custom_vjp'd Pallas
# kernel pair lowers to custom calls (no convolution/dot opcode, no
# ``transpose(`` marker on the custom_vjp backward), so the ONLY thing
# keeping its bytes in the right bucket and its backward in the bwd
# phase is the ``tpunet_<kernel>_{fwd,bwd}`` named scope around the
# kernel body. tpucheck rule R2 (tpunet/analysis/rules/scopes.py)
# imports this table and fails the tree when a kernel in tpunet/ops/
# is missing its scope or uses one this table doesn't know — so the
# attribution can't silently rot (the PR-6 failure class).
# ---------------------------------------------------------------------------

# Kernel scope prefix -> (forward category, backward category). The
# scope in the code must be exactly ``<prefix>_fwd`` / ``<prefix>_bwd``.
KERNEL_SCOPES: Dict[str, Tuple[str, str]] = {
    "tpunet_fused_ir": ("conv_fwd", "conv_bwd"),
    "tpunet_depthwise": ("conv_fwd", "conv_bwd"),
    # Flash attention is MXU matmul work; without the marker its
    # custom calls land in ``elementwise`` and its custom_vjp backward
    # (no ``transpose(`` scope) would misattribute to the fwd phase.
    "tpunet_flash": ("matmul", "matmul"),
}

# Scopes that mark a training phase directly (train/steps.py et al.).
PHASE_MARKERS: Tuple[Tuple[str, str], ...] = (
    ("tpunet_optimizer", "optimizer"),
    ("tpunet_ema", "ema"),
    ("tpunet_eval_forward", "eval"),
    ("tpunet_augment", "augment"),
)

_BWD_MARKERS = tuple(f"{p}_bwd" for p in KERNEL_SCOPES)


def _shape_bytes(type_str: str) -> int:
    """Total bytes of an HLO type string (tuples sum their elements)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        nbytes = _DTYPE_BYTES.get(dtype)
        if nbytes is None:
            continue  # token[] / opaque[] / unknown
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * nbytes
    return total


def is_backward(op_name: str) -> bool:
    """True when the framework op name sits under an autodiff
    transpose scope (cotangent computation, remat replays included) or
    an explicit backward marker (the fused-IR / depthwise custom_vjp
    backwards, whose ops a custom-vjp rule does not nest under
    ``transpose(``)."""
    name = op_name or ""
    return ("transpose(" in name
            or any(m in name for m in _BWD_MARKERS))


def phase_of(op_name: str) -> str:
    """Training phase of a framework op name: fwd / bwd / optimizer /
    ema / eval / other — the split scripts/obs_report.py reports
    device time under."""
    name = op_name or ""
    for marker, phase in PHASE_MARKERS:
        if marker in name:
            return phase
    if "tpunet_fwd_bwd" in name or "jvp(" in name:
        return "bwd" if is_backward(name) else "fwd"
    return "other"


def _leaf_primitive(op_name: str) -> str:
    """Last path element of a framework op name ('.../bn/reduce_sum'
    -> 'reduce_sum')."""
    return (op_name or "").rsplit("/", 1)[-1]


def categorize(opcode: str, op_name: str) -> str:
    name = op_name or ""
    phase = phase_of(name)
    if phase in ("optimizer", "ema"):
        return "optimizer"
    if "tpunet_augment" in name:
        # Before the conv/dot checks: the rotation's shear matmul
        # banks are dots, but they are input-pipeline work.
        return "augment"
    for prefix, (fwd_cat, bwd_cat) in KERNEL_SCOPES.items():
        # The custom_vjp'd Pallas kernels lower to custom calls, not
        # convolution/dot opcodes; their explicit fwd/bwd scopes keep
        # them in the buckets the budget gates. (The tpunet_ prefix
        # keeps the match off the model's '/depthwise/' module path,
        # whose XLA convs the opcode branch below already handles.)
        if prefix in name:
            return bwd_cat if is_backward(name) else fwd_cat
    leaf = _leaf_primitive(name)
    if opcode == "convolution" or "conv_general_dilated" in leaf:
        return "conv_bwd" if is_backward(name) else "conv_fwd"
    if opcode == "dot" or leaf.startswith("dot_general"):
        return "matmul"
    # Multi-chip TPU modules print collectives as async pairs
    # (all-reduce-start / all-reduce-done); the -start carries the
    # traffic (the -done is skipped in the walk as a completion
    # marker).
    base_op = opcode[:-6] if opcode.endswith("-start") else opcode
    if base_op in _COLLECTIVES or leaf in _COLLECTIVES:
        return "collective"
    if "/bn/" in name:
        return "bn"
    if opcode in _COPY_ROOTS:
        return "copy_pad"
    if opcode in ("reduce", "reduce-window") or leaf.startswith("reduce"):
        return "reduce"
    return "elementwise"


def _computations(hlo_text: str) -> Tuple[Optional[str], Dict[str, List[str]]]:
    """Split module text into {computation name: [instruction lines]};
    returns (entry_name, comps)."""
    comps: Dict[str, List[str]] = {}
    entry = None
    current: Optional[List[str]] = None
    for line in hlo_text.splitlines():
        m = _COMP_RE.match(line)
        if m:
            name = m.group(2)
            if m.group(1):
                entry = name
            current = comps.setdefault(name, [])
            continue
        if line.startswith("}"):
            current = None
            continue
        if current is not None and _INSTR_RE.match(line):
            current.append(line)
    return entry, comps


def _parse_instr(line: str) -> Optional[Tuple[str, int, int, str]]:
    """-> (opcode, out_bytes, operand_bytes, op_name) or None."""
    m = _INSTR_RE.match(line)
    if not m:
        return None
    rest = m.group(1)
    # Output type: either a tuple "(...)" or a single token.
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    break
        type_str, rest = rest[:i + 1], rest[i + 1:]
    else:
        sp = rest.find(" ")
        if sp < 0:
            return None
        type_str, rest = rest[:sp], rest[sp:]
    rest = rest.lstrip()
    om = re.match(r"([\w\-]+)\(", rest)
    if not om:
        return None
    opcode = om.group(1)
    # Operand segment: the matching paren after the opcode. metadata/
    # attrs follow it, so quoted strings never reach the shape regex.
    depth, start = 0, om.end() - 1
    end = len(rest)
    for i in range(start, len(rest)):
        if rest[i] == "(":
            depth += 1
        elif rest[i] == ")":
            depth -= 1
            if depth == 0:
                end = i
                break
    args = rest[start + 1:end]
    op_name = ""
    nm = _OPNAME_RE.search(rest[end:])
    if nm:
        op_name = nm.group(1)
    return (opcode, _shape_bytes(type_str),
            _shape_bytes(args) if opcode != "constant" else 0, op_name)


def instruction_bytes(hlo_text: str) -> Iterator[Tuple[str, str, int, str]]:
    """Yield (opcode, category, bytes, op_name) per counted top-level
    instruction, walking ENTRY and any called (non-fused) bodies."""
    entry, comps = _computations(hlo_text)
    if entry is None:
        return
    seen = set()

    def walk(name: str) -> Iterator[Tuple[str, str, int, str]]:
        if name in seen or name not in comps:
            return
        seen.add(name)
        for line in comps[name]:
            parsed = _parse_instr(line)
            if parsed is None:
                continue
            opcode, out_b, in_b, op_name = parsed
            if opcode in _SKIP_OPS:
                continue
            if opcode.endswith("-done"):
                # Async completion markers (all-reduce-done,
                # copy-done, async-done): the traffic was counted at
                # the matching -start; counting both halves would
                # double-charge every collective/async copy.
                continue
            if opcode in _CALL_OPS:
                for target in _called_comps(line):
                    yield from walk(target)
                continue
            yield opcode, categorize(opcode, op_name), out_b + in_b, op_name

    yield from walk(entry)


def _called_comps(line: str) -> List[str]:
    out = []
    for single, many in _CALLED_RE.findall(line):
        if single:
            out.append(single)
        if many:
            out.extend(t.strip().lstrip("%") for t in many.split(","))
    return out


def breakdown(hlo_text: str) -> Dict[str, float]:
    """{category: total bytes} over the module, plus 'total'."""
    by_cat = {c: 0.0 for c in CATEGORIES}
    total = 0.0
    for _opcode, cat, nbytes, _name in instruction_bytes(hlo_text):
        by_cat[cat] = by_cat.get(cat, 0.0) + nbytes
        total += nbytes
    out = {k: v for k, v in by_cat.items() if v}
    out["total"] = total
    return out


def per_image_breakdown(hlo_text: str, images: int) -> Dict[str, int]:
    """Bytes per image by category ('total' included), from the
    per-device module text and the PER-DEVICE image count of one
    execution."""
    return {k: int(round(v / max(1, images)))
            for k, v in breakdown(hlo_text).items()}


def emit_gauges(registry, per_image: Dict[str, int]) -> None:
    """Mirror a per-image breakdown into the ``hbm_bytes_per_image_*``
    gauge family (snapshot keys usable in --obs-rule predicates)."""
    for cat, val in per_image.items():
        registry.gauge(f"hbm_bytes_per_image_{cat}").set(float(val))
