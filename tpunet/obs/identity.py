"""Run identity: the join keys that make record streams mergeable.

A fleet dashboard aggregating N runs' streams (tpunet/obs/agg/) needs
to know which records belong together; nothing in a bare record says
so. Every record emitted through ``Registry.emit`` is therefore
stamped at the source with

- ``run_id``        — one logical run (stable across a preemption
  restore: ``--resume`` reads the id the original run persisted next
  to its checkpoints, so the restored stream continues the same run
  instead of appearing as a new replica);
- ``process_index`` — which process of the run (0 on single-host);
- ``host``          — the machine, for the human reading the page.

The id is persisted as ``<checkpoint_dir>/run_id`` by the coordinator
(the only process whose records leave the host — jsonl and exporters
are both coordinator-only). A fresh run into a reused directory
regenerates the id, mirroring MetricsLogger's truncate-on-fresh-run
discipline: one file, one run, one id.
"""

from __future__ import annotations

import os
import socket
import uuid

RUN_ID_FILE = "run_id"


def ensure_run_id(directory: str, resume: bool = False,
                  *, persist: bool = True) -> str:
    """Return the run's id, creating or reusing ``<directory>/run_id``.

    ``resume=True`` reuses a persisted id when one exists (the
    preemption-restore path); otherwise a fresh id is generated and —
    when ``persist`` (coordinator) — written for future restores.
    Non-coordinator processes pass ``persist=False``: on a resume they
    read the coordinator's persisted file like everyone else; on a
    fresh run they get an ephemeral id rather than racing the
    coordinator's rewrite of a possibly stale file — acceptable
    because only coordinator records ever leave the host (jsonl and
    exporters are both coordinator-only).
    """
    path = os.path.join(directory, RUN_ID_FILE) if directory else ""
    if resume and path and os.path.isfile(path):
        with open(path) as f:
            run_id = f.read().strip()
        if run_id:
            return run_id
    run_id = uuid.uuid4().hex[:12]
    if persist and path:
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(run_id + "\n")
        os.replace(tmp, path)
    return run_id


def run_identity(*, run_id: str = "", directory: str = "",
                 resume: bool = False, process_index: int = 0,
                 persist: bool = True) -> dict:
    """The identity stamp for ``Registry.set_identity``: an explicit
    ``run_id`` (config/CLI) wins; otherwise one is ensured under
    ``directory`` (see ``ensure_run_id``)."""
    rid = run_id or ensure_run_id(directory, resume, persist=persist)
    return {
        "run_id": rid,
        "process_index": int(process_index),
        "host": socket.gethostname(),
    }
