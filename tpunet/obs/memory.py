"""Device memory gauges and the multi-host heartbeat.

Both are *epoch-boundary* samplers: ``memory_stats()`` is a host-side
runtime query (no device sync) but still costs a Python round-trip per
device, and the heartbeat is a real cross-host collective — neither
belongs on the per-step path.
"""

from __future__ import annotations

from typing import Dict, List

import jax

# memory_stats() keys worth persisting (PJRT exposes many more; these
# are the capacity-planning ones and are stable across TPU runtimes).
_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_records() -> List[Dict]:
    """Per-local-device memory samples. Backends without allocator
    stats (CPU's PJRT returns None) yield an entry with just the
    device id, so the record schema is shape-stable across backends."""
    out = []
    for d in jax.local_devices():
        rec: Dict = {"device": d.id}
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if stats:
            for k in _MEM_KEYS:
                if k in stats:
                    rec[k] = int(stats[k])
        out.append(rec)
    return out


def sample_memory_gauges(registry) -> List[Dict]:
    """Set ``mem_bytes_in_use`` / ``mem_peak_bytes_in_use`` gauges
    (max over local devices — the OOM-relevant figure) and return the
    per-device records for the epoch summary."""
    records = device_memory_records()
    in_use = [r["bytes_in_use"] for r in records if "bytes_in_use" in r]
    peak = [r["peak_bytes_in_use"] for r in records
            if "peak_bytes_in_use" in r]
    if in_use:
        registry.gauge("mem_bytes_in_use").set(max(in_use))
    if peak:
        registry.gauge("mem_peak_bytes_in_use").set(max(peak))
    return records


def heartbeat(registry, elapsed_s: float) -> int:
    """Coordinator-side liveness gauge: every process contributes a
    flag to an allgather (so a wedged host surfaces as a hang HERE, at
    a labeled epoch boundary, rather than deep inside a step's
    collective); the coordinator records how many answered and when.
    Single-process runs skip the collective."""
    n = jax.process_count()
    if n > 1:
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            jnp.ones((), jnp.int32))
        n = int(np.asarray(flags).sum())
    registry.gauge("live_processes").set(n)
    registry.gauge("heartbeat_s").set(elapsed_s)
    return n
