"""Device memory gauges and the multi-host heartbeat.

Both are *epoch-boundary* samplers: ``memory_stats()`` is a host-side
runtime query (no device sync) but still costs a Python round-trip per
device, and the heartbeat is a real cross-host collective — neither
belongs on the per-step path.
"""

from __future__ import annotations

from typing import Dict, List

import jax

# memory_stats() keys worth persisting (PJRT exposes many more; these
# are the capacity-planning ones and are stable across TPU runtimes).
_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_records() -> List[Dict]:
    """Per-local-device memory samples. Backends without allocator
    stats (CPU's PJRT returns None) yield an entry with just the
    device id, so the record schema is shape-stable across backends."""
    out = []
    for d in jax.local_devices():
        rec: Dict = {"device": d.id}
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            pass
        if stats:
            for k in _MEM_KEYS:
                if k in stats:
                    rec[k] = int(stats[k])
        out.append(rec)
    return out


def sample_memory_gauges(registry) -> List[Dict]:
    """Set ``mem_bytes_in_use`` / ``mem_peak_bytes_in_use`` gauges
    (max over local devices — the OOM-relevant figure) and return the
    per-device records for the epoch summary."""
    records = device_memory_records()
    in_use = [r["bytes_in_use"] for r in records if "bytes_in_use" in r]
    peak = [r["peak_bytes_in_use"] for r in records
            if "peak_bytes_in_use" in r]
    if in_use:
        registry.gauge("mem_bytes_in_use").set(max(in_use))
    if peak:
        registry.gauge("mem_peak_bytes_in_use").set(max(peak))
    return records


_HEARTBEAT_SEQ = 0


def heartbeat(registry, elapsed_s: float) -> int:
    """Coordinator-side liveness gauge: every process checks in at the
    epoch boundary; the coordinator records how many answered and
    when. Routed through the coordination-service KV store
    (tpunet/parallel/dist.kv_live_processes) because the epoch
    boundary is exactly where the async checkpoint worker is running
    orbax's cross-host barriers — an allgather here from the main
    thread interleaves with them and aborts the transport (same bug
    class as the stop agreement; see Trainer._agree_stop). Allgather
    remains the fallback when no coordination service exists; the
    sequence counter advances identically on every process (one call
    per epoch boundary each)."""
    global _HEARTBEAT_SEQ
    n = jax.process_count()
    if n > 1:
        from tpunet.parallel.dist import kv_live_processes
        _HEARTBEAT_SEQ += 1
        live = kv_live_processes(f"epoch/{_HEARTBEAT_SEQ}")
        if live is None:
            import jax.numpy as jnp
            import numpy as np
            from jax.experimental import multihost_utils
            flags = multihost_utils.process_allgather(
                jnp.ones((), jnp.int32))
            live = int(np.asarray(flags).sum())
        n = live
    registry.gauge("live_processes").set(n)
    registry.gauge("heartbeat_s").set(elapsed_s)
    return n
