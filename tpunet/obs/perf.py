"""Throughput and efficiency accounting: analytic model FLOPs, device
peak-FLOPs lookup, and the MFU estimate.

The FLOPs numbers are *analytic* (closed-form from the config, the
standard 6ND-style accounting), not measured from the compiled HLO —
they exist to turn examples/s into a hardware-utilization fraction, so
~percent-level fidelity is the bar. A family we cannot model returns
0.0 and MFU is simply omitted from the record rather than guessed.
"""

from __future__ import annotations

from typing import Optional

import jax

# Dense peak TFLOP/s per chip by device_kind substring (bf16 unless
# noted). Matched case-insensitively in ORDER, so more specific strings
# come first. Unknown hardware (CPU included) -> None -> no MFU claim.
_PEAK_TFLOPS = (
    ("v5 lite", 197.0),       # v5e: 197 bf16 TFLOP/s
    ("v5litepod", 197.0),
    ("v5p", 459.0),
    ("v6 lite", 918.0),       # trillium
    ("v6e", 918.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def device_peak_flops(device=None) -> Optional[float]:
    """Peak dense FLOP/s of one device, or None when unknown."""
    if device is None:
        device = jax.local_devices()[0]
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, tflops in _PEAK_TFLOPS:
        if key in kind:
            return tflops * 1e12
    return None


def _transformer_train_flops_per_token(n_params: float, depth: int,
                                       hidden: int, seq_len: int) -> float:
    """6*N per token (fwd 2N + bwd 4N) plus the attention-score term
    the parameter count misses: per token per layer, QK^T and AV are
    each 2*T*H MACs -> 12*L*T*H FLOPs for fwd+bwd (causal masking
    halves the realized work; we charge the dense figure, matching the
    convention MFU tables use)."""
    return 6.0 * n_params + 12.0 * depth * seq_len * hidden


def train_flops_per_unit(model_cfg, data_cfg,
                         n_params: Optional[int] = None) -> float:
    """Analytic training FLOPs per *metric unit* — per next-token
    prediction for the LM family (matching the trainer's token-count
    metric), per image for the vision families. 0.0 == unknown."""
    name = model_cfg.name
    if name in ("lm", "lm_pp"):
        if n_params is None:
            return 0.0
        # Embedding rows do no FLOPs; the tied readout projection does
        # (2*H*V fwd per token), and n_params already includes the
        # embedding once — the 6N convention absorbs this.
        return _transformer_train_flops_per_token(
            float(n_params), model_cfg.vit_depth, model_cfg.vit_hidden,
            data_cfg.seq_len)
    if name.startswith("vit"):
        if n_params is None:
            return 0.0
        tokens = (data_cfg.image_size // max(1, model_cfg.vit_patch)) ** 2 + 1
        return tokens * _transformer_train_flops_per_token(
            float(n_params), model_cfg.vit_depth, model_cfg.vit_hidden,
            tokens)
    if name == "mobilenet_v2":
        # Conv FLOPs are not proportional to params: anchor on the
        # published 0.30 GMACs inference cost at width 1.0 / 224px and
        # scale by resolution (activations are O(HW)) and width^2
        # (channel pairs). Training ~= 3x inference (fwd + 2x bwd).
        gmacs_224 = 0.30e9
        scale = (data_cfg.image_size / 224.0) ** 2 * model_cfg.width_mult ** 2
        return 3.0 * 2.0 * gmacs_224 * scale
    return 0.0


def mfu(units_per_sec: float, flops_per_unit: float,
        n_devices: Optional[int] = None) -> Optional[float]:
    """Model FLOPs utilization in [0, 1], or None when either the model
    FLOPs or the hardware peak is unknown (never a fabricated number)."""
    if not flops_per_unit or units_per_sec <= 0:
        return None
    peak = device_peak_flops()
    if peak is None:
        return None
    if n_devices is None:
        n_devices = jax.device_count()
    return (units_per_sec * flops_per_unit) / (peak * n_devices)
