"""Metrics registry: counters, gauges, histograms with pluggable sinks.

The instruments are deliberately host-side-only (plain Python floats):
observing a value never touches a device or forces a sync — the caller
decides when device values become host floats. Sinks receive finished
*records* (flat JSON-able dicts tagged with a ``kind``), not raw
observations, so the per-step hot path never formats or writes
anything; records are built at window edges (epoch boundaries, opt-in
per-step sampling).
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional


def percentile_of_sorted(xs: List[float], q: float) -> float:
    """Linear-interpolated q-th percentile (q in [0, 100]) of an
    already-sorted non-empty list. THE percentile definition for the
    whole obs subsystem — Histogram summaries and the
    summary/dashboard/report pipeline all call this one function, so
    live views can never drift from the trainer's emitted records."""
    if len(xs) == 1:
        return xs[0]
    rank = (q / 100.0) * (len(xs) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(xs) - 1)
    frac = rank - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


class Counter:
    """Monotonically increasing sum (e.g. checkpoint saves, stall
    seconds). ``inc`` is thread-safe: the serving path increments from
    HTTP handler threads concurrently with the engine thread, and an
    unlocked float read-modify-write can lose updates."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    """Last-write-wins sample (e.g. device bytes in use). The single
    float store in ``set`` is atomic under the GIL today; the lock
    exists to pin the instrument-mutation discipline (Counter and
    Histogram hold one) so a future compound setter — min/max
    tracking, delta-from-previous — cannot silently reintroduce the
    serve-path race between HTTP handler threads and the engine."""

    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)


class Histogram:
    """Windowed distribution with bounded memory.

    Observations accumulate in a list until ``reset()`` (one window ==
    one epoch in the trainer); percentiles sort a copy on demand, so
    ``observe`` is a single append — cheap enough for the per-step
    path. Up to ``max_samples`` observations the window is stored
    exactly (exact percentiles); beyond it, reservoir sampling
    (Vitter's Algorithm R, seeded so runs are reproducible) keeps a
    uniform sample of the window and percentiles become approximate —
    ``count`` and ``total`` stay exact either way. The default bound
    holds a long epoch of float laps in ~0.5 MB.

    ``observe`` (and every reader) holds a lock: the serving path
    observes ``serve_*`` latency histograms from HTTP handler threads
    concurrently with the engine thread, and the unlocked
    count/total/reservoir updates lose observations under that race —
    same discipline as ``Counter.inc``, one uncontended acquire on the
    trainer's single-threaded hot path.
    """

    __slots__ = ("values", "max_samples", "_count", "_total", "_rng",
                 "_lock")

    DEFAULT_MAX_SAMPLES = 65536
    # Bound on the per-record exported sample (``export_sample``):
    # large enough that rank-space quantile error stays small (see
    # docs/metrics_schema.md), small enough that an obs_epoch record
    # stays a few KB.
    EXPORT_SAMPLE_MAX = 256

    def __init__(self, max_samples: int = DEFAULT_MAX_SAMPLES):
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1, got {max_samples}")
        self.values: List[float] = []
        self.max_samples = max_samples
        self._count = 0
        self._total = 0.0
        self._rng = random.Random(0x0B5)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self._count += 1
            self._total += value
            if len(self.values) < self.max_samples:
                self.values.append(value)
                return
            # Reservoir (Algorithm R): keep each of the n seen so far
            # with probability max_samples/n — percentiles degrade to a
            # uniform sample of the window instead of the list growing
            # unboundedly.
            j = self._rng.randrange(self._count)
            if j < self.max_samples:
                self.values[j] = value

    def __len__(self) -> int:
        return self._count

    @property
    def saturated(self) -> bool:
        """True once the window overflowed the exact bound (percentiles
        are reservoir approximations from here on)."""
        return self._count > self.max_samples

    @property
    def total(self) -> float:
        return self._total

    _interp = staticmethod(percentile_of_sorted)

    def percentile(self, q: float) -> Optional[float]:
        """Linear-interpolated q-th percentile (q in [0, 100]); None on
        an empty window."""
        with self._lock:
            xs = sorted(self.values)
        if not xs:
            return None
        return self._interp(xs, q)

    def summary(self) -> Dict[str, float]:
        """{count, mean, p50, p90, p99} of the current window (empty
        dict on an empty window); one sort serves all three
        percentiles. ``count``/``mean`` are exact even when the window
        saturated the reservoir (percentiles are then approximate, and
        the summary says so with ``approx: 1``)."""
        with self._lock:
            xs = sorted(self.values)
            count, total = self._count, self._total
        if not xs:
            return {}
        out = {
            "count": count,
            "mean": total / count,
            "p50": self._interp(xs, 50),
            "p90": self._interp(xs, 90),
            "p99": self._interp(xs, 99),
        }
        if count > self.max_samples:
            out["approx"] = 1
        return out

    def export_sample(self, max_n: int = EXPORT_SAMPLE_MAX) -> List[float]:
        """The window's bounded sample, sorted, for cross-stream
        percentile merging (tpunet/obs/agg/merge.py). Up to ``max_n``
        points the stored sample is returned whole; beyond that it is
        compressed to ``max_n`` rank-strided points — the values at
        ranks (i + 0.5)/max_n — which preserves any quantile of the
        stored sample to within 1/(2*max_n) in rank. Combined with the
        reservoir's own DKW bound once saturated, a merged quantile's
        total rank error is documented in docs/metrics_schema.md."""
        with self._lock:
            xs = sorted(self.values)
        if len(xs) <= max_n:
            return xs
        return [xs[int((i + 0.5) * len(xs) / max_n)] for i in range(max_n)]

    def reset(self) -> None:
        with self._lock:
            self.values = []
            self._count = 0
            self._total = 0.0


class MemorySink:
    """In-memory sink for tests: records land in ``self.records``."""

    def __init__(self):
        self.records: List[dict] = []

    def write(self, record: dict) -> None:
        self.records.append(record)

    def by_kind(self, kind: str) -> List[dict]:
        return [r for r in self.records if r.get("kind") == kind]


class JsonlSink:
    """Sink adapter over ``MetricsLogger`` — obs records share the
    run's ``metrics.jsonl`` (one append-mode file, coordinator-only
    writes; MetricsLogger already enforces both)."""

    def __init__(self, logger):
        self._logger = logger

    def write(self, record: dict) -> None:
        self._logger.log(record)


class Registry:
    """Named instruments + sinks. ``counter``/``gauge``/``histogram``
    are get-or-create, so call sites never coordinate registration.

    Creation and ``snapshot()`` hold a lock: the serving frontend
    snapshots from HTTP handler threads while the engine thread
    lazily creates instruments, and an unguarded dict iteration over
    a mutating family raises RuntimeError. The trainer's
    single-threaded hot path pays one uncontended acquire per
    get-or-create call (instrument methods themselves stay lock-free
    except Counter.inc)."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._sinks: list = []
        self._lock = threading.Lock()
        self._identity: Dict[str, object] = {}

    def set_identity(self, **fields) -> None:
        """Stamp every subsequently emitted record with these fields
        (``run_id`` / ``process_index`` / ``host`` — the join keys the
        fleet aggregator routes streams by; docs/metrics_schema.md
        "Run identity"). None values are dropped; an explicit record
        field of the same name wins over the stamp."""
        self._identity = {k: v for k, v in fields.items()
                          if v is not None}

    def identity(self) -> Dict[str, object]:
        return dict(self._identity)

    def _claim(self, name: str, family: Dict) -> None:
        """One name, one instrument family: a counter and a gauge
        sharing a name used to collide silently in ``snapshot()``
        (last writer won); refuse at creation instead."""
        for other in (self._counters, self._gauges, self._histograms):
            if other is not family and name in other:
                kind = {id(self._counters): "counter",
                        id(self._gauges): "gauge",
                        id(self._histograms): "histogram"}[id(other)]
                raise ValueError(
                    f"instrument name {name!r} already registered as a "
                    f"{kind}; one name maps to one snapshot() key")

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._claim(name, self._counters)
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._claim(name, self._gauges)
            return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str,
                  max_samples: int = Histogram.DEFAULT_MAX_SAMPLES
                  ) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._claim(name, self._histograms)
                self._histograms[name] = Histogram(max_samples)
            return self._histograms[name]

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def emit(self, kind: str, record: dict) -> None:
        """Tag, identity-stamp, and fan a finished record out to every
        sink."""
        rec = {"kind": kind}
        rec.update(self._identity)
        rec.update(record)
        for sink in self._sinks:
            sink.write(rec)

    def snapshot(self) -> Dict[str, float]:
        """Flat {name: value} view of every instrument: counters and
        gauges by name, histograms as ``name_p50`` etc. Cross-family
        duplicates are refused at creation; the one collision class
        left — a derived histogram key (``lap_p50``) matching a literal
        counter/gauge name — is disambiguated by suffixing the derived
        key with ``_hist`` instead of silently overwriting."""
        out: Dict[str, float] = {}
        with self._lock:
            for name, c in self._counters.items():
                out[name] = c.value
            for name, g in self._gauges.items():
                if g.value is not None:
                    out[name] = g.value
            for name, h in self._histograms.items():
                for k, v in h.summary().items():
                    key = f"{name}_{k}"
                    while key in out:
                        key += "_hist"
                    out[key] = v
        return out

    def reset_window(self) -> None:
        """Start a new observation window: histograms clear; counters
        and gauges persist (they are run-cumulative)."""
        with self._lock:
            hists = list(self._histograms.values())
        for h in hists:
            h.reset()
