"""Metrics registry: counters, gauges, histograms with pluggable sinks.

The instruments are deliberately host-side-only (plain Python floats):
observing a value never touches a device or forces a sync — the caller
decides when device values become host floats. Sinks receive finished
*records* (flat JSON-able dicts tagged with a ``kind``), not raw
observations, so the per-step hot path never formats or writes
anything; records are built at window edges (epoch boundaries, opt-in
per-step sampling).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional


class Counter:
    """Monotonically increasing sum (e.g. checkpoint saves, stall
    seconds)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class Gauge:
    """Last-write-wins sample (e.g. device bytes in use)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value: Optional[float] = None

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    """Windowed distribution with exact percentiles.

    Observations accumulate in a list until ``reset()`` (one window ==
    one epoch in the trainer); percentiles sort a copy on demand, so
    ``observe`` is a single append — cheap enough for the per-step
    path.
    """

    __slots__ = ("values",)

    def __init__(self):
        self.values: List[float] = []

    def observe(self, value: float) -> None:
        self.values.append(float(value))

    def __len__(self) -> int:
        return len(self.values)

    @property
    def total(self) -> float:
        return math.fsum(self.values)

    @staticmethod
    def _interp(xs: List[float], q: float) -> float:
        """q-th percentile of an already-sorted non-empty list."""
        if len(xs) == 1:
            return xs[0]
        rank = (q / 100.0) * (len(xs) - 1)
        lo = int(rank)
        hi = min(lo + 1, len(xs) - 1)
        frac = rank - lo
        return xs[lo] * (1.0 - frac) + xs[hi] * frac

    def percentile(self, q: float) -> Optional[float]:
        """Linear-interpolated q-th percentile (q in [0, 100]); None on
        an empty window."""
        if not self.values:
            return None
        return self._interp(sorted(self.values), q)

    def summary(self) -> Dict[str, float]:
        """{count, mean, p50, p90, p99} of the current window (empty
        dict on an empty window); one sort serves all three
        percentiles."""
        if not self.values:
            return {}
        xs = sorted(self.values)
        return {
            "count": len(xs),
            "mean": math.fsum(xs) / len(xs),
            "p50": self._interp(xs, 50),
            "p90": self._interp(xs, 90),
            "p99": self._interp(xs, 99),
        }

    def reset(self) -> None:
        self.values = []


class MemorySink:
    """In-memory sink for tests: records land in ``self.records``."""

    def __init__(self):
        self.records: List[dict] = []

    def write(self, record: dict) -> None:
        self.records.append(record)

    def by_kind(self, kind: str) -> List[dict]:
        return [r for r in self.records if r.get("kind") == kind]


class JsonlSink:
    """Sink adapter over ``MetricsLogger`` — obs records share the
    run's ``metrics.jsonl`` (one append-mode file, coordinator-only
    writes; MetricsLogger already enforces both)."""

    def __init__(self, logger):
        self._logger = logger

    def write(self, record: dict) -> None:
        self._logger.log(record)


class Registry:
    """Named instruments + sinks. ``counter``/``gauge``/``histogram``
    are get-or-create, so call sites never coordinate registration."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._sinks: list = []

    def counter(self, name: str) -> Counter:
        return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str) -> Histogram:
        return self._histograms.setdefault(name, Histogram())

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def emit(self, kind: str, record: dict) -> None:
        """Tag and fan a finished record out to every sink."""
        rec = {"kind": kind}
        rec.update(record)
        for sink in self._sinks:
            sink.write(rec)

    def snapshot(self) -> Dict[str, float]:
        """Flat {name: value} view of every instrument: counters and
        gauges by name, histograms as ``name_p50`` etc."""
        out: Dict[str, float] = {}
        for name, c in self._counters.items():
            out[name] = c.value
        for name, g in self._gauges.items():
            if g.value is not None:
                out[name] = g.value
        for name, h in self._histograms.items():
            for k, v in h.summary().items():
                out[f"{name}_{k}"] = v
        return out

    def reset_window(self) -> None:
        """Start a new observation window: histograms clear; counters
        and gauges persist (they are run-cumulative)."""
        for h in self._histograms.values():
            h.reset()
