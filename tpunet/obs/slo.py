"""SLO engine: declarative objectives, error budgets, burn-rate paging.

The observability stack before this module was entirely passive — it
records TTFT/e2e/trace quantiles, regressions and crashes after the
fact, but nothing STATES a target, tracks an error budget against it,
or pages while the budget is still burning. This module closes that
loop for the serve tier:

- **Policy** (``docs/slos.json`` / ``load_policy``): declarative SLO
  definitions — an SLI (availability, TTFT/e2e latency-vs-threshold,
  probe correctness), an objective (e.g. 0.99), a compliance window,
  and per-severity burn-rate alert rules.
- **SLI streams**: bounded in-memory event series the router (and its
  synthetic prober, tpunet/router/prober.py) feed — request outcomes,
  latency samples, probe verdicts. Everything is evaluated from the
  SAME streams, so passive traffic and canary probes share one budget.
- **Multi-window multi-burn-rate evaluation** (Google-SRE style): a
  rule fires only when the burn rate — observed error rate divided by
  the budget rate ``1 - objective`` — exceeds its threshold over BOTH
  a long and a short window. The long window gives the page
  significance (a real burn, not one unlucky minute); the short
  window gives it a fast reset (recovery stops paging within
  ``short_s``, not ``long_s``). ``page`` rules are the fast-burn
  "wake a human" tier; ``ticket`` rules the slow-burn "file a bug"
  tier.
- **Edge latching**: a rule pages once when it starts firing and
  re-arms when the condition clears — a sustained burn is one page,
  a relapse is a second one. Pages ride the existing ``obs_alert``
  kind (reasons ``slo_fast_burn`` / ``slo_slow_burn``), so the
  AlertWebhook delivery path (retry/backoff/dead-letter) works
  unchanged.
- **``obs_slo`` records** (docs/metrics_schema.md): one per SLO per
  emit window — budget remaining over the compliance window, burn
  rate per alert window, firing state, probe tallies, and the last
  failed probe's trace id (every failed probe points at a replayable
  trace).

Windows with no events yield NO verdict: a rule neither fires nor
clears on silence (an idle fleet is not an outage, and a wedged
prober must not clear an active page). Event timestamps are taken as
given — a skewed or replayed clock changes which window an event
lands in, never crashes the evaluator.
"""

from __future__ import annotations

import json
import re
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

#: SLI stream kinds a spec may target. ``availability`` and
#: ``correctness`` are good/bad count streams; the ``latency_*``
#: streams hold raw seconds judged against the spec's threshold.
SLIS = ("availability", "latency_ttft", "latency_e2e", "correctness")

#: Alert severities, in paging order.
SEVERITIES = ("page", "ticket")

#: Per-SLI event retention: enough for any realistic alert window at
#: probe cadence; under heavy passive traffic the oldest events age
#: out first, so the windows stay honest for recent traffic.
MAX_EVENTS = 4096


@dataclass(frozen=True)
class BurnRule:
    """One multi-window burn-rate alert rule: fire when the burn rate
    exceeds ``burn`` over BOTH the long and the short window."""

    severity: str            # "page" (fast burn) | "ticket" (slow burn)
    long_s: float
    short_s: float
    burn: float


@dataclass(frozen=True)
class SloSpec:
    """One declarative SLO over one SLI stream."""

    name: str
    sli: str
    objective: float         # good fraction target in (0, 1)
    compliance_window_s: float
    threshold_s: Optional[float] = None   # latency_* SLIs only
    rules: Tuple[BurnRule, ...] = ()

    @property
    def budget(self) -> float:
        """The error budget rate: the bad fraction the objective
        tolerates (burn rate 1.0 = spending it exactly on time)."""
        return 1.0 - self.objective


#: The built-in default policy — same content as docs/slos.json (which
#: is the commented, operator-editable copy). Production-scale windows:
#: the classic 14.4x-over-1h fast burn (2% of a 30-day budget in an
#: hour) and 6x-over-6h slow burn.
DEFAULT_POLICY: dict = {
    "slos": [
        {"name": "availability", "sli": "availability",
         "objective": 0.99, "compliance_window_s": 2592000,
         "page": {"long_s": 3600, "short_s": 300, "burn": 14.4},
         "ticket": {"long_s": 21600, "short_s": 1800, "burn": 6.0}},
        {"name": "ttft", "sli": "latency_ttft", "objective": 0.99,
         "threshold_s": 1.5, "compliance_window_s": 2592000,
         "page": {"long_s": 3600, "short_s": 300, "burn": 14.4},
         "ticket": {"long_s": 21600, "short_s": 1800, "burn": 6.0}},
        {"name": "e2e_latency", "sli": "latency_e2e",
         "objective": 0.95, "threshold_s": 10.0,
         "compliance_window_s": 2592000,
         "page": {"long_s": 3600, "short_s": 300, "burn": 14.4},
         "ticket": {"long_s": 21600, "short_s": 1800, "burn": 6.0}},
        {"name": "correctness", "sli": "correctness",
         "objective": 0.999, "compliance_window_s": 2592000,
         "page": {"long_s": 600, "short_s": 60, "burn": 1.0}},
    ],
}


class SloPolicyError(ValueError):
    """A malformed policy file — loud at boot, never mid-incident."""


def _strip_comments(text: str) -> str:
    """Drop full-line ``//`` comments so docs/slos.json can explain
    itself to operators (stdlib json has no comment support; only
    whole-line comments are stripped — ``//`` inside string values,
    e.g. URLs, is never touched)."""
    return "\n".join("" if re.match(r"\s*//", line) else line
                     for line in text.splitlines())


def _parse_spec(raw: dict) -> SloSpec:
    name = str(raw.get("name") or "")
    if not re.fullmatch(r"[a-z0-9_]+", name):
        raise SloPolicyError(
            f"slo name must be lowercase [a-z0-9_]+, got {name!r}")
    sli = str(raw.get("sli") or "")
    if sli not in SLIS:
        raise SloPolicyError(
            f"slo {name!r}: sli must be one of {SLIS}, got {sli!r}")
    try:
        objective = float(raw["objective"])
    except (KeyError, TypeError, ValueError):
        raise SloPolicyError(f"slo {name!r}: missing numeric objective")
    if not 0.0 < objective < 1.0:
        raise SloPolicyError(
            f"slo {name!r}: objective must be in (0, 1), got {objective}")
    window = float(raw.get("compliance_window_s") or 0)
    if window <= 0:
        raise SloPolicyError(
            f"slo {name!r}: compliance_window_s must be > 0")
    threshold = raw.get("threshold_s")
    if sli.startswith("latency_"):
        if threshold is None or float(threshold) <= 0:
            raise SloPolicyError(
                f"slo {name!r}: latency SLIs need threshold_s > 0")
        threshold = float(threshold)
    else:
        threshold = None
    rules = []
    for severity in SEVERITIES:
        rule = raw.get(severity)
        if rule is None:
            continue
        long_s = float(rule.get("long_s") or 0)
        short_s = float(rule.get("short_s") or 0)
        burn = float(rule.get("burn") or 0)
        if not 0 < short_s <= long_s:
            raise SloPolicyError(
                f"slo {name!r} {severity}: need 0 < short_s <= long_s")
        if burn <= 0:
            raise SloPolicyError(
                f"slo {name!r} {severity}: burn must be > 0")
        rules.append(BurnRule(severity, long_s, short_s, burn))
    if not rules:
        raise SloPolicyError(
            f"slo {name!r}: at least one of {SEVERITIES} required")
    return SloSpec(name=name, sli=sli, objective=objective,
                   compliance_window_s=window, threshold_s=threshold,
                   rules=tuple(rules))


def load_policy(path: str = "") -> Tuple[SloSpec, ...]:
    """Parse a policy file (``--slo-policy``) into specs; an empty
    path loads the built-in defaults (the same content docs/slos.json
    ships commented)."""
    if not path:
        raw = DEFAULT_POLICY
    else:
        with open(path) as f:
            text = _strip_comments(f.read())
        try:
            raw = json.loads(text)
        except ValueError as e:
            raise SloPolicyError(f"{path}: not valid JSON "
                                 f"(after //-comment strip): {e}")
    slos = raw.get("slos")
    if not isinstance(slos, list) or not slos:
        raise SloPolicyError(
            f"{path or '<default>'}: policy needs a non-empty "
            "'slos' list")
    specs = tuple(_parse_spec(s) for s in slos)
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise SloPolicyError(f"duplicate slo names: {sorted(names)}")
    return specs


def build_slo_record(*, name: str, sli: str, objective: float,
                     compliance_window_s: float,
                     threshold_s: Optional[float] = None,
                     events: int = 0, bad: int = 0,
                     error_rate: Optional[float] = None,
                     budget_remaining: Optional[float] = None,
                     page_burn_long: Optional[float] = None,
                     page_burn_short: Optional[float] = None,
                     page_burn_threshold: Optional[float] = None,
                     page_window_long_s: Optional[float] = None,
                     page_window_short_s: Optional[float] = None,
                     page_firing: bool = False,
                     ticket_burn_long: Optional[float] = None,
                     ticket_burn_short: Optional[float] = None,
                     ticket_burn_threshold: Optional[float] = None,
                     ticket_window_long_s: Optional[float] = None,
                     ticket_window_short_s: Optional[float] = None,
                     ticket_firing: bool = False,
                     pages_total: int = 0, tickets_total: int = 0,
                     probe_requests: int = 0, probe_failures: int = 0,
                     probe_mismatches: int = 0,
                     last_failed_trace: str = "") -> dict:
    """One flat ``obs_slo`` record (docs/metrics_schema.md).
    Module-level and engine-free so the schema-conformance check
    (scripts/check_metrics_schema.py) drives the exact shape without
    standing up a router."""
    record: dict = {"name": name, "sli": sli,
                    "objective": round(float(objective), 6),
                    "compliance_window_s": float(compliance_window_s),
                    "events": int(events), "bad": int(bad)}
    if threshold_s is not None:
        record["threshold_s"] = round(float(threshold_s), 6)
    for key, val, nd in (("error_rate", error_rate, 6),
                         ("budget_remaining", budget_remaining, 6),
                         ("page_burn_long", page_burn_long, 4),
                         ("page_burn_short", page_burn_short, 4),
                         ("ticket_burn_long", ticket_burn_long, 4),
                         ("ticket_burn_short", ticket_burn_short, 4)):
        if val is not None:
            record[key] = round(float(val), nd)
    for key, val in (("page_burn_threshold", page_burn_threshold),
                     ("page_window_long_s", page_window_long_s),
                     ("page_window_short_s", page_window_short_s),
                     ("ticket_burn_threshold", ticket_burn_threshold),
                     ("ticket_window_long_s", ticket_window_long_s),
                     ("ticket_window_short_s", ticket_window_short_s)):
        if val is not None:
            record[key] = float(val)
    if page_firing:
        record["page_firing"] = 1
    if ticket_firing:
        record["ticket_firing"] = 1
    if pages_total:
        record["pages_total"] = int(pages_total)
    if tickets_total:
        record["tickets_total"] = int(tickets_total)
    if probe_requests:
        record["probe_requests"] = int(probe_requests)
        record["probe_failures"] = int(probe_failures)
        record["probe_mismatches"] = int(probe_mismatches)
    if last_failed_trace:
        record["last_failed_trace"] = last_failed_trace
    return record


class SloEngine:
    """SLI streams + the multi-window burn-rate evaluator.

    Feed it events (``note_request`` / ``note_latency`` /
    ``note_probe``), call ``evaluate()`` on the control-loop cadence:
    it updates the ``slo_*`` gauges, fires/clears edge-latched pages
    through the registry's ``obs_alert`` path, and returns the
    ``obs_slo`` record bodies (the caller owns emission cadence).
    Thread-safe for the router's handler-threads-feed /
    control-loop-evaluates split.
    """

    def __init__(self, specs, *, registry=None, clock=time.time,
                 max_events: int = MAX_EVENTS):
        import threading
        self.specs: Tuple[SloSpec, ...] = tuple(specs)
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        # One event deque per SLI actually targeted: count SLIs hold
        # (t, good, bad); latency SLIs hold (t, seconds).
        self._streams: Dict[str, deque] = {
            s.sli: deque(maxlen=max_events) for s in self.specs}
        # Longest window any spec evaluates per SLI — events older
        # than it are pruned on append.
        self._horizon: Dict[str, float] = {}
        for s in self.specs:
            windows = [s.compliance_window_s]
            windows += [r.long_s for r in s.rules]
            self._horizon[s.sli] = max(self._horizon.get(s.sli, 0.0),
                                       max(windows))
        self._latched: Dict[Tuple[str, str], bool] = {}
        self._pages: Dict[str, int] = {s.name: 0 for s in self.specs}
        self._tickets: Dict[str, int] = {s.name: 0 for s in self.specs}
        self.probe_requests = 0
        self.probe_failures = 0
        self.probe_mismatches = 0
        self.last_failed_trace = ""

    # -- feed side -------------------------------------------------------

    def _append(self, sli: str, event, t: float) -> None:
        q = self._streams.get(sli)
        if q is None:
            return             # no spec targets this SLI
        horizon = self._horizon.get(sli, 0.0)
        with self._lock:
            q.append(event)
            while q and q[0][0] < t - horizon:
                q.popleft()

    def note_request(self, ok: bool, t: Optional[float] = None) -> None:
        """One availability event: a request that completed (ok) or
        was rejected / errored out (not ok)."""
        t = self._clock() if t is None else t
        self._append("availability", (t, 0 if ok else 1), t)

    def note_latency(self, kind: str, seconds: float,
                     t: Optional[float] = None) -> None:
        """One latency sample for the ``latency_<kind>`` SLI
        (``kind`` is ``ttft`` or ``e2e``); judged against each
        targeting spec's own threshold at evaluate time."""
        t = self._clock() if t is None else t
        self._append(f"latency_{kind}", (t, float(seconds)), t)

    def note_correctness(self, ok: bool,
                         t: Optional[float] = None) -> None:
        t = self._clock() if t is None else t
        self._append("correctness", (t, 0 if ok else 1), t)

    def note_probe(self, *, ok: bool, mismatch: bool = False,
                   ttft_s: Optional[float] = None,
                   e2e_s: Optional[float] = None, trace_id: str = "",
                   t: Optional[float] = None) -> None:
        """One synthetic-prober verdict, fanned into every SLI stream:
        availability (did it answer), latency (how fast), correctness
        (were the tokens bitwise golden — only judgeable when it
        answered). A failed or wrong probe pins its trace id so the
        page that follows points at a replayable trace."""
        t = self._clock() if t is None else t
        self.probe_requests += 1
        self.note_request(ok, t=t)
        if ok:
            if ttft_s is not None:
                self.note_latency("ttft", ttft_s, t=t)
            if e2e_s is not None:
                self.note_latency("e2e", e2e_s, t=t)
            self.note_correctness(not mismatch, t=t)
        if not ok:
            self.probe_failures += 1
        if mismatch:
            self.probe_mismatches += 1
        if (not ok or mismatch) and trace_id:
            self.last_failed_trace = trace_id

    # -- evaluate side ---------------------------------------------------

    def _window_counts(self, spec: SloSpec, now: float,
                       window_s: float) -> Tuple[int, int]:
        """(events, bad) inside ``[now - window_s, ...]`` for one
        spec. Latency SLIs count a sample as bad when it exceeds the
        spec's threshold; future-stamped events (clock skew) land in
        every window rather than vanishing."""
        q = self._streams.get(spec.sli)
        if not q:
            return 0, 0
        lo = now - window_s
        total = bad = 0
        with self._lock:
            events = list(q)
        if spec.sli.startswith("latency_"):
            for t, seconds in events:
                if t >= lo:
                    total += 1
                    if seconds > spec.threshold_s:
                        bad += 1
        else:
            for t, is_bad in events:
                if t >= lo:
                    total += 1
                    bad += is_bad
        return total, bad

    def _burn(self, spec: SloSpec, now: float,
              window_s: float) -> Optional[float]:
        """Burn rate over one window: observed error rate / budget
        rate. None when the window holds no events (no verdict)."""
        total, bad = self._window_counts(spec, now, window_s)
        if total == 0:
            return None
        return (bad / total) / spec.budget

    def _fire(self, spec: SloSpec, rule: BurnRule, burn_long: float,
              burn_short: float, budget_remaining) -> None:
        reason = ("slo_fast_burn" if rule.severity == "page"
                  else "slo_slow_burn")
        if rule.severity == "page":
            self._pages[spec.name] += 1
        else:
            self._tickets[spec.name] += 1
        if self.registry is None:
            return
        self.registry.counter("slo_pages_total" if rule.severity
                              == "page" else "slo_tickets_total").inc()
        # Detail fields flat on the record, the obs_alert convention
        # every emitter follows (health.py, agg/alerts.py, orbax_io).
        record: dict = {
            "reason": reason, "severity": rule.severity, "step": 0,
            "slo": spec.name, "sli": spec.sli,
            "objective": spec.objective,
            "burn_long": round(burn_long, 4),
            "burn_short": round(burn_short, 4),
            "burn_threshold": rule.burn,
            "window_long_s": rule.long_s,
            "window_short_s": rule.short_s,
        }
        if budget_remaining is not None:
            record["budget_remaining"] = round(budget_remaining, 6)
        if self.last_failed_trace:
            record["trace_id"] = self.last_failed_trace
        self.registry.emit("obs_alert", record)

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass over every SLO: update gauges, fire or
        re-arm edge-latched pages, return the ``obs_slo`` record
        bodies. Idempotent between state changes — safe on every
        control-loop round."""
        now = self._clock() if now is None else now
        records = []
        for spec in self.specs:
            total, bad = self._window_counts(
                spec, now, spec.compliance_window_s)
            error_rate = bad / total if total else None
            budget_remaining = None
            if error_rate is not None:
                budget_remaining = max(
                    0.0, 1.0 - error_rate / spec.budget)
            fields: dict = {}
            for rule in spec.rules:
                burn_long = self._burn(spec, now, rule.long_s)
                burn_short = self._burn(spec, now, rule.short_s)
                sev = rule.severity
                fields[f"{sev}_burn_long"] = burn_long
                fields[f"{sev}_burn_short"] = burn_short
                fields[f"{sev}_burn_threshold"] = rule.burn
                fields[f"{sev}_window_long_s"] = rule.long_s
                fields[f"{sev}_window_short_s"] = rule.short_s
                key = (spec.name, sev)
                latched = self._latched.get(key, False)
                if burn_long is None or burn_short is None:
                    # Empty window: no verdict — the latch holds (an
                    # idle fleet is not an outage; a wedged prober
                    # must not clear an active page).
                    firing = latched
                else:
                    firing = (burn_long >= rule.burn
                              and burn_short >= rule.burn)
                    if firing and not latched:
                        self._fire(spec, rule, burn_long, burn_short,
                                   budget_remaining)
                    self._latched[key] = firing
                fields[f"{sev}_firing"] = firing
                if self.registry is not None and burn_long is not None:
                    self.registry.gauge(
                        f"slo_{spec.name}_{sev}_burn").set(
                        round(burn_long, 4))
            if self.registry is not None \
                    and budget_remaining is not None:
                self.registry.gauge(
                    f"slo_{spec.name}_budget_remaining").set(
                    round(budget_remaining, 6))
            records.append(build_slo_record(
                name=spec.name, sli=spec.sli, objective=spec.objective,
                compliance_window_s=spec.compliance_window_s,
                threshold_s=spec.threshold_s, events=total, bad=bad,
                error_rate=error_rate,
                budget_remaining=budget_remaining,
                pages_total=self._pages[spec.name],
                tickets_total=self._tickets[spec.name],
                probe_requests=self.probe_requests,
                probe_failures=self.probe_failures,
                probe_mismatches=self.probe_mismatches,
                last_failed_trace=self.last_failed_trace,
                **fields))
        return records
