"""Trace spans and windowed profiling.

Spans wrap ``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation``
so the step, data-wait, eval, and checkpoint phases show up as labeled
regions in xprof alongside the device timeline. ``WindowedProfiler``
replaces the old whole-run ``jax.profiler.start_trace`` toggle
(tpunet/main.py pre-obs): a trace is captured for exactly the
configured step window [start, start+num), with ``block_until_ready``
fences at the two window edges ONLY — async dispatch means work queued
before the window would otherwise bleed into it, and work dispatched
inside the window would escape it.
"""

from __future__ import annotations

import contextlib

import jax

# Reusable no-op span for the disabled path (nullcontext is documented
# reentrant and reusable — nothing allocated per use).
NULL_SPAN = contextlib.nullcontext()


def span(name: str):
    """Host-side labeled region for xprof (nests freely)."""
    return jax.profiler.TraceAnnotation(name)


def step_span(step: int, name: str = "train"):
    """Per-step region; xprof's step-oriented views key on these."""
    return jax.profiler.StepTraceAnnotation(name, step_num=step)


class WindowedProfiler:
    """Capture a jax profiler trace for steps [start, start+num).

    ``num_steps == 0`` with a non-empty ``profile_dir`` keeps the old
    whole-run semantics (start at the first step, stop at ``close()``)
    so existing ``--profile-dir`` invocations still work. ``on_step``
    is called before each step's dispatch with the global step number
    and a ``sync`` callable (``block_until_ready`` over the live
    state); the sync runs at window edges only, never on interior
    steps.
    """

    def __init__(self, profile_dir: str, start_step: int = 0,
                 num_steps: int = 0):
        if start_step < 0 or num_steps < 0:
            raise ValueError(
                f"profile window must be non-negative, got start_step="
                f"{start_step} num_steps={num_steps}")
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self.running = False
        self._done = not bool(profile_dir)

    @property
    def active(self) -> bool:
        """True while this profiler may still start or stop a trace
        (the loop skips the per-step check entirely once False)."""
        return not self._done or self.running

    def on_step(self, step: int, sync=None) -> None:
        if self._done and not self.running:
            return
        if self.running:
            if (self.num_steps
                    and step >= self.start_step + self.num_steps):
                self._stop(sync)
            return
        if step >= self.start_step:
            if self.num_steps and step >= self.start_step + self.num_steps:
                # The run resumed past the window (or the window fell
                # inside a skipped epoch): never trace.
                self._done = True
                return
            if sync is not None:
                sync()  # fence: pre-window dispatches complete outside
            jax.profiler.start_trace(self.profile_dir)
            self.running = True

    def _stop(self, sync=None) -> None:
        if sync is not None:
            sync()  # fence: in-window dispatches complete inside
        jax.profiler.stop_trace()
        self.running = False
        self._done = True

    def close(self, sync=None) -> None:
        """End-of-run: flush a still-open (whole-run or truncated)
        window."""
        if self.running:
            self._stop(sync)
        self._done = True
