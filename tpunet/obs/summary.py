"""One summarizer for every metrics.jsonl consumer.

``scripts/obs_report.py`` (post-mortem), ``scripts/obs_dashboard.py``
(live) and any ``--json`` machine consumer all read the same record
stream; this module turns parsed records into one structured summary
dict so the three views can never drift on what "stall fraction" or
"step-time trend" means. Record kinds are documented in
``docs/metrics_schema.md``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from tpunet.obs.registry import percentile_of_sorted


def _percentile(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    return percentile_of_sorted(sorted(xs), q)


def step_windows(steps: List[dict], n_windows: int = 12) -> List[Dict]:
    """Bucket ``obs_step`` records into up to ``n_windows`` contiguous
    step-range windows and summarize each — the step-time *trend* view
    (is the run slowing down? did stalls start at step 40k?) that a
    single whole-run percentile hides."""
    times = [(r["step"], r["step_time_s"], r.get("data_wait_s", 0.0))
             for r in steps if "step_time_s" in r]
    if not times:
        return []
    times.sort(key=lambda t: t[0])
    per = max(1, -(-len(times) // n_windows))  # ceil division
    out = []
    for i in range(0, len(times), per):
        chunk = times[i:i + per]
        laps = [t[1] for t in chunk]
        waits = [t[2] for t in chunk]
        out.append({
            "step_lo": chunk[0][0],
            "step_hi": chunk[-1][0],
            "samples": len(chunk),
            "step_time_mean_s": sum(laps) / len(laps),
            "step_time_p50_s": _percentile(laps, 50),
            "step_time_p99_s": _percentile(laps, 99),
            "data_wait_mean_s": sum(waits) / len(waits),
        })
    return out


def summarize(records: List[dict], n_windows: int = 12) -> Dict:
    """Structured summary of a run's metrics.jsonl records.

    Returns ``{epochs, obs_epochs, step_windows, alerts, totals}``:
    the raw per-epoch rows (plain training records and ``obs_epoch``
    records), the bucketed ``obs_step`` trend, every ``obs_alert``,
    and run-level aggregates (stall fraction, memory high-water, last
    throughput/MFU).
    """
    epochs = [r for r in records if "kind" not in r and "epoch" in r]
    obs = [r for r in records if r.get("kind") == "obs_epoch"]
    steps = [r for r in records if r.get("kind") == "obs_step"]
    alerts = [r for r in records if r.get("kind") == "obs_alert"]
    # obs_crash records (a restarted run reporting its predecessor's
    # death, tpunet/obs/flightrec/) surface in the alert feed: a crash
    # is the page of pages. They keep their own count in totals.
    crashes = [r for r in records if r.get("kind") == "obs_crash"]
    alerts = alerts + [{**r, "reason": "crash", "severity": "fatal",
                        "step": r.get("step", 0)} for r in crashes]

    totals: Dict = {"epochs": len(epochs), "obs_epochs": len(obs),
                    "obs_steps": len(steps), "alerts": len(alerts)}
    if crashes:
        totals["crashes"] = len(crashes)
    if obs:
        stall = sum(r.get("input_stall_s", 0.0) for r in obs)
        train = sum(r.get("train_seconds", 0.0) for r in obs)
        totals["input_stall_s"] = round(stall, 4)
        totals["train_seconds"] = round(train, 4)
        totals["stall_frac"] = round(stall / train, 4) if train else 0.0
        last = obs[-1]
        totals["last_step"] = last.get("step")
        for k in ("examples_per_sec", "tokens_per_sec", "mfu"):
            if last.get(k) is not None:
                totals[k] = last[k]
        peaks = [m.get("peak_bytes_in_use")
                 for r in obs for m in r.get("device_memory", [])
                 if m.get("peak_bytes_in_use") is not None]
        if peaks:
            totals["peak_bytes_in_use"] = max(peaks)
        beats = [r.get("live_processes") for r in obs
                 if r.get("live_processes") is not None]
        if beats:
            totals["live_processes"] = beats[-1]
    return {
        "epochs": epochs,
        "obs_epochs": obs,
        "step_windows": step_windows(steps, n_windows),
        "alerts": alerts,
        "totals": totals,
    }
