"""Device-time attribution by training phase from a profiler trace.

The windowed profiler (tpunet/obs/spans.py) captures an xplane under
``--profile-dir``; xprof's ``hlo_stats`` tool turns it into per-HLO-op
rows with measured device self time. This module groups those rows by
the training PHASE the op belongs to — fwd / bwd / optimizer / ema /
eval — using the same ``jax.named_scope`` markers the jitted steps
plant (``tpunet_fwd_bwd`` etc., classified by
``tpunet.obs.hlo_bytes.phase_of``), so a step-time regression names
the phase that moved instead of one opaque host lap.

``hlo_stats_rows`` needs the optional ``xprof`` package (present on
the TPU toolchain, not in minimal CPU installs) — callers get a clear
ImportError. ``phase_times`` is pure and unit-tested without it.
Consumers: scripts/obs_report.py ``--trace`` and
scripts/roofline_attrib.py.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List

from tpunet.obs.hlo_bytes import phase_of

PHASES = ("augment", "fwd", "bwd", "optimizer", "ema", "eval", "other")


def hlo_stats_rows(trace_dir: str) -> List[dict]:
    """Parse the captured xplane(s) under ``trace_dir`` into per-HLO-op
    row dicts via xprof's hlo_stats tool (a gviz DataTable: one dict
    per op with 'Framework op name', 'Total self time (us)', ...)."""
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as e:
        raise ImportError(
            "per-phase device-time attribution needs the 'xprof' "
            "package (ships with the TPU toolchain); host-lap timings "
            "in obs_epoch records remain available without it") from e
    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {trace_dir!r} "
                                "(did the profile window run?)")
    data, _ = rtd.xspace_to_tool_data(paths, "hlo_stats", {})
    tab = json.loads(data.decode() if isinstance(data, bytes) else data)
    labels = [c["label"] for c in tab["cols"]]
    return [dict(zip(labels, [(c or {}).get("v") for c in r["c"]]))
            for r in tab["rows"]]


def phase_times(rows: List[dict]) -> Dict[str, Dict[str, float]]:
    """Group measured device self time by training phase.

    -> {phase: {"us": total self time, "pct": share of profiled
    time}}, phases ordered by time. Rows without a framework op name
    (infeed, runtime gaps) land in 'other'.
    """
    by_phase: Dict[str, float] = {}
    for r in rows:
        try:
            t = float(r.get("Total self time (us)") or 0.0)
        except (TypeError, ValueError):
            t = 0.0
        if not t:
            continue
        ph = phase_of(r.get("Framework op name") or "")
        by_phase[ph] = by_phase.get(ph, 0.0) + t
    total = sum(by_phase.values()) or 1.0
    return {ph: {"us": round(us, 1), "pct": round(100.0 * us / total, 2)}
            for ph, us in sorted(by_phase.items(), key=lambda kv: -kv[1])}
