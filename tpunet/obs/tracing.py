"""End-to-end request tracing across the serve tier.

One request now crosses up to three processes — the router frontend,
the replica that started the stream, and (under mid-stream failover,
docs/serving.md) the survivor that finished it. This module is the
shared vocabulary that lets all of them talk about the SAME request:

- **trace id** — 16 lowercase hex chars, minted by the router (or
  adopted from a client-supplied ``X-Trace-Id``), carried on every
  replica hop via headers, including failover re-submits carrying
  ``resume_tokens``.
- **hop** — which process span a breadcrumb belongs to: hop 0 is the
  router relay, hop 1 the first replica attempt, each re-open (route
  retry or failover re-submit) increments. ``(trace_id, hop)`` is
  globally unique; per-process request ids are not.
- **breadcrumbs** — ``trace``-kind flight-recorder ring events
  (``crumb()``), one per phase transition. The ring slot caps ``msg``
  at 80 bytes, so crumbs are a compact ``verb id hop k=v ...`` line.
  ``obs/history/timeline.py`` JOINs them across a router ring plus N
  replica rings into one causal track per trace.
- **``obs_trace`` records** — one flat per-hop span summary
  (docs/metrics_schema.md) emitted at request finish: queue / prefill
  / first-decode decomposition, preemption count and wall, the
  failover seam (``tokens_relayed``), finish reason. The fleet
  aggregator digests them into ``fleet_trace_*`` SLO decomposition
  and a slow-request exemplar list.

Cost discipline: tracing is head-sampled at the router
(``--trace-sample``; a client-supplied ``X-Trace-Id`` is always
sampled — explicit opt-in). An unsampled request carries an empty
``trace_id`` through the serve path and every call site short-circuits
on that one truthiness check, keeping the default path inside the
existing observability overhead gate (scripts/check_obs_overhead.py).
"""

from __future__ import annotations

import os
import re
from typing import Optional

from tpunet.obs import flightrec

#: Wire format (docs/metrics_schema.md "Trace wire format"): the
#: router stamps all three on every replica hop; clients may supply
#: ``X-Trace-Id`` to force-sample one request.
TRACE_HEADER = "X-Trace-Id"
SAMPLED_HEADER = "X-Trace-Sampled"
HOP_HEADER = "X-Trace-Hop"

_ID_RE = re.compile(r"[0-9a-f]{8,32}\Z")


def mint_trace_id() -> str:
    """A fresh 16-hex trace id (64 random bits — collision-safe for
    any realistic request volume, small enough for an 80-byte ring
    slot next to a verb and a hop)."""
    return os.urandom(8).hex()


def valid_trace_id(value) -> bool:
    """Accept 8-32 lowercase hex chars — our own ids plus common
    external formats (W3C trace ids are 32 hex). Anything else is
    rejected so a hostile header can't pollute rings or records."""
    return isinstance(value, str) and bool(_ID_RE.fullmatch(value))


def should_sample(rate: float, trace_id: str) -> bool:
    """Deterministic head-based sampling: hash the id's first 8 hex
    chars into [0, 1). Every process that sees the same id makes the
    same call — no coin-flip disagreement between hops."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) / 0x100000000 < rate


def crumb(verb: str, trace_id: str, hop: int, **fields) -> None:
    """One ``trace``-kind ring breadcrumb: ``verb id hop k=v ...``.
    No-op when no recorder is armed (flightrec.record contract).
    Callers guard on ``trace_id`` truthiness so unsampled requests
    pay one attribute read, not a string build."""
    extra = "".join(f" {k}={v}" for k, v in fields.items())
    flightrec.record("trace", f"{verb} {trace_id} {hop}{extra}")


def parse_crumb(msg: str) -> Optional[dict]:
    """Invert ``crumb()`` for the timeline join: ``{"verb", "trace_id",
    "hop", <k: v strings>}`` or None for a malformed line."""
    parts = msg.split()
    if len(parts) < 3 or not parts[2].isdigit():
        return None
    out = {"verb": parts[0], "trace_id": parts[1],
           "hop": int(parts[2])}
    for kv in parts[3:]:
        k, sep, v = kv.partition("=")
        if sep:
            out[k] = v
    return out


def build_trace_record(*, trace_id: str, hop: int, role: str,
                       finish_reason: str,
                       queue_s: Optional[float] = None,
                       prefill_s: Optional[float] = None,
                       prefill_bucket: Optional[int] = None,
                       first_decode_s: Optional[float] = None,
                       tokens: int = 0,
                       preemptions: int = 0,
                       preempt_wall_s: Optional[float] = None,
                       resume_offset: int = 0,
                       failover_count: int = 0,
                       tokens_relayed: Optional[int] = None,
                       ttft_s: Optional[float] = None,
                       e2e_s: Optional[float] = None,
                       error: str = "") -> dict:
    """One flat ``obs_trace`` record (docs/metrics_schema.md) — the
    per-hop span summary. Module-level and engine-free so the
    schema-conformance check (scripts/check_metrics_schema.py) drives
    the exact shape without standing up a server. ``role`` is
    ``router`` (relay span: e2e, failover seam) or ``replica``
    (compute span: queue/prefill/decode decomposition)."""
    if role not in ("router", "replica"):
        raise ValueError(f"role must be router|replica, got {role!r}")
    record: dict = {"trace_id": trace_id, "hop": int(hop),
                    "role": role, "finish_reason": finish_reason,
                    "tokens": int(tokens)}
    for key, val, nd in (("queue_s", queue_s, 6),
                         ("prefill_s", prefill_s, 6),
                         ("first_decode_s", first_decode_s, 6),
                         ("preempt_wall_s", preempt_wall_s, 6),
                         ("ttft_s", ttft_s, 6),
                         ("e2e_s", e2e_s, 6)):
        if val is not None:
            record[key] = round(float(val), nd)
    if prefill_bucket is not None:
        record["prefill_bucket"] = int(prefill_bucket)
    if preemptions:
        record["preemptions"] = int(preemptions)
    if resume_offset:
        record["resume_offset"] = int(resume_offset)
    if failover_count:
        record["failover_count"] = int(failover_count)
    if tokens_relayed is not None:
        record["tokens_relayed"] = int(tokens_relayed)
    if error:
        record["error"] = str(error)[:200]
    return record


def observe_trace(reg, record: dict) -> None:
    """Bump the ``trace_*`` registry instruments from one record —
    sampled-request counts plus the phase histograms the fleet SLO
    decomposition quantiles come from."""
    reg.counter("trace_requests_total").inc()
    for key in ("queue_s", "prefill_s", "first_decode_s", "e2e_s"):
        val = record.get(key)
        if val is not None:
            reg.histogram(f"trace_{key}").observe(float(val))
