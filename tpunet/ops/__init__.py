"""TPU kernels and attention ops for tpunet's hot paths.

Two families live here:

- ``depthwise``: Pallas TPU kernel for the 3x3 depthwise convolution —
  the VPU-bound hot op of MobileNetV2 (9 multiply-adds per output
  element with no contraction to feed the MXU; the one place a
  hand-written kernel beats XLA's generic conv emitter).
- ``attention``: dense / blockwise / ring / Ulysses attention. Ring
  (K/V shards rotate over a mesh axis via ppermute with online-softmax
  accumulation) and Ulysses (all-to-all head resharding around a
  blockwise core) are the sequence-parallel primitives backing
  long-context support in the attention-based model families.
"""

from tpunet.ops.attention import (blockwise_attention, dense_attention,
                                  ring_attention, ring_self_attention,
                                  ulysses_attention, ulysses_self_attention)
from tpunet.ops.depthwise import depthwise_conv3x3, depthwise_conv3x3_reference

__all__ = [
    "blockwise_attention",
    "dense_attention",
    "depthwise_conv3x3",
    "depthwise_conv3x3_reference",
    "ring_attention",
    "ring_self_attention",
    "ulysses_attention",
    "ulysses_self_attention",
]
