"""TPU kernels and attention ops for tpunet's hot paths.

Two families live here:

- ``depthwise``: Pallas TPU kernel for the 3x3 depthwise convolution —
  the VPU-bound hot op of MobileNetV2 (9 multiply-adds per output
  element with no contraction to feed the MXU). Honest measurement:
  XLA's fused conv pipeline beats it end-to-end, so it is off by
  default and kept as the worked VPU-kernel example.
- ``attention``: dense / blockwise / ring / Ulysses attention. Ring
  (K/V shards rotate over a mesh axis via ppermute with online-softmax
  accumulation) and Ulysses (all-to-all head resharding around a
  blockwise core) are the sequence-parallel primitives backing
  long-context support in the attention-based model families.
- ``flash``: Pallas TPU flash-attention kernel — the fused MXU form of
  the same online-softmax math (scores never leave VMEM).
- ``fused_ir``: Pallas kernel pair for the inverted-residual 1x1 convs
  (expand/project): one-pass conv + BN-stats forward and an IO-aware
  backward that recomputes the elementwise epilogue in VMEM — the
  HBM-traffic lever behind ``ModelConfig.fused_ir``.
"""

from tpunet.ops.attention import (blockwise_attention, dense_attention,
                                  ring_attention, ring_self_attention,
                                  ulysses_attention, ulysses_self_attention)
from tpunet.ops.depthwise import depthwise_conv3x3, depthwise_conv3x3_reference
from tpunet.ops.flash import flash_attention
from tpunet.ops.fused_ir import conv1x1_bn_act, conv1x1_bn_act_reference

__all__ = [
    "blockwise_attention",
    "conv1x1_bn_act",
    "conv1x1_bn_act_reference",
    "dense_attention",
    "depthwise_conv3x3",
    "depthwise_conv3x3_reference",
    "flash_attention",
    "ring_attention",
    "ring_self_attention",
    "ulysses_attention",
    "ulysses_self_attention",
]
