"""Pallas TPU kernels for tpunet's hot ops.

The MobileNetV2 compute profile on TPU splits into MXU work (1x1
expansion/projection convs and the stem — XLA tiles these onto the
systolic array well) and VPU work (the 3x3 depthwise convs — 9
multiply-adds per output element with no contraction to feed the MXU).
The depthwise layers are the one place a hand-written kernel can beat
XLA's generic conv emitter, so that is what lives here.
"""

from tpunet.ops.depthwise import depthwise_conv3x3, depthwise_conv3x3_reference

__all__ = ["depthwise_conv3x3", "depthwise_conv3x3_reference"]
