"""Attention ops: dense, blockwise, ring and Ulysses (sequence-parallel).

The reference workload is a CNN with no attention anywhere (SURVEY.md
section 2b), but tpunet treats long-context support as first-class: these
ops are the sequence/context-parallel layer that the attention-based
model families (tpunet/models/) build on.

Design (TPU-first):

- All variants share one *online-softmax* block update (the math of
  FlashAttention / Rabe-Staats): running max ``m``, normalizer ``l`` and
  un-normalized accumulator ``acc`` are carried across key/value blocks,
  so the full [Tq, Tk] score matrix never materializes. Accumulation is
  float32 regardless of compute dtype.
- ``blockwise_attention`` scans the *local* K/V in chunks — bounded
  memory for long sequences on one chip.
- ``ring_attention`` is the sequence-parallel form (Liu et al., "Ring
  Attention with Blockwise Transformers"): Q stays put, K/V shards
  rotate around the mesh axis via ``lax.ppermute`` (one ICI hop per
  step), each arrival folded in with the same online-softmax update.
  It is written against a shard_map axis name; ``ring_self_attention``
  wraps it in ``jax.shard_map`` over a mesh.
- ``ulysses_attention`` is the all-to-all sequence-parallel form
  (DeepSpeed-Ulysses): two ``lax.all_to_all``s trade the seq sharding
  for head sharding around a locally-dense full-sequence attention.
  Fewer collectives than the ring; memory O(T) per head group.
- Layout is [batch, seq, heads, head_dim] (BTHD) throughout.
- Causal masking uses *global* positions reconstructed from the axis
  index, so causality is exact under sequence sharding.

Differentiable end-to-end (the ring rotation is a ``lax.scan``; JAX
reverse-differentiates through the ppermutes).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.compat import shard_map

_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/grads NaN-free


# ---------------------------------------------------------------------------
# Shared online-softmax block update
# ---------------------------------------------------------------------------

def _block_update(carry: Tuple[jax.Array, jax.Array, jax.Array],
                  q: jax.Array, k: jax.Array, v: jax.Array,
                  scale: float,
                  mask: Optional[jax.Array]) -> Tuple[jax.Array, jax.Array,
                                                      jax.Array]:
    """Fold one K/V block into the (m, l, acc) running softmax state.

    q [B,Tq,H,D]; k,v [B,Tk,H,D]; mask [Tq,Tk] or — per-example
    (packed-segment) masks — [B,Tq,Tk] bool (True = attend) or None.
    m,l [B,H,Tq]; acc [B,Tq,H,D]. All state float32.
    """
    m, l, acc = carry
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        if mask.ndim == 2:
            mask = mask[None]
        mask = mask[:, None]                       # broadcast over heads
        s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # Rows with nothing to attend to yet keep m at the initial floor;
    # exp(s - floor) would overflow, so shift defensively.
    p = jnp.exp(s - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                    preferred_element_type=jnp.float32)
    acc = acc * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l, acc


def _init_carry(q: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    b, tq, h, d = q.shape
    m = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)
    acc = jnp.zeros((b, tq, h, d), jnp.float32)
    return m, l, acc


def _finalize(m, l, acc, dtype) -> jax.Array:
    # l == 0 only for rows masked out of every block; emit zeros there.
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(dtype)


# ---------------------------------------------------------------------------
# Dense reference
# ---------------------------------------------------------------------------

def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    scale: Optional[float] = None,
                    segment_ids=None) -> jax.Array:
    """Plain softmax attention, float32 accumulation. BTHD layout.

    ``segment_ids``: optional (q_seg [B,Tq], kv_seg [B,Tk]) int pair for
    packed sequences — a query attends only to same-segment keys."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    mask = None                                    # [B, Tq, Tk] or None
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)[None]
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        seg = q_seg[:, :, None] == kv_seg[:, None, :]
        mask = seg if mask is None else mask & seg
    if mask is not None:
        s = jnp.where(mask[:, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if mask is not None:
        # Rows with no valid key (tq > tk top rows, orphan segments) get
        # zeros, matching the l == 0 convention of the blockwise/ring
        # variants — softmax alone would attend uniformly, leaking
        # masked values.
        p = jnp.where(mask.any(-1)[:, None, :, None], p, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Blockwise attention (single device, chunked K/V)
# ---------------------------------------------------------------------------

def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        block_size: int = 512,
                        causal: bool = False,
                        scale: Optional[float] = None,
                        segment_ids=None) -> jax.Array:
    """Online-softmax attention over K/V chunks of ``block_size``.

    Memory is O(Tq * block_size) instead of O(Tq * Tk); exact same
    result as ``dense_attention``. ``segment_ids``: optional
    (q_seg [B,Tq], kv_seg [B,Tk]) pair for packed sequences — the
    kv-block slice of the mask rides the scan, keeping the
    O(Tq * block_size) bound."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    tq, tk = q.shape[1], k.shape[1]
    block_size = min(block_size, tk)
    if tk % block_size != 0:
        raise ValueError(f"seq len {tk} not divisible by block {block_size}")
    n_blocks = tk // block_size
    b = k.shape[0]
    kb = k.reshape(b, n_blocks, block_size, *k.shape[2:])
    vb = v.reshape(b, n_blocks, block_size, *v.shape[2:])
    q_pos = jnp.arange(tq)
    if segment_ids is not None:
        q_seg, kv_seg = segment_ids
        sb = kv_seg.reshape(b, n_blocks, block_size).swapaxes(0, 1)
    else:
        q_seg = None
        sb = jnp.zeros((n_blocks, 0), jnp.int32)   # scan arity filler

    def body(carry, xs):
        j, k_j, v_j, s_j = xs
        mask = None
        if causal:
            k_pos = j * block_size + jnp.arange(block_size)
            mask = q_pos[:, None] + (tk - tq) >= k_pos[None, :]
        if q_seg is not None:
            seg = q_seg[:, :, None] == s_j[:, None, :]  # [B, Tq, bs]
            mask = seg if mask is None else mask[None] & seg
        return _block_update(carry, q, k_j, v_j, scale, mask), None

    (m, l, acc), _ = jax.lax.scan(
        body, _init_carry(q),
        (jnp.arange(n_blocks), kb.swapaxes(0, 1), vb.swapaxes(0, 1), sb))
    return _finalize(m, l, acc, q.dtype)


# ---------------------------------------------------------------------------
# Ring attention (sequence-parallel, shard_map body)
# ---------------------------------------------------------------------------

def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, *,
                   causal: bool = False,
                   scale: Optional[float] = None,
                   core: Optional[str] = None) -> jax.Array:
    """Sequence-parallel attention over shard_map axis ``axis_name``.

    Call inside ``shard_map`` with q/k/v sharded on their seq dim over
    ``axis_name``. K/V shards rotate around the ring (``lax.ppermute``,
    one neighbor hop per step — ICI-friendly); each arriving block is
    folded in. Exactly matches ``dense_attention`` on the gathered
    arrays.

    ``core`` (like Ulysses'): None = the flash kernel on TPU, the
    pure-JAX online-softmax update elsewhere; "flash"/"blockwise"
    force. The flash core computes each arriving block with the fused
    kernel and folds it in by exact attention-state merging
    (tpunet/ops/flash.py merge_attention_states); a ring step is one
    of three static cases per source shard — fully past (unmasked
    flash), the diagonal (causal flash), fully future (skip) — selected
    with lax.cond on the rotating source index.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    tq = q.shape[1]
    tk = k.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    auto = core is None
    if auto:
        core = "flash" if jax.default_backend() == "tpu" else "blockwise"
    if core not in ("flash", "blockwise"):
        raise ValueError(f"unknown attention core {core!r}")
    if core == "flash":
        if not causal or tq == tk:
            return _ring_flash(q, k, v, axis_name, causal, scale, n, my,
                               perm)
        if not auto:
            raise ValueError(
                f"core='flash' does not support causal cross-length "
                f"rings (tq={tq} != tk={tk}: per-step masks are "
                "arbitrary); use core='blockwise'")
    # core == "blockwise" (the pure-JAX path), or auto-selected flash
    # downgraded for a causal cross-length ring.

    q_pos = my * tq + jnp.arange(tq)

    def block_mask(step):
        # k block held at `step` originated on device (my - step) mod n.
        if not causal:
            return None
        k_pos = ((my - step) % n) * tk + jnp.arange(tk)
        return q_pos[:, None] >= k_pos[None, :]

    def body(carry, step):
        state, k_cur, v_cur = carry
        state = _block_update(state, q, k_cur, v_cur, scale,
                              block_mask(step))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (state, k_nxt, v_nxt), None

    # n-1 update+rotate steps, then a final update with no rotation (the
    # last ppermute's result would be discarded, but XLA cannot DCE a
    # collective inside the scan — one wasted ICI hop per layer per step).
    state, k_last, v_last = _init_carry(q), k, v
    if n > 1:
        (state, k_last, v_last), _ = jax.lax.scan(
            body, (state, k, v), jnp.arange(n - 1))
    m, l, acc = _block_update(state, q, k_last, v_last, scale,
                              block_mask(n - 1))
    return _finalize(m, l, acc, q.dtype)


def _ring_flash(q, k, v, axis_name, causal, scale, n, my, perm):
    """Flash-core ring body (see ring_attention): fused-kernel local
    attention per arriving K/V shard + exact state merging."""
    from tpunet.ops.flash import (local_flash_attention_state,
                                  merge_attention_states)
    b, tq, h, d = q.shape

    def block_state(k_cur, v_cur, blk_causal: bool):
        return local_flash_attention_state(q, k_cur, v_cur,
                                           causal=blk_causal, scale=scale)

    def fold(state, k_cur, v_cur, step):
        if not causal:
            return merge_attention_states(
                state, block_state(k_cur, v_cur, False))
        src = (my - step) % n
        return jax.lax.cond(
            src < my,
            lambda args: merge_attention_states(
                state, block_state(args[0], args[1], False)),
            lambda args: jax.lax.cond(
                src == my,
                lambda a: merge_attention_states(
                    state, block_state(a[0], a[1], True)),
                lambda a: state,          # fully future: skip
                args),
            (k_cur, v_cur))

    def body(carry, step):
        state, k_cur, v_cur = carry
        state = fold(state, k_cur, v_cur, step)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (state, k_nxt, v_nxt), None

    # The merged-output accumulator stays float32 across all n folds
    # (merge_attention_states keeps the carry's dtype) — one cast at
    # the end, like the pure-JAX path's _finalize; a q.dtype carry
    # would re-round bf16 at every ring step.
    state = (jnp.zeros((b, tq, h, d), jnp.float32),
             jnp.full((b, h, tq), _NEG_INF, jnp.float32))
    k_last, v_last = k, v
    if n > 1:
        (state, k_last, v_last), _ = jax.lax.scan(
            body, (state, k, v), jnp.arange(n - 1))
    out, _ = fold(state, k_last, v_last, n - 1)
    return out.astype(q.dtype)


def _resolve_head_axis(mesh: Mesh, head_axis: Optional[str], heads: int,
                       local_divisor: int = 1) -> Optional[str]:
    """Head-dim mesh axis for the shard_map wrappers, or None to
    replicate: the axis must exist, be >1, and divide the head count
    (with the per-shard head count further divisible by
    ``local_divisor`` — Ulysses needs local heads to divide the seq
    axis)."""
    if not head_axis or head_axis not in mesh.shape:
        return None
    size = mesh.shape[head_axis]
    if size <= 1 or heads % size or (heads // size) % local_divisor:
        return None
    return head_axis


def _divisor_block(t: int, cap: int) -> int:
    """Largest divisor of ``t`` that is <= cap — honors explicitly tiny
    caps (used when the caller chose the block size deliberately; the
    flash kernel shares this)."""
    return next(b for b in range(min(cap, t), 0, -1) if t % b == 0)


def _auto_block(t: int, cap: int = 512) -> int:
    """Block size for a length-``t`` blockwise pass: the largest divisor
    of t that is <= ``cap``, bounding score memory to O(t x cap).
    Lengths whose only small divisors are degenerate (< 64, e.g. primes
    — a t-step scan of 1-wide blocks) fall back to one dense pass
    instead; that trades memory for not serializing the contraction."""
    if t <= cap:
        return t
    b = next(b for b in range(cap, 0, -1) if t % b == 0)
    return b if b >= 64 else t


def _local_full_attention(q, k, v, causal, scale, core: Optional[str],
                          block: Optional[int] = None,
                          segment_ids=None):
    """The locally-dense full-sequence core used inside Ulysses.

    ``core`` None resolves to the Pallas flash kernel on TPU (measured
    1.31x the blockwise scan, tpunet/ops/flash.py) and the blockwise
    scan elsewhere; "flash"/"blockwise" force a choice ("flash" off-TPU
    runs the kernel in interpret mode — test use only). ``block``
    overrides the kernel/scan block size (cfg.attention_block).
    ``segment_ids``: optional (q_seg, kv_seg) pair — both cores are
    segment-capable (packed x SP)."""
    if core is None:
        core = "flash" if jax.default_backend() == "tpu" else "blockwise"
    if core == "flash":
        from tpunet.ops.flash import local_flash_attention
        interpret = True if jax.default_backend() != "tpu" else None
        b = block or 512
        return local_flash_attention(q, k, v, causal=causal, scale=scale,
                                     block_q=b, block_k=b,
                                     interpret=interpret,
                                     segment_ids=segment_ids)
    if core == "blockwise":
        # ``block`` is a CAP clamped to a divisor of the local length.
        # An EXPLICIT cap is honored even below _auto_block's 64 floor
        # (the user chose it to bound memory); only auto-selection
        # applies the degenerate-length dense fallback.
        bs = (_divisor_block(q.shape[1], block) if block
              else _auto_block(q.shape[1]))
        return blockwise_attention(q, k, v, block_size=bs,
                                   causal=causal, scale=scale,
                                   segment_ids=segment_ids)
    raise ValueError(f"unknown attention core {core!r}")


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, *,
                      causal: bool = False,
                      scale: Optional[float] = None,
                      core: Optional[str] = None,
                      block: Optional[int] = None,
                      segment_ids=None) -> jax.Array:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style),
    shard_map body: inputs arrive seq-sharded [B, T/s, H, D]; one
    all-to-all (q/k/v stacked, so it is a single collective) re-shards
    heads instead ([B, T, H/s, D]), attention runs over the FULL
    sequence per head group (the flash kernel on TPU, the blockwise
    scan elsewhere — ``core``), and a second all-to-all restores seq
    sharding. Two collectives total per call — fewer than the ring's
    per-step hops when heads divide the axis — at the cost of holding
    full-T activations per head group (the scores themselves stay in
    VMEM / O(T x block)).

    ``segment_ids`` (packed x SP): a (q_seg, kv_seg) pair of
    seq-SHARDED [B, T/s] int arrays (equal for self-attention). The
    local core sees the full sequence per head group, so segment
    masking is exact under sharding: one [B, T/s] -> [B, T]
    ``all_gather`` (int32 metadata, negligible next to the qkv
    all-to-all) rebuilds the global ids the core masks with."""
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(
            f"ulysses_attention is self-attention only (q {q.shape}, "
            f"k {k.shape}, v {v.shape}); use ring_attention for "
            "cross-length attention")
    n = jax.lax.psum(1, axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"{q.shape[2]} heads not divisible by sequence axis {n}")
    seg = None
    if segment_ids is not None:
        ids = segment_ids[0]     # self-attention: q_seg is kv_seg
        if n > 1:
            ids = jax.lax.all_gather(ids, axis_name, axis=1, tiled=True)
        seg = (ids, ids)
    if n == 1:
        return _local_full_attention(q, k, v, causal, scale, core, block,
                                     segment_ids=seg)
    # [3, B, T/s, H, D] -> [3, B, T, H/s, D]: split heads, concat seq.
    qkv = jax.lax.all_to_all(jnp.stack([q, k, v]), axis_name,
                             split_axis=3, concat_axis=2, tiled=True)
    out = _local_full_attention(qkv[0], qkv[1], qkv[2], causal, scale,
                                core, block, segment_ids=seg)
    # [B, T, H/s, D] -> [B, T/s, H, D]: split seq, concat heads.
    return jax.lax.all_to_all(out, axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           mesh: Mesh, *,
                           seq_axis: str = "seq",
                           batch_axis: str = "data",
                           head_axis: Optional[str] = "model",
                           causal: bool = False,
                           scale: Optional[float] = None,
                           core: Optional[str] = None,
                           block: Optional[int] = None,
                           segment_ids=None) -> jax.Array:
    """shard_map wrapper for ``ulysses_attention`` (mirror of
    ``ring_self_attention``, including pass-through tensor-parallel
    head sharding — local heads must still divide the seq axis).
    ``segment_ids``: optional (q_seg, kv_seg) [B, T] pair (packed
    sequences) — sharded over ``seq_axis`` into the body, where the
    gather-and-mask happens."""
    h_ax = _resolve_head_axis(mesh, head_axis, q.shape[2],
                              local_divisor=mesh.shape[seq_axis])
    spec = P(batch_axis, seq_axis, h_ax, None)
    if segment_ids is None:
        fn = shard_map(
            functools.partial(ulysses_attention, axis_name=seq_axis,
                              causal=causal, scale=scale, core=core,
                              block=block),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return fn(q, k, v)
    s_spec = P(batch_axis, seq_axis)

    def body(q, k, v, q_seg, kv_seg):
        return ulysses_attention(q, k, v, axis_name=seq_axis,
                                 causal=causal, scale=scale, core=core,
                                 block=block,
                                 segment_ids=(q_seg, kv_seg))

    fn = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec, s_spec, s_spec),
        out_specs=spec, check_vma=False)
    return fn(q, k, v, *segment_ids)


def ring_self_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        mesh: Mesh, *,
                        seq_axis: str = "seq",
                        batch_axis: str = "data",
                        head_axis: Optional[str] = "model",
                        causal: bool = False,
                        scale: Optional[float] = None,
                        core: Optional[str] = None) -> jax.Array:
    """shard_map wrapper: global BTHD arrays in, ring attention inside.

    Batch dim sharded over ``batch_axis``, seq dim over ``seq_axis``.
    When ``head_axis`` names a mesh axis that divides the head count,
    the head dim stays sharded over it too (attention is elementwise in
    heads), so tensor-parallel activations flow through without the
    all-gather an unmentioned axis would force.
    """
    h_ax = _resolve_head_axis(mesh, head_axis, q.shape[2])
    spec = P(batch_axis, seq_axis, h_ax, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=seq_axis,
                          causal=causal, scale=scale, core=core),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
