"""Pallas TPU kernel: 3x3 depthwise convolution (NHWC, torch padding=1).

This is the VPU-bound hot op of MobileNetV2 (the reference consumes it
through cuDNN inside torchvision's ``mobilenet_v2``; here it is a
first-class kernel). Design:

- Input is pre-padded by one pixel (XLA fuses the pad), so the kernel
  body is 9 shifted multiply-adds over a VMEM-resident image — pure VPU
  work with no bounds logic. Channels ride the lane dimension (NHWC).
- Grid is (batch, row_stripes); each program computes one stripe of
  output rows, slicing its input rows (+2-row halo) from the resident
  padded image with ``pl.ds``. Whole-image programs would overflow the
  16 MB scoped-vmem stack: the 9 float32 tap temporaries at a 112x112
  layer alone are ~14 MB (stride 2's slice/reshape trick reads ~4x
  more, so the stripe height budget is stride-aware — ``_pick_rows``).
- Stride 2 is expressed as slice + reshape + take (no strided vector
  slices, which Mosaic handles poorly).
- Accumulation in float32 regardless of compute dtype; output cast back.
- ``jax.custom_vjp``: forward runs the Pallas kernel; backward runs
  IO-aware Pallas kernels with the same stripe/halo VMEM design
  (``_bwd_kernel``): dx is a stride-1 correlation with the flipped taps
  over the (for stride 2, zero-dilated IN VMEM) output gradient, and dw
  is reduced per image in float32 inside the same kernel — the
  transposed-conv lowering XLA emits for the reference (input-dilated
  gradient image, window-gathered weight reduction) never materializes
  its dilated/padded temporaries in HBM. Off-TPU (and for any caller
  that asks via ``interpret=None`` on a non-TPU backend) the backward
  stays the transpose of the XLA reference via ``jax.vjp``, exactly as
  before. Remaining known HBM amplification on the Pallas path: the
  host-side ``jnp.pad`` of x/g feeding the kernels (~(1 + 2/H)^2 of one
  activation each) — the kernel body itself reads each padded image
  once and writes dx/per-image dw partials once.

Numerically identical (up to dtype rounding) to
``depthwise_conv3x3_reference`` — property-tested (forward AND both
backward kernels, stride 1 and 2, odd sizes, off-lane-multiple
channels) in interpret mode on CPU (tests/test_ops.py).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def depthwise_conv3x3_reference(x: jax.Array, w: jax.Array,
                                stride: int = 1) -> jax.Array:
    """XLA reference: x [N,H,W,C], w [3,3,C] -> [N,Ho,Wo,C], padding=1."""
    return jax.lax.conv_general_dilated(
        x, w[:, :, None, :],
        window_strides=(stride, stride),
        padding=((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=x.shape[-1],
    )


def _tap(x, dy: int, dx: int, ho: int, wo: int, stride: int):
    """x [Hp, Wp, C] -> the (ho, wo, C) input samples for kernel tap
    (dy, dx): rows dy, dy+stride, ...; cols dx, dx+stride, ..."""
    if stride == 1:
        return x[dy:dy + ho, dx:dx + wo]
    v = x[dy:dy + stride * ho, dx:dx + stride * wo]
    c = v.shape[-1]
    v = v.reshape(ho, stride, stride * wo, c)[:, 0]
    return v.reshape(ho, wo, stride, c)[:, :, 0]


def _kernel(x_ref, w_ref, o_ref, *, wo: int, stride: int, rows: int):
    """Compute one ``rows``-high output stripe per grid step. The 9
    float32 tap temporaries are stripe-sized, not image-sized —
    computing the whole image in one program overflows the 16 MB
    scoped-vmem stack at the 224px workload's 112x112 layers (9 taps x
    112x112xC x 4B; an in-kernel loop doesn't help because Mosaic's
    stack allocator sums the iterations' temporaries)."""
    w = w_ref[:]                       # (3, 3, C)
    c = x_ref.shape[-1]
    bh = stride * rows + 2             # input rows feeding one stripe
                                       # (max tap offset dy=2 + stride*rows)
    r0 = pl.program_id(1) * rows
    xs = x_ref[0, pl.ds(r0 * stride, bh)]   # (bh, Wp, C) stripe
    acc = jnp.zeros((rows, wo, c), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            t = _tap(xs, dy, dx, rows, wo, stride).astype(jnp.float32)
            acc = acc + t * w[dy, dx].astype(jnp.float32)
    o_ref[0] = acc.astype(o_ref.dtype)


def _pick_rows(ho: int, wo: int, c: int, stride: int,
               bufs: int = 12) -> int:
    """Largest divisor of ho whose stripe temporaries (~``bufs`` f32
    buffers: 9 taps + accumulator + slack; stride 2's slice/reshape
    trick reads ~stride^2 x more elements per tap) stay within a ~4 MB
    budget. The backward kernel passes a larger ``bufs`` (its stripes
    carry dx taps AND dw reduction temporaries)."""
    budget = 4 * 1024 * 1024
    for rows in range(ho, 0, -1):
        if ho % rows == 0 and rows * wo * c * 4 * bufs * stride**2 <= budget:
            return rows
    return 1


def _pallas_forward(x: jax.Array, w: jax.Array, stride: int,
                    interpret: bool) -> jax.Array:
    n, h, w_in, c = x.shape
    ho = (h - 1) // stride + 1
    wo = (w_in - 1) // stride + 1
    # Pad so every tap's full slice (stride*ho rows from offset <=2, for
    # the stride>1 reshape trick) stays in bounds; the extra zero rows
    # land only in discarded reshape positions. The last stripe's
    # dynamic_slice ends exactly at hp = stride*ho + 2, in bounds.
    pad_b = stride * ho + 1 - h
    pad_r = stride * wo + 1 - w_in
    xp = jnp.pad(x, ((0, 0), (1, pad_b), (1, pad_r), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]

    rows = _pick_rows(ho, wo, c, stride)
    kern = functools.partial(_kernel, wo=wo, stride=stride, rows=rows)
    return pl.pallas_call(
        kern,
        grid=(n, ho // rows),
        in_specs=[
            # Whole padded image per program (same block for every
            # stripe index — Pallas keeps it resident); the kernel
            # slices its stripe (+halo) out with pl.ds.
            pl.BlockSpec((1, hp, wp, c), lambda i, j: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, c), lambda i, j: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, rows, wo, c), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, ho, wo, c), x.dtype),
        interpret=interpret,
    )(xp, w)


# ---------------------------------------------------------------------------
# SPMD partitioning: a pallas_call is opaque to GSPMD, so without help the
# partitioner would all-gather the batch onto every device. The op is
# trivially parallel over batch and channels (the kernel grids over N and
# is elementwise in C), so we register exactly that rule and lower to a
# per-shard pallas call. H/W stay replicated.
# ---------------------------------------------------------------------------

from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

from tpunet.compat import def_partition_compat


def _shard_specs(arg_shapes):
    def spec_of(s):
        sh = s.sharding
        return sh.spec if isinstance(sh, NamedSharding) else P()
    xs = spec_of(arg_shapes[0])
    xp = list(xs) + [None] * (4 - len(xs))
    return P(xp[0], None, None, xp[3])


def _infer(stride, interpret, mesh, arg_shapes, result_shape):
    spec = _shard_specs(arg_shapes)
    return NamedSharding(mesh, spec)


def _partition(stride, interpret, mesh, arg_shapes, result_shape):
    spec = _shard_specs(arg_shapes)
    arg_shardings = (NamedSharding(mesh, spec),
                     NamedSharding(mesh, P(None, None, spec[3])))
    result_sharding = NamedSharding(mesh, spec)

    def lower_fn(x, w):
        return _pallas_forward(x, w, stride, interpret)

    return mesh, lower_fn, result_sharding, arg_shardings


_partitioned = custom_partitioning(_pallas_forward, static_argnums=(2, 3))
def_partition_compat(
    _partitioned,
    partition=_partition,
    infer_sharding_from_operands=_infer,
    sharding_rule="n h w c, kh kw c -> n ho wo c",
    need_replication_factors=("h", "w", "kh", "kw", "ho", "wo"),
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def depthwise_conv3x3(x: jax.Array, w: jax.Array, stride: int = 1,
                      interpret: Optional[bool] = None) -> jax.Array:
    """3x3 depthwise conv, NHWC, padding=1 (torch semantics).

    ``x`` [N,H,W,C], ``w`` [3,3,C]. Forward runs the Pallas kernel on
    TPU; off-TPU the default is the XLA reference (the Pallas
    interpreter is far too slow for a hot path — pass ``interpret=True``
    explicitly to exercise the kernel in tests). Under SPMD jit it
    partitions over batch/channels via the registered rule. Gradients
    are exactly the XLA reference's.
    """
    if interpret is None:
        if jax.default_backend() != "tpu":
            return depthwise_conv3x3_reference(x, w, stride)
        interpret = False
    # Named for byte/phase attribution (tpunet/obs/hlo_bytes.py): the
    # kernel lowers to a custom call, not a convolution opcode, so the
    # scope is what keeps it in the conv_fwd bucket.
    with jax.named_scope("tpunet_depthwise_fwd"):
        return _partitioned(x, w, stride, interpret)


# ---------------------------------------------------------------------------
# IO-aware backward kernels. The math: with xp = pad(x, 1) and
# out[i,j] = sum_{dy,dx} xp[s*i+dy, s*j+dx] * w[dy,dx],
#
#   dw[dy,dx,c] = sum_{n,i,j} xp[n, s*i+dy, s*j+dx, c] * g[n,i,j,c]
#   dx[p,q,c]   = sum_{dy',dx'} G[p+dy', q+dx', c] * w[2-dy', 2-dx', c]
#
# where G is the gradient image zero-DILATED by the stride and shifted
# by the forward padding: G[a,b] = g[(a-1)/s, (b-1)/s] when both are
# whole in-range numbers, else 0. I.e. dx is a plain stride-1
# correlation with the flipped taps over the dilated gradient — the
# dilation is built IN VMEM per stripe (zero-interleaving via
# stack+reshape, the same no-strided-vector-ops discipline as the
# forward's stride trick), so the 4x-elements dilated image the XLA
# transposed conv materializes never exists in HBM. dw partials are
# reduced per image in float32 inside the same kernel and summed over
# batch OUTSIDE the pallas_call: the (N, 3, 3, C) partial is tiny, and
# summing it in XLA keeps the op trivially batch-partitionable (the
# data-parallel gradient all-reduce stays a plain psum XLA inserts from
# shardings, instead of a collective the kernel would have to own).
# ---------------------------------------------------------------------------


def _bwd_kernel(xp_ref, gp_ref, w_ref, dx_ref, dwp_ref, *,
                wo: int, stride: int, rows: int):
    """One output-row stripe per grid step, same stripe/halo design as
    the forward kernel: the stripe's dw partial accumulates into the
    per-image (3, 3, C) block across grid steps (j == 0 initializes),
    and the stride*rows dx rows this stripe's gradient reaches are
    computed once. All tap temporaries are stripe-sized f32 in VMEM."""
    w = w_ref[:]                        # (3, 3, C)
    c = xp_ref.shape[-1]
    j = pl.program_id(1)
    r0 = j * rows                       # first output (gradient) row

    # -- dw partial: sum over stripe of xp[s*i+dy, s*j+dx] * g[i, j] --
    bh = stride * rows + 2
    xs = xp_ref[0, pl.ds(r0 * stride, bh)]            # (bh, Wp, C)
    if stride == 1:
        # gp is pad(g, 1): the unpadded gradient is its interior.
        gs_dw = gp_ref[0, pl.ds(r0 + 1, rows)][:, 1:1 + wo]
    else:
        # gp is pad(g, (0,1),(0,1)): rows/cols [0, rows)/[0, wo).
        gs_dw = gp_ref[0, pl.ds(r0, rows)][:, :wo]
    gf = gs_dw.astype(jnp.float32)
    parts = []
    for dy in range(3):
        for dx in range(3):
            t = _tap(xs, dy, dx, rows, wo, stride).astype(jnp.float32)
            parts.append(jnp.sum(t * gf, axis=(0, 1)))  # (C,)
    part = jnp.stack(parts).reshape(3, 3, c)

    @pl.when(j == 0)
    def _init():
        dwp_ref[0] = part

    @pl.when(j > 0)
    def _accum():
        dwp_ref[0] = dwp_ref[0] + part

    # -- dx: stride-1 flipped-tap correlation over the dilated g ------
    rows_in = stride * rows
    if stride == 1:
        # No dilation: G rows [p0, p0+rows+2) are gp rows directly.
        G = gp_ref[0, pl.ds(r0, rows_in + 2)]         # (rows+2, W+2, C)
    else:
        # Zero-dilate in VMEM: G[t] = g[r0 + (t-1)/2] for odd t else 0
        # (p0 = stride*r0 is even, so stripe-local parity == global).
        gs = gp_ref[0, pl.ds(r0, rows + 1)]           # (rows+1, wo+1, C)
        z = jnp.zeros_like(gs)
        G = jnp.stack([z, gs], axis=2).reshape(rows + 1, -1, c)
        G = jnp.stack([jnp.zeros_like(G), G], axis=1).reshape(
            rows_in + 2, -1, c)
    wout = stride * wo
    acc = jnp.zeros((rows_in, wout, c), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            t = G[dy:dy + rows_in, dx:dx + wout].astype(jnp.float32)
            acc = acc + t * w[2 - dy, 2 - dx].astype(jnp.float32)
    dx_ref[0] = acc.astype(dx_ref.dtype)


def _pallas_backward(x: jax.Array, w: jax.Array, g: jax.Array,
                     stride: int, interpret: bool):
    """(x, w, g) -> (dx, per-image dw partials [N, 3, 3, C] f32)."""
    n, h, w_in, c = x.shape
    ho = (h - 1) // stride + 1
    wo = (w_in - 1) // stride + 1
    pad_b = stride * ho + 1 - h
    pad_r = stride * wo + 1 - w_in
    xp = jnp.pad(x, ((0, 0), (1, pad_b), (1, pad_r), (0, 0)))
    if stride == 1:
        gp = jnp.pad(g, ((0, 0), (1, 1), (1, 1), (0, 0)))
    else:
        # Dilation supplies the leading zero row/col; one trailing
        # zero row/col keeps the last stripe's slices in bounds.
        gp = jnp.pad(g, ((0, 0), (0, 1), (0, 1), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    gh, gw = gp.shape[1], gp.shape[2]

    rows = _pick_rows(ho, wo, c, stride, bufs=24)
    kern = functools.partial(_bwd_kernel, wo=wo, stride=stride, rows=rows)
    hout, wout = stride * ho, stride * wo
    dx_full, dwp = pl.pallas_call(
        kern,
        grid=(n, ho // rows),
        in_specs=[
            pl.BlockSpec((1, hp, wp, c), lambda i, j: (i, 0, 0, 0)),
            pl.BlockSpec((1, gh, gw, c), lambda i, j: (i, 0, 0, 0)),
            pl.BlockSpec((3, 3, c), lambda i, j: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, stride * rows, wout, c),
                         lambda i, j: (i, j, 0, 0)),
            # Constant index map over j: the block stays resident and
            # accumulates across the image's stripes (standard TPU
            # revisiting pattern; the grid is sequential per image).
            pl.BlockSpec((1, 3, 3, c), lambda i, j: (i, 0, 0, 0)),
        ],
        out_shape=[
            # dx covers [0, stride*ho) rows; rows >= h (at most one
            # phantom row/col for odd sizes) are sliced off below.
            jax.ShapeDtypeStruct((n, hout, wout, c), x.dtype),
            jax.ShapeDtypeStruct((n, 3, 3, c), jnp.float32),
        ],
        interpret=interpret,
    )(xp, gp, w)
    return dx_full[:, :h, :w_in], dwp


def _bwd_shard_specs(arg_shapes):
    def spec_of(s):
        sh = s.sharding
        return sh.spec if isinstance(sh, NamedSharding) else P()
    xs = spec_of(arg_shapes[0])
    xp = list(xs) + [None] * (4 - len(xs))
    return P(xp[0], None, None, xp[3])


def _bwd_infer(stride, interpret, mesh, arg_shapes, result_shape):
    spec = _bwd_shard_specs(arg_shapes)
    return (NamedSharding(mesh, spec),
            NamedSharding(mesh, P(spec[0], None, None, spec[3])))


def _bwd_partition(stride, interpret, mesh, arg_shapes, result_shape):
    spec = _bwd_shard_specs(arg_shapes)
    arg_shardings = (NamedSharding(mesh, spec),
                     NamedSharding(mesh, P(None, None, spec[3])),
                     NamedSharding(mesh, spec))
    result_shardings = (NamedSharding(mesh, spec),
                        NamedSharding(mesh, P(spec[0], None, None,
                                              spec[3])))

    def lower_fn(x, w, g):
        return _pallas_backward(x, w, g, stride, interpret)

    return mesh, lower_fn, result_shardings, arg_shardings


_partitioned_bwd = custom_partitioning(_pallas_backward,
                                       static_argnums=(3, 4))
def_partition_compat(
    _partitioned_bwd,
    partition=_bwd_partition,
    infer_sharding_from_operands=_bwd_infer,
    sharding_rule="n h w c, kh kw c, n go wog c -> n h w c, n kh kw c",
    need_replication_factors=("h", "w", "kh", "kw", "go", "wog"),
)


def _reference_bwd(x, w, g, stride):
    _, vjp = jax.vjp(lambda xx, ww: depthwise_conv3x3_reference(
        xx, ww, stride), x, w)
    return vjp(g)


def _fwd(x, w, stride, interpret):
    # With nondiff_argnums, f_fwd takes the primal's full signature;
    # f_bwd gets the nondiff args first.
    return depthwise_conv3x3(x, w, stride, interpret), (x, w)


def _bwd(stride, interpret, res, g):
    # The whole body sits under the tpunet_depthwise_bwd scope: a
    # custom_vjp backward carries no ``transpose(`` marker, so the
    # scope is what keeps the kernel's custom call (and the reference
    # fallback's transposed conv, and the dw batch-sum) attributed to
    # the backward phase / conv_bwd bucket (tpunet/obs/hlo_bytes.py)
    # instead of leaking into fwd — the same contract as the fused-IR
    # pair's backward.
    with jax.named_scope("tpunet_depthwise_bwd"):
        x, w = res
        # Mirror the primal's dispatch: interpret=None means "Pallas on
        # TPU, XLA reference elsewhere" (the interpreter is too slow
        # for a hot path); interpret=True exercises the kernels in
        # tests. TPUNET_DEPTHWISE_REF_BWD=1 is the escape hatch back to
        # the reference-transpose backward (e.g. a Mosaic regression on
        # a new toolchain) without giving up the Pallas forward.
        if interpret is None:
            if jax.default_backend() != "tpu":
                return _reference_bwd(x, w, g, stride)
            interpret = False
        if os.environ.get("TPUNET_DEPTHWISE_REF_BWD"):
            return _reference_bwd(x, w, g, stride)
        dx, dwp = _partitioned_bwd(x, w, g, stride, interpret)
        return dx, jnp.sum(dwp, axis=0).astype(w.dtype)


depthwise_conv3x3.defvjp(_fwd, _bwd)
